"""Pallas-kernel numerics parity on REAL TPU at bf16 tolerances.

Reference analog: tests/unit/test_cuda_forward.py:333 and
test_cuda_backward.py:335 — fused-kernel outputs and gradients vs a
reference implementation at half-precision tolerances on real hardware.
The CPU sim mesh can only exercise these kernels in interpret mode, which
does not cover lane masking, MXU accumulation order, or real bf16
rounding; this lane does.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                               flash_attention_pallas,
                                               mha_reference)
from deepspeed_tpu.ops.normalize import fused_layer_norm
from deepspeed_tpu.runtime.quantize import quantize_dequantize

# bf16 has ~3 decimal digits; sums over S=1024 add noise
BF16_RTOL = 2e-2
BF16_ATOL = 2e-2


def _qkv(b, h, s, d, dtype, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d), dtype) for k in ks)


@pytest.mark.parametrize("s,causal", [(256, False), (1024, True),
                                      (1536, True)])
def test_flash_forward_parity_bf16(s, causal):
    q, k, v = _qkv(2, 4, s, 64, jnp.bfloat16)
    out = flash_attention_pallas(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=BF16_RTOL, atol=BF16_ATOL)


def test_flash_backward_parity_bf16():
    q, k, v = _qkv(2, 4, 512, 64, jnp.bfloat16, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       impl="pallas").astype(jnp.float32)
                       ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v,
                                     causal=True).astype(jnp.float32) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=5e-2)


def test_flash_pallas_non_lane_multiple_lengths():
    """Lengths the block-fit logic ACCEPTS onto the Pallas path without
    being 128-multiples (the advisor-r3 gap): a q length of 328 tiles as
    one 41-sublane block (8-aligned, not lane-aligned) against k=1024,
    and S=1152 self-attention tiles as 384x384 (non-power-of-2 blocks).
    Interpret mode cannot validate these tilings under Mosaic."""
    q, _, _ = _qkv(1, 2, 328, 64, jnp.bfloat16, seed=8)
    _, k, v = _qkv(1, 2, 1024, 64, jnp.bfloat16, seed=9)
    out = flash_attention(q, k, v, causal=False, impl="pallas")
    ref = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=BF16_RTOL, atol=BF16_ATOL)

    q, k, v = _qkv(1, 2, 1152, 64, jnp.bfloat16, seed=10)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       impl="pallas").astype(jnp.float32)
                       ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v,
                                     causal=True).astype(jnp.float32) ** 2)

    out = flash_attention(q, k, v, causal=True, impl="pallas")
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=BF16_RTOL, atol=BF16_ATOL)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=5e-2)


def test_flash_dispatcher_unaligned_length_falls_back():
    """Non-lane-aligned lengths must take the XLA path (the advisor-r2
    alignment gate) and still be numerically right on TPU."""
    q, k, v = _qkv(1, 2, 1000, 64, jnp.bfloat16, seed=2)
    out = flash_attention(q, k, v, causal=True)  # auto -> XLA fallback
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=BF16_RTOL, atol=BF16_ATOL)


def test_fused_layer_norm_fwd_bwd_parity_bf16(monkeypatch):
    """Compiled-Mosaic parity of the PALLAS LN kernels (they are no
    longer the dispatch default — XLA LN measured faster — so this test
    must select them explicitly or it compares XLA against XLA)."""
    monkeypatch.setattr("deepspeed_tpu.ops.dispatch._ln_impl", "pallas")
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 1024, 768),
                          jnp.bfloat16)
    w = jnp.ones((768,), jnp.float32) * 1.1
    b = jnp.zeros((768,), jnp.float32) + 0.1

    def ref_ln(x, w, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        return (((xf - mu) / jnp.sqrt(var + 1e-5)) * w + b).astype(x.dtype)

    out = fused_layer_norm(x, w, b, 1e-5)
    ref = ref_ln(x, w, b)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=BF16_RTOL, atol=BF16_ATOL)

    def loss(f):
        def inner(x, w, b):
            return jnp.sum(f(x, w, b).astype(jnp.float32) ** 2)
        return inner

    gf = jax.grad(loss(lambda x, w, b: fused_layer_norm(x, w, b, 1e-5)),
                  argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss(ref_ln), argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            rtol=5e-2, atol=5e-1)  # wide: bf16 sums over 8*1024 rows


def test_group_quantizer_roundtrip_tpu():
    x = jax.random.normal(jax.random.PRNGKey(4), (4096, 256), jnp.float32)
    dq = quantize_dequantize(x, bits=8, groups=64)
    err = float(jnp.abs(dq - x).max() / jnp.abs(x).max())
    assert err < 0.02, err


def test_engine_smoke_one_step_tpu():
    """One real engine train step on the chip — the package boundary works
    end-to-end on TPU, not just through the CPU sim mesh."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    ds.reset_mesh_context()
    cfg = GPT2Config(vocab_size=512, n_positions=128, hidden_size=128,
                     num_layers=2, num_heads=2, bf16=True)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10 ** 9})
    ids = np.random.RandomState(0).randint(0, 512, (2, 128)).astype(np.int32)
    loss = engine.forward(ids)
    engine.backward(loss)
    engine.step()
    assert np.isfinite(float(loss))
    ds.reset_mesh_context()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bsh_layout_parity_bf16_tpu(causal):
    """The transpose-free [B, S, heads, d] layout — now the training
    layer's default attention path — compiled by REAL Mosaic (interpret
    mode cannot validate the (1, rows, 1, d) block tiling)."""
    from deepspeed_tpu.ops.flash_attention import flash_attention_bsh

    q, k, v = _qkv(2, 4, 1024, 64, jnp.bfloat16, seed=5)

    def to_bsh(t):
        return t.transpose(0, 2, 1, 3)

    out = flash_attention_bsh(to_bsh(q), to_bsh(k), to_bsh(v), causal=causal,
                              impl="pallas")
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out.transpose(0, 2, 1, 3), np.float32),
        np.asarray(ref, np.float32), rtol=BF16_RTOL, atol=BF16_ATOL)

    def loss_bsh(q_, k_, v_):
        o = flash_attention_bsh(to_bsh(q_), to_bsh(k_), to_bsh(v_),
                                causal=causal, impl="pallas")
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(mha_reference(q_, k_, v_,
                                     causal=causal).astype(jnp.float32) ** 2)

    gb = jax.grad(loss_bsh, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gb, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_flash_parity_bf16_tpu(causal):
    """Compiled-Mosaic parity of the block-sparse flash kernel (fwd+bwd)
    vs the dense-masked XLA reference at a lane-aligned block (128)."""
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        block_sparse_flash_attention, layout_gather)

    h, block, s, d = 4, 128, 1024, 64
    cfg = FixedSparsityConfig(num_heads=h, block=block, num_local_blocks=2,
                              num_global_blocks=1)
    layout = cfg.make_layout(s)
    fidx, fvalid = layout_gather(layout)
    tidx, tvalid = layout_gather(layout, transpose=True)
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (2, h, s, d), jnp.bfloat16) for kk in ks)

    mask = np.kron(layout, np.ones((block, block)))
    bias = jnp.asarray(np.where(mask > 0, 0.0, -1e30)
                       .astype(np.float32))[None]

    def loss_sparse(q, k, v):
        o = block_sparse_flash_attention(q, k, v, fidx, fvalid, tidx, tvalid,
                                         block, causal=causal)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    def loss_ref(q, k, v):
        # fp32 reference: at degenerate causal rows (q-position 0 of a
        # block attending one key) the true dq is EXACTLY 0 via
        # dp - delta cancellation; a bf16 reference on the MXU leaves
        # ~0.1-magnitude cancellation noise there while the kernel's
        # in-kernel fp32 math gives the exact 0 (measured round 4 —
        # 39/524288 "mismatches" were the reference's noise, not kernel
        # error; CPU interpret hid it by emulating bf16 in fp32)
        o = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), causal=causal, bias=bias)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (_, out), gs = jax.jit(jax.value_and_grad(
        loss_sparse, argnums=(0, 1, 2), has_aux=True))(q, k, v)
    (_, ref), gr = jax.jit(jax.value_and_grad(
        loss_ref, argnums=(0, 1, 2), has_aux=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=2e-2, rtol=2e-2)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=5e-2, rtol=5e-2)


@pytest.mark.parametrize("pbits", [32, 8])
def test_flash_inkernel_dropout_tpu(pbits, monkeypatch):
    """In-kernel probability dropout on the compiled Mosaic path:
    determinism per seed, drop-rate statistics via a ones-valued v, exact
    rate-0 equality, and a directional finite-difference check of the
    custom VJP (valid because a fixed seed makes the function
    deterministic).  Parametrized over the PRNG width: 8-bit mode packs
    four mask bytes per random word (4x cheaper generation) and must pass
    the same statistics/FD bars as the 32-bit default."""
    import importlib
    from deepspeed_tpu.ops.flash_attention import flash_attention
    # monkeypatch by module OBJECT: the string path resolves through
    # deepspeed_tpu.ops.__init__, where the re-exported flash_attention
    # FUNCTION shadows the submodule attribute of the same name
    fa_mod = importlib.import_module("deepspeed_tpu.ops.flash_attention")
    monkeypatch.setattr(fa_mod, "_dropout_bits", pbits)
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    shape = (2, 4, 1024, 64)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) for kk in ks[:3])
    ones_v = jnp.ones_like(v)
    rate = 0.2

    def attn(q_, k_, v_, seed):
        return flash_attention(q_, k_, v_, causal=True, impl="pallas",
                               dropout_rate=rate, dropout_seed=seed)

    o1 = jax.jit(attn)(q, k, ones_v, 11)
    o2 = jax.jit(attn)(q, k, ones_v, 11)
    o3 = jax.jit(attn)(q, k, ones_v, 12)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert float(jnp.max(jnp.abs(o1 - o3))) > 0.0
    # each out row = sum of dropped-normalized P against ones: mean 1
    assert abs(float(jnp.mean(o1)) - 1.0) < 0.05

    o0 = flash_attention(q, k, v, causal=True, impl="pallas",
                         dropout_rate=0.0)
    onodrop = flash_attention(q, k, v, causal=True, impl="pallas")
    np.testing.assert_array_equal(np.asarray(o0), np.asarray(onodrop))

    # directional finite differences through the full custom VJP
    def loss(q_, k_, v_):
        return jnp.sum(attn(q_, k_, v_, 11).astype(jnp.float32) ** 2)

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    rng = np.random.RandomState(0)
    # eps must be large enough that the fp32 loss difference (magnitude
    # ~1e4, so ~1e-1 evaluation noise after cancellation) doesn't dominate
    # the quotient: at 1e-2 even an exact-gradient XLA reference fails its
    # own finite-difference check here.
    eps = 1e-1
    for i, (x, g) in enumerate(zip((q, k, v), grads)):
        u = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        args_p = [q, k, v]; args_m = [q, k, v]
        args_p[i] = x + eps * u
        args_m[i] = x - eps * u
        fd = (float(loss(*args_p)) - float(loss(*args_m))) / (2 * eps)
        an = float(jnp.sum(g * u))
        assert abs(fd - an) / (abs(fd) + abs(an) + 1e-6) < 5e-2, \
            (i, fd, an)


def test_fused_dequant_matmul_parity_tpu():
    """Compiled-Mosaic parity of the fused int8 dequant-matmul at the
    decode shapes (M=8 GEMV-ish) and a prefill shape."""
    from deepspeed_tpu.ops.quant import (QuantizedWeight,
                                         fused_dequant_matmul, dequant)
    rng = np.random.RandomState(2)
    for (m, k, n, groups) in [(8, 768, 2304, 8), (256, 768, 3072, 8)]:
        x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32),
                        jnp.bfloat16)
        qw = jnp.asarray(rng.randint(-127, 128, (k, n)).astype(np.int8))
        scale = jnp.asarray(
            np.abs(rng.standard_normal((groups, 1))).astype(np.float32))
        w = QuantizedWeight(qw, scale)
        out = jax.jit(lambda a: fused_dequant_matmul(a, w))(x)
        ref = x.astype(jnp.float32) @ dequant(w, jnp.float32)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=2e-2, atol=2.0)


def test_flash_dropout_mask_reuse_tpu(monkeypatch):
    """Mask-reuse mode (store bit-packed keep mask in fwd, read it in
    both bwd kernels) must be BIT-IDENTICAL to the regen default: the
    stored mask IS the regenerated mask, so outputs and grads cannot
    differ.  Also pins that reuse engages (residual mask present) rather
    than silently falling back to regen."""
    import importlib
    fa_mod = importlib.import_module("deepspeed_tpu.ops.flash_attention")
    from deepspeed_tpu.ops.flash_attention import flash_attention
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    shape = (2, 4, 1024, 64)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) for kk in ks)
    rate = 0.2

    def loss(q_, k_, v_):
        o = flash_attention(q_, k_, v_, causal=True, impl="pallas",
                            dropout_rate=rate, dropout_seed=11)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (_, o_regen), g_regen = jax.jit(jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)

    monkeypatch.setattr(fa_mod, "_dropout_reuse", True)
    # reuse path engages: the fwd residuals carry a packed mask
    _, res = fa_mod._flash_fwd(q, k, v, jnp.array([11], jnp.int32), True,
                               None, fa_mod.DEFAULT_BLOCK_Q,
                               fa_mod.DEFAULT_BLOCK_K, "bhsd", rate)
    assert res[-1] is not None and res[-1].dtype == jnp.uint32
    assert res[-1].shape == (2, 4, 1024 // 32, 1024)

    (_, o_reuse), g_reuse = jax.jit(jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)
    np.testing.assert_array_equal(np.asarray(o_regen), np.asarray(o_reuse))
    for a, b in zip(g_regen, g_reuse):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
