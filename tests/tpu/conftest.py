"""Real-TPU kernel-parity lane (reference analog: test_cuda_forward.py:333 /
test_cuda_backward.py:335 run fused kernels against reference numerics on
real hardware at fp16/fp32 tolerances).

Unlike tests/unit (which forces the 8-device CPU sim mesh), this lane runs
on the DEFAULT backend and skips itself entirely when that backend is not a
TPU.  Run it manually on the chip:

    python -m pytest tests/tpu -q

CAUTION (this harness): the tunnel admits ONE claim — never run this lane
concurrently with bench.py or any profiler.
"""

import os

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))


def pytest_collection_modifyitems(config, items):
    """Skip THIS DIRECTORY's tests off-TPU.  The hook receives every item
    in the session (conftest hooks are not directory-scoped), so filter
    by path — otherwise a `pytest tests/` run would skip the whole
    suite."""
    tpu_items = [i for i in items
                 if str(getattr(i, "fspath", "")).startswith(_HERE + os.sep)]
    if not tpu_items:
        return
    try:
        import jax

        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001 — no backend at all
        backend = f"unavailable ({e})"
    if backend not in ("tpu", "axon"):
        skip = pytest.mark.skip(
            reason=f"TPU kernel-parity lane needs a real TPU backend "
                   f"(default backend: {backend})")
        for item in tpu_items:
            item.add_marker(skip)
