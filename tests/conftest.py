"""Test harness: simulate an 8-device mesh on CPU so every collective path is
testable without TPU hardware (improves on the reference, which has no fake
backend — SURVEY.md §4)."""

import os
import sys

# A dedicated `pytest tests/tpu ...` invocation must run on the REAL
# backend — this conftest is the tpu lane's parent, so the CPU forcing
# below would otherwise make tests/tpu/conftest.py see backend "cpu" and
# skip the whole real-hardware lane (it did, silently, until round 3).
# Mixed runs (`pytest tests/`) still force CPU and the tpu dir skips
# itself, as documented there.  Only POSITIONAL args count: option
# values like `--ignore=tests/tpu` or `--deselect tests/tpu/...` must
# not disable the CPU sim for a unit-suite run.
_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_TPU_DIR = os.path.join(_TESTS_DIR, "tpu")

# pytest flags that take NO value — an arg following one of these is a
# positional.  An arg following any OTHER flag (e.g. --ignore, --deselect,
# -k, -n, --durations) is treated as that flag's value and skipped; for
# an unknown no-value flag this errs toward NOT detecting the tpu lane,
# i.e. toward the CPU sim (the tpu dir then skips itself visibly) rather
# than toward running the unit suite on a real backend.
_NOVALUE_FLAGS = {"-q", "-v", "-vv", "-vvv", "-s", "-x", "-l", "-rs",
                  "-ra", "-rA", "-rf", "-rx", "--collect-only", "--co",
                  "--no-header", "--forked", "--exitfirst", "--lf",
                  "--ff", "--sw", "--last-failed", "--failed-first"}


def _takes_no_value(flag):
    if flag in _NOVALUE_FLAGS or "=" in flag:
        return True
    # combined short flags (-xvs, -qx, ...): no value iff every letter is
    # itself a no-value short flag
    if len(flag) > 2 and flag[1] != "-" and flag[1:].isalpha():
        return all("-" + c in _NOVALUE_FLAGS for c in flag[1:])
    return False


# Flags KNOWN to take a value whose content is not a collection target —
# their values are excluded from the veto scan below (e.g. `-k flash`
# from inside tests/ must not resolve to tests/flash and veto the lane).
_VALUE_FLAGS = {"-k", "-m", "-n", "-p", "-o", "-c", "-W", "--durations",
                "--ignore", "--deselect", "--rootdir", "--confcutdir",
                "--tb", "--maxfail", "--junitxml", "--color", "--capture",
                "--basetemp", "--timeout", "--cov"}
# --cov stays a value flag even though pytest-cov declares it nargs='?':
# argparse still CONSUMES a following non-dash arg as the coverage
# source, so in `pytest --cov tests/tpu` the path is never a collection
# target (pytest collects the default paths) and dropping it matches
# pytest's real parse.  Removing it would instead let the cov source in
# `pytest tests/tpu --cov tests` veto the explicitly requested lane.


def _classified_paths(argv, cwd):
    """Yield (path, is_positional) for each non-flag arg, resolved
    against cwd (so `cd tests/tpu && pytest t.py`, `cd tests && pytest
    tpu`, and repo-root invocations all classify by the directory the
    arg actually points into).  An arg following an unknown flag is
    treated as that flag's value: not positional, but still visible to
    the veto scan (it might be a real collection target the parser
    misjudged — e.g. `pytest tests/tpu --runxfail tests/unit/x.py`).
    Values of KNOWN value-flags are dropped entirely."""
    prev = ""
    for a in argv:
        if not a.startswith("-"):
            positional = (not prev.startswith("-")
                          or _takes_no_value(prev))
            known_value = prev in _VALUE_FLAGS
            if not known_value:
                yield (os.path.normpath(
                    os.path.join(cwd, a.split("::", 1)[0])), positional)
        prev = a


def _under(path, root):
    return path == root or path.startswith(root + os.sep)


_cwd = os.getcwd()
_classified = list(_classified_paths(sys.argv[1:], _cwd))
_paths = [p for p, pos in _classified if pos]
_tpu_refs = [p for p in _paths if _under(p, _TPU_DIR)]
# Asymmetric on purpose: affirming the tpu lane requires a strict
# positional, vetoing it only requires any scanned arg (positional OR
# unknown-flag value) to name a non-tpu tests path — unknown-flag
# mistakes then always fall toward the CPU sim (where the tpu dir skips
# itself visibly), never toward running the unit suite on a real
# backend.
_other_tests_refs = [p for p, _pos in _classified
                     if _under(p, _TESTS_DIR) and not _under(p, _TPU_DIR)]
_tpu_lane_only = (
    bool(_tpu_refs) or (_under(_cwd, _TPU_DIR) and not _paths)
) and not _other_tests_refs

if not _tpu_lane_only:
    # Must be set before jax initializes its backends.  Note: the
    # environment may pre-import jax via sitecustomize, so the platform
    # override must go through jax.config (still honored
    # pre-backend-init) rather than JAX_PLATFORMS.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _tpu_lane_only:
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    from deepspeed_tpu.parallel import reset_mesh_context
    reset_mesh_context()


# ------------------------------------------------------------------------- #
# Two-tier suite (VERDICT r2 #7; reference analog: CI gates on
# `pytest --forked tests/unit`, .github/workflows/main.yml:50-52):
#
#   fast lane: python -m pytest tests/ -q -m "not slow"   (~4 min)
#   full lane: python -m pytest tests/ -q                 (~25 min, 1 core)
#
# Tests measured >= ~8 s on this box (1-core CPU sim mesh; generated from
# `pytest --durations=60`, 2026-07-30) are auto-marked `slow` below —
# trajectory-equality matrices, multi-process runs, convergence loops.
# Prefix match, so parametrized variants are covered.  Regenerate the list
# with --durations after large suite changes.
# ------------------------------------------------------------------------- #
_SLOW_PREFIXES = (
    "test_3d_matrix.py::test_composition_matches_baseline",
    "test_3d_matrix.py::test_moe_pipe_checkpoint_roundtrip",
    "test_3d_matrix.py::test_moe_zero_matches_zero0",
    # round-5 composition matrices: the fast lane keeps the representative
    # cells (plain-body pipe x expert, MoE manual-TP layer parity,
    # allgather attention parity); the full trajectory matrices run slow
    "test_3d_matrix.py::test_pipe_expert_matches_baseline",
    "test_3d_matrix.py::test_pipe_seq_matches_baseline",
    # HLO-compiles every candidate in the search (the dense twin's wire
    # is GSPMD-inserted, so monotonicity needs the compiled view)
    "test_autotuner.py::test_onebit_never_increases_wire_bytes",
    "test_bench_harness.py::test_sigterm_emits_one_diagnostic_json_line",
    "test_checkpoint_matrix.py::test_roundtrip",
    "test_convergence.py::test_gpt2_engine_converges",
    "test_engine_couplings.py::test_eigenvalue_disabled_keeps_global_schedule",
    "test_engine_couplings.py::test_eigenvalue_drives_moq_schedule",
    "test_engine_couplings.py::test_sparse_gradients_matches_dense",
    "test_fused_cross_entropy.py::test_gpt2_fused_loss_matches_naive",
    "test_functionality_matrix.py::test_matrix_matches_baseline",
    "test_gpt_moe.py::test_engine_training_converges",
    "test_gpt_moe.py::test_engine_training_tp_times_ep",
    "test_gpt_moe.py::test_engine_training_zero3",
    "test_gpt_moe.py::test_expert_params_sharded_over_expert_axis",
    "test_inference.py::test_generate_matches_full_recompute",
    "test_inference.py::test_hf_checkpoint_loader_path_greedy_decode_parity",
    "test_inference.py::test_hf_gpt2_injection_parity",
    "test_inference.py::test_megatron_layer_policy_parity",
    "test_infinity.py::test_host_param_streaming_matches_resident",
    # the fast lane keeps the fp32 prefetch-parity pin + the fault/
    # fallback/validation cells; the bf16 re-run of the same schedule
    # property goes slow
    "test_infinity_prefetch.py::test_prefetch_parity[bf16",
    "test_low_bandwidth.py::test_e2e_hpz_bf16_trains_on_cpu",
    "test_low_bandwidth.py::test_e2e_hpz_exact_parity_on_two_axis_mesh",
    "test_infinity.py::test_nvme_param_streaming_matches_resident",
    "test_models.py::test_bert_attention_mask_changes_output",
    "test_models.py::test_bert_mlm_loss_ignores_unmasked_positions",
    "test_models.py::test_gpt2_activation_checkpointing_same_loss",
    "test_models.py::test_gpt2_tensor_parallel_training_on_mesh",
    "test_models.py::test_gpt2_trains_through_engine",
    "test_moe.py::TestMOELayer::test_batched_input_shape",
    "test_moe.py::TestScatterDispatch::test_scatter_gradients_match_einsum",
    "test_moe.py::TestScatterDispatch::test_scatter_matches_einsum",
    "test_one_f_one_b.py::test_1f1b_matches_gpipe_trajectory",
    "test_one_f_one_b.py::test_1f1b_memory_does_not_scale_with_microbatches",
    "test_ops.py::test_transformer_layer_shapes_and_determinism",
    "test_profiler_launcher_tools.py::test_compressed_allreduce_error_feedback",
    "test_profiler_launcher_tools.py::test_onebit_adam_converges_after_freeze",
    "test_sequence_parallel.py::test_engine_trains_with_sequence_parallel",
    "test_sequence_parallel.py::test_ring_attention_grad_flows",
    "test_sharded_checkpoint.py::test_dp_resize_restore",
    "test_sharded_checkpoint.py::test_two_process_distributed_checkpoint",
    "test_sharded_checkpoint.py::test_two_process_distributed_training",
    "test_sparse_attention.py::test_gpt2_with_sparse_attention_trains",
    "test_training_dynamics.py::test_engine_pld_injected_into_gpt2",
    "test_zero3_streaming.py::test_carried_hpz_parity",
    "test_zero3_streaming.py::test_carried_low_bandwidth_parity",
    # prefix covers the fp32 parametrization and bf16 (the fast lane
    # keeps the carried cells that matter: the fused scan-in-scan
    # parity, the overlap-gate pin, and the liveness pin)
    "test_zero3_streaming.py::test_carried_mode_parity",
    "test_zero3_streaming.py::test_streaming_matches_baseline",
    "test_zero3_streaming.py::test_streaming_with_tensor_parallel",
    "test_zero3_streaming.py::test_zero3_bf16_streams_on_cpu",
)


def pytest_collection_modifyitems(config, items):
    slow = pytest.mark.slow
    for item in items:
        rel = item.nodeid.rsplit("/", 1)[-1]  # "<file>.py::<test>[...]"
        if rel.startswith(_SLOW_PREFIXES):
            item.add_marker(slow)
