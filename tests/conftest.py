"""Test harness: simulate an 8-device mesh on CPU so every collective path is
testable without TPU hardware (improves on the reference, which has no fake
backend — SURVEY.md §4)."""

import os

# Must be set before jax initializes its backends.  Note: the environment may
# pre-import jax via sitecustomize, so the platform override must go through
# jax.config (still honored pre-backend-init) rather than JAX_PLATFORMS.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    from deepspeed_tpu.parallel import reset_mesh_context
    reset_mesh_context()
