"""ZeRO-Infinity carried NVMe prefetch (ISSUE 8): the streaming engine's
double-buffered swap-in schedule must be compute-invariant (prefetch on/off
parity), measurable (overlap stats), honest under faults (a torn swap file
fails loudly, never a silent half-stale read), and degrade gracefully to
the Python sync path when no native aio lib builds.

Reference shapes: stage3.py:546 backward re-fetch + the PR 7 carried
double-buffer discipline one tier down (docs/zero_infinity.md)."""

import os

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.resilience.fault_injection import (InjectedCrash,
                                                              crash_after_bytes)
from deepspeed_tpu.runtime.swap_tensor import aio_handle as aio_handle_mod
from deepspeed_tpu.runtime.swap_tensor import (NVMeOffloadOptimizer,
                                               PartitionedParamSwapper)
from deepspeed_tpu.runtime.zero.infinity import (ZeroInfinityEngine,
                                                 load_sweep_ceiling)

SEQ = 32
BATCH = 4


def _model(bf16=False):
    cfg = GPT2Config(vocab_size=128, n_positions=SEQ, hidden_size=32,
                     num_layers=4, num_heads=4, bf16=bf16, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    return GPT2Model(cfg)


def _data():
    return np.asarray(jax.random.randint(jax.random.PRNGKey(5),
                                         (BATCH, SEQ), 0, 128), np.int32)


def _build(tmp_path, prefetch_depth, bf16=False, steps=0, **zo_extra):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=1, devices=jax.devices()[:1])
    model = _model(bf16=bf16)
    zo = {
        "stage": 3,
        "offload_param": {"device": "nvme", "nvme_path": str(tmp_path),
                          "buffer_count": 2,
                          "prefetch_depth": prefetch_depth},
        "offload_optimizer": {"device": "nvme",
                              "nvme_path": str(tmp_path)},
    }
    zo.update(zo_extra)
    conf = {
        "train_micro_batch_size_per_gpu": BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zo,
        "steps_per_print": 10 ** 9,
    }
    if bf16:
        conf["bf16"] = {"enabled": True}
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(9))
    assert isinstance(engine, ZeroInfinityEngine)
    ids = _data()
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return engine, losses


@pytest.mark.parametrize("bf16", [False, True], ids=["fp32", "bf16"])
def test_prefetch_parity(tmp_path, bf16):
    """The carried swap-in schedule moves bytes earlier, never changes the
    arithmetic: prefetch-on and prefetch-off trajectories must match
    exactly, while only the on-mode hides its swap traffic."""
    _, losses_off = _build(tmp_path / "off", prefetch_depth=0, bf16=bf16,
                           steps=3)
    engine_on, losses_on = _build(tmp_path / "on", prefetch_depth=2,
                                  bf16=bf16, steps=3)
    np.testing.assert_allclose(losses_on, losses_off, rtol=0, atol=0)
    stats = engine_on.swap_stats()
    assert stats["prefetch_depth"] == 2
    assert stats["read_bytes"] > 0
    # the double buffer hides most swap bytes even on a toy model
    assert stats["overlap_fraction"] > 0.5
    ds.reset_mesh_context()


def test_prefetch_off_reports_serialized(tmp_path):
    """With prefetch disabled every read is paid at use: the stats must
    say so (near-zero overlap), not flatter the schedule."""
    engine, _ = _build(tmp_path, prefetch_depth=0, steps=2)
    stats = engine.swap_stats()
    assert stats["prefetch_depth"] == 0
    assert stats["overlap_fraction"] < 0.2
    assert stats["read_exposed_s"] > 0
    ds.reset_mesh_context()


def test_swap_stats_shape_and_ceiling(tmp_path):
    """The honesty report carries achieved bytes/s and, when the sweep
    artifact exists, the ceiling it is compared against."""
    engine, _ = _build(tmp_path, prefetch_depth=2, steps=2)
    stats = engine.swap_stats()
    for key in ("aio_backend", "read_bytes", "read_gbps", "overlap_bytes",
                "overlap_fraction", "serialized_swap_ins", "write_bytes",
                "step_wall_s", "read_vs_ceiling", "optimizer_sweep"):
        assert key in stats, key
    ceiling = load_sweep_ceiling(engine.aio_backend)
    if ceiling is not None:  # benchmarks/aio_sweep_results.txt in repo
        assert stats["sweep_read_gbps"] == ceiling["read_gbps"]
        assert stats["read_vs_ceiling"] is not None
    assert stats["optimizer_sweep"]["leaves"] > 0
    ds.reset_mesh_context()


def test_crash_mid_swap_write_fails_loudly(tmp_path, monkeypatch):
    """A crash mid write-back (resilience's crash-after-N-bytes wrapper,
    on the Python aio path where open() is interceptable) must propagate
    out of step() — and the torn group file must then REFUSE to be
    consumed: the next forward raises instead of training on a half-old
    half-new layer."""
    monkeypatch.setattr(aio_handle_mod, "get_aio_lib", lambda: None)
    engine, _ = _build(tmp_path, prefetch_depth=2, steps=1)
    assert not engine._swapper.write_handle.using_native
    ids = _data()
    loss = engine.forward(ids)
    engine.backward(loss)
    # budget: enough for the optimizer tier's leaf write-backs to begin
    # param-group write-back, then die mid-group-file
    with pytest.raises(InjectedCrash):
        with crash_after_bytes(10_000, path_prefix=str(
                tmp_path / "zero_stage_3" / "params")):
            engine.step()
    # the interrupted write left a truncated group file somewhere — the
    # engine must fail loudly on it, not consume a torn read
    with pytest.raises(OSError):
        for _ in range(2):  # sweep all groups (first may be resident)
            loss = engine.forward(ids)
            engine.backward(loss)
    ds.reset_mesh_context()


def test_truncated_group_file_fails_loudly_native(tmp_path):
    """Same torn-read refusal on the NATIVE engines: a group file
    truncated under the engine (torn write-back, disk eviction) turns
    into -EIO at the next swap-in, raised as OSError."""
    engine, _ = _build(tmp_path, prefetch_depth=2, steps=1)
    assert engine._swapper.write_handle.using_native
    params_dir = tmp_path / "zero_stage_3" / "params"
    victim = params_dir / "param_group_layer2.bin"
    with open(victim, "r+b") as f:
        f.truncate(max(1, os.path.getsize(victim) // 2))
    ids = _data()
    with pytest.raises(OSError):
        loss = engine.forward(ids)
        engine.backward(loss)
    ds.reset_mesh_context()


def test_python_sync_fallback_parity(tmp_path, monkeypatch):
    """No native lib: the whole streaming stack (param swapper, optimizer
    tier, prefetch handles) must still train, on synchronous Python I/O,
    with the same trajectory as the native engines."""
    _, losses_native = _build(tmp_path / "native", prefetch_depth=2,
                              steps=2)
    monkeypatch.setattr(aio_handle_mod, "get_aio_lib", lambda: None)
    engine, losses_py = _build(tmp_path / "py", prefetch_depth=2, steps=2)
    assert not engine._swapper.write_handle.using_native
    assert engine.aio_backend == "python"
    np.testing.assert_allclose(losses_py, losses_native, rtol=0, atol=0)
    ds.reset_mesh_context()


def test_write_during_pending_prefetch_is_coherent(tmp_path):
    """ISSUE 8 bugfix: write() to a group whose prefetch read is still in
    flight must not race the file — the read completes first, then the
    window slot AND the file get the new bytes."""
    rs = np.random.RandomState(0)
    groups = {"a": {"w": rs.randn(64, 64).astype(np.float32)},
              "b": {"w": rs.randn(64, 64).astype(np.float32)}}
    sw = PartitionedParamSwapper(str(tmp_path), groups, buffer_count=2)
    sw.write("a", groups["a"])
    sw.write("b", groups["b"])
    sw.prefetch("a")                      # read in flight
    new_a = {"w": rs.randn(64, 64).astype(np.float32)}
    sw.write("a", new_a, async_op=True)   # overlaps the pending read
    sw.flush_writes()
    got = sw.get("a")
    np.testing.assert_array_equal(got["w"], new_a["w"])
    sw.release("a")
    got2 = sw.get("a")                    # re-read from the file
    np.testing.assert_array_equal(got2["w"], new_a["w"])


def test_optimizer_pipeline_depth_parity(tmp_path):
    """Depth-3 rotating buffer sets must produce the exact depth-2
    results — deeper pipelining moves reads earlier, never changes the
    Adam math."""
    rs = np.random.RandomState(0)
    params = {f"w{i}": rs.randn(32, 16).astype(np.float32)
              for i in range(6)}
    import jax.numpy as jnp
    outs = {}
    for depth in (2, 3):
        opt = NVMeOffloadOptimizer(params, str(tmp_path / f"d{depth}"),
                                   pipeline_depth=depth)
        for s in range(3):
            g = {k: np.random.RandomState(100 + s).randn(32, 16)
                 .astype(np.float32) for k in params}
            out = opt.apply(g, 1.0, None, jnp.float32)
            assert out is not None
        assert opt.last_sweep_stats["pipeline_depth"] == depth
        outs[depth] = opt.gather_master()
    for k in params:
        np.testing.assert_array_equal(outs[2][k], outs[3][k])


def test_config_validation_rejects_bad_knobs():
    """aio.backend / queue depths / prefetch depth are validated at the
    config boundary with constants single-sourced (PR 7 review pattern)."""
    from deepspeed_tpu.config import DeepSpeedConfig

    def cfg(aio=None, op=None, oo=None):
        c = {"train_micro_batch_size_per_gpu": 1,
             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
        if aio:
            c["aio"] = aio
        zo = {"stage": 3}
        if op:
            zo["offload_param"] = op
        if oo:
            zo["offload_optimizer"] = oo
        c["zero_optimization"] = zo
        return DeepSpeedConfig(c)

    with pytest.raises(DeepSpeedConfigError, match="backend"):
        cfg(aio={"backend": "libaio"})
    with pytest.raises(DeepSpeedConfigError, match="queue_depth"):
        cfg(aio={"queue_depth": 0})
    with pytest.raises(DeepSpeedConfigError, match="block_size"):
        cfg(aio={"block_size": 512})
    with pytest.raises(DeepSpeedConfigError, match="thread_count"):
        cfg(aio={"thread_count": 0})
    with pytest.raises(DeepSpeedConfigError, match="prefetch_depth"):
        cfg(op={"device": "nvme", "prefetch_depth": -1})
    with pytest.raises(DeepSpeedConfigError, match="prefetch_depth"):
        cfg(op={"device": "nvme", "buffer_count": 2, "prefetch_depth": 5})
    with pytest.raises(DeepSpeedConfigError, match="pipeline_depth"):
        cfg(oo={"device": "nvme", "pipeline_depth": 1})
    # valid composite passes and lands on the dataclasses
    c = cfg(aio={"backend": "batched", "queue_depth": 16},
            op={"device": "nvme", "buffer_count": 4, "prefetch_depth": 3},
            oo={"device": "nvme", "pipeline_depth": 4})
    assert c.aio_config.backend == "batched"
    assert c.zero_config.offload_param.prefetch_depth == 3
    assert c.zero_config.offload_optimizer.pipeline_depth == 4
