"""ZeRO-Infinity layer-streaming engine (runtime/zero/infinity.py):
parameters paged from host/NVMe group by group, fp32 master + moments in
the host/NVMe optimizer tier, HBM never holding the full model.

Reference parity targets: stage3 + offload_param (stage3.py:932 NVMe param
swapping; partitioned_param_swapper.py:36), sub_group-wise optimizer sweep
(stage3.py:2777), "max model per device" (BASELINE.md 40B/V100 row).
"""

import numpy as np

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.zero.infinity import ZeroInfinityEngine

SEQ = 32
BATCH = 4


def _model():
    cfg = GPT2Config(vocab_size=128, n_positions=SEQ, hidden_size=32,
                     num_layers=4, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    return GPT2Model(cfg)


def _data():
    return np.asarray(jax.random.randint(jax.random.PRNGKey(5),
                                         (BATCH, SEQ), 0, 128), np.int32)


def _train_baseline(steps=4):
    """Reference trajectory: resident engine + the same host Adam tier."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=1, devices=jax.devices()[:1])
    model = _model()
    conf = {
        "train_micro_batch_size_per_gpu": BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(9))
    ids = _data()
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    master = jax.tree.map(np.asarray, engine.optimizer.master_params)
    ds.reset_mesh_context()
    return losses, master


def _train_infinity(offload_param_device, tmp_path, steps=4,
                    opt_device="cpu"):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=1, devices=jax.devices()[:1])
    model = _model()
    zo = {
        "stage": 3,
        "offload_param": {"device": offload_param_device,
                          "nvme_path": str(tmp_path), "buffer_count": 2},
    }
    if opt_device == "nvme":
        zo["offload_optimizer"] = {"device": "nvme",
                                   "nvme_path": str(tmp_path)}
    conf = {
        "train_micro_batch_size_per_gpu": BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zo,
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(9))
    assert isinstance(engine, ZeroInfinityEngine)
    ids = _data()
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    ds.reset_mesh_context()
    return losses, engine


def test_host_param_streaming_matches_resident(tmp_path):
    base_losses, base_master = _train_baseline()
    losses, engine = _train_infinity("cpu", tmp_path)
    np.testing.assert_allclose(losses, base_losses, rtol=1e-5, atol=1e-6)
    master = jax.tree.map(np.asarray, engine.optimizer.master_params)
    # tied-wte grads accumulate in a different order (embed vjp + head vjp
    # vs one fused autodiff) — fp32 summation noise only
    for a, b in zip(jax.tree.leaves(master), jax.tree.leaves(base_master)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=2e-5)


def test_nvme_param_streaming_matches_resident(tmp_path):
    """Params AND optimizer states on NVMe files — the full Infinity tier.
    The CPU sim cannot enforce an HBM budget, so the 'never fully resident'
    claim is asserted via the engine's own residency accounting: at most 2
    parameter groups on device at any time, for a 6-group model."""
    base_losses, _ = _train_baseline()
    losses, engine = _train_infinity("nvme", tmp_path, opt_device="nvme")
    np.testing.assert_allclose(losses, base_losses, rtol=1e-5, atol=1e-6)
    assert engine.max_live_param_groups <= 2
    assert engine._swapper is not None
    # the host window never holds more groups than its buffer count
    assert len(engine._swapper.resident_groups) <= 2
    mem = engine.estimate_memory()
    assert mem["hbm_param_window"] < mem["host_or_nvme_params"]


def test_gradient_accumulation(tmp_path):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=1, devices=jax.devices()[:1])
    model = _model()
    conf = {
        "train_micro_batch_size_per_gpu": BATCH,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu"}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(9))
    ids = _data()
    for _ in range(2):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
    assert engine.global_steps == 1
    assert engine.micro_steps == 2
    ds.reset_mesh_context()


def test_legacy_cpu_offload_params_key_dispatches(tmp_path):
    """The v0.5-era flat key (zero/config.py cpu_offload_params back-compat)
    must reach the streaming engine exactly like the offload_param dict."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=1, devices=jax.devices()[:1])
    model = _model()
    conf = {
        "train_micro_batch_size_per_gpu": BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "cpu_offload_params": True},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(9))
    assert isinstance(engine, ZeroInfinityEngine)
    loss = engine.forward(_data())
    engine.backward(loss)
    engine.step()
    assert engine.global_steps == 1
    ds.reset_mesh_context()


def test_checkpoint_roundtrip(tmp_path):
    losses, engine = _train_infinity("cpu", tmp_path, steps=2)
    ckpt_dir = str(tmp_path / "ckpt")
    engine.save_checkpoint(ckpt_dir)
    before = jax.tree.map(np.asarray, engine.module_state_dict())

    _, engine2 = _train_infinity("cpu", tmp_path / "other", steps=1)
    engine2.load_checkpoint(ckpt_dir)
    after = jax.tree.map(np.asarray, engine2.module_state_dict())
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)
    assert engine2.global_steps == 2


def test_join_consuming_matches_join_and_frees():
    """join_consuming must produce a tree EQUAL to join (same stacked
    layout the optimizer tier was built around) while consuming its
    input: every numpy layer-group leaf reference is dropped (set to
    None) once stacked — the r4 fix for the optimizer-boundary OOM at
    multi-B params (a full second copy of all layer grads)."""
    model = _model()
    api = model.layerwise_api()
    params = model.init_params(jax.random.PRNGKey(0))
    host = jax.tree.map(lambda a: np.asarray(a, np.float32), params)

    groups_a = api["split"](host)
    groups_b = api["split"](host)
    # split returns views of the SAME host arrays for both copies, so
    # value comparison below is against independent reconstructions
    joined = api["join"](groups_a)
    consumed = api["join_consuming"](groups_b)

    la = jax.tree.leaves(joined)
    lb = jax.tree.leaves(consumed)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the consuming join must have dropped every layer-group reference
    for i in range(api["num_layers"]):
        assert groups_b[f"layer{i}"] is None
