"""Functionality matrix — the run_func_test.py:606 analog: train the same
tiny GPT-2 under every (zero stage x tensor parallel x offload) combination
on the simulated 8-device mesh and assert they all compute the SAME
optimization trajectory (ZeRO/TP/offload are memory/layout strategies, not
math changes)."""

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model


GLOBAL_BATCH = 8  # fixed across every cell — tp changes dp, never the data


def _train(zero_stage: int, tp: int, offload: bool, steps: int = 3):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1, model=tp)
    cfg = GPT2Config(vocab_size=128, n_positions=32, hidden_size=64,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    dp = mesh.data_parallel_world_size
    assert GLOBAL_BATCH % dp == 0
    conf = {
        # hold the GLOBAL batch constant so every matrix cell trains on
        # identical data (round-1 bug: per-chip batch was held fixed, so
        # tp=2 cells saw a different batch and diverged from the baseline)
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 10 ** 9,
    }
    if offload:
        conf["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(42))
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                        (GLOBAL_BATCH, 32), 0, 128), np.int32)
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    final = jax.tree.map(np.asarray, engine.params)
    ds.reset_mesh_context()
    return losses, final


MATRIX = [
    (0, 1, False), (1, 1, False), (2, 1, False), (3, 1, False),
    (0, 2, False),  # pure TP vs TP=1 — validates TP is math-preserving
    (2, 2, False), (3, 2, False), (2, 1, True), (3, 2, True),
]


@pytest.mark.parametrize("stage,tp,offload", MATRIX,
                         ids=[f"z{s}-tp{t}{'-off' if o else ''}"
                              for s, t, o in MATRIX])
def test_matrix_matches_baseline(stage, tp, offload):
    base_losses, base_params = _train(0, 1, False)
    losses, params = _train(stage, tp, offload)
    np.testing.assert_allclose(losses, base_losses, rtol=2e-4,
                               err_msg=f"z{stage} tp{tp} off={offload}")
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)
