"""Source-lint suite (ISSUE 20): one seeded violation fixture per rule
(rule-id/severity/provenance asserts), the clean-tree zero-findings pin
(the in-process twin of the tier1.yml lint-source gate), the
suppression-with-reason round-trip, and the CLI exit-code cells.

Fixture trees mirror the real package layout under tmp_path because
the manifest keys invariants by repo-relative path (the deterministic
planes, the declared state classes) — a violation planted at
``deepspeed_tpu/runtime/resilience/chaos.py`` in a scratch tree
exercises exactly the lookup the real tree gets.
"""

import subprocess
import sys
from pathlib import Path

import pytest

from deepspeed_tpu.analysis.source_lint import (
    RULE_CHECKPOINT_STATE,
    RULE_DEGRADATION_COVERAGE,
    RULE_DETERMINISM,
    RULE_KNOB_TRI_SOURCING,
    RULE_SUPPRESSION,
    RULE_THREAD_DISCIPLINE,
    run_source_lint,
)

REPO = Path(__file__).resolve().parents[2]


def _plant(root: Path, rel: str, text: str) -> None:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)


def _findings(root: Path, rule: str):
    report = run_source_lint(str(root))
    return [f for f in report.findings if f.rule == rule]


# --------------------------------------------------------------- #
# the clean-tree pin: the shipped tree must lint clean with zero
# suppressions — the in-process twin of the tier1.yml gate step
# --------------------------------------------------------------- #

def test_shipped_tree_lints_clean():
    report = run_source_lint(str(REPO))
    errors = [f.format() for f in report.findings
              if f.severity == "error"]
    assert not errors, "source lint errors on the shipped tree:\n" \
        + "\n".join(errors)
    # zero unexplained suppressions: today that is zero suppressions,
    # full stop — adding one must be a visible, test-breaking act
    assert report.suppressed == [], (
        "the shipped tree should need no ds-lint suppressions; if one "
        "became necessary, re-pin this with its reason in view: "
        f"{report.suppressed}")
    assert report.files_scanned > 100  # walked the real package


# --------------------------------------------------------------- #
# one seeded violation fixture per rule
# --------------------------------------------------------------- #

def test_thread_discipline_fixture(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/worker.py", """\
import threading

def spawn():
    t = threading.Thread(target=print)
    t.start()
    return t
""")
    hits = _findings(tmp_path, RULE_THREAD_DISCIPLINE)
    assert len(hits) == 2  # neither daemon'd/joined, and unnamed
    for f in hits:
        assert f.severity == "error"
        assert f.path == "deepspeed_tpu/worker.py"
        assert f.line == 4
        assert f.scope == "spawn"
    msgs = " | ".join(f.message for f in hits)
    assert "neither daemon'd nor provably joined" in msgs
    assert "must be named with the ds- prefix" in msgs


def test_thread_discipline_accepts_the_sanctioned_shapes(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/worker.py", """\
import threading

def good_daemon():
    t = threading.Thread(target=print, daemon=True,
                         name="ds-test-worker")
    t.start()

def good_fstring(host):
    t = threading.Thread(target=print, daemon=True,
                         name=f"ds-pump-{host}")
    t.start()

def good_post_creation():
    t = threading.Timer(1.0, print)
    t.daemon = True
    t.name = "ds-test-grace"
    t.start()

def good_joined():
    t = threading.Thread(target=print)
    t.start()
    t.join()
""")
    assert _findings(tmp_path, RULE_THREAD_DISCIPLINE) == []


def test_thread_discipline_timed_join_is_not_provably_joined(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/worker.py", """\
import threading

def timed():
    t = threading.Thread(target=print)
    t.start()
    t.join(5.0)
""")
    hits = _findings(tmp_path, RULE_THREAD_DISCIPLINE)
    assert hits and all(f.severity == "error" for f in hits)


def test_thread_discipline_bare_acquire(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/locky.py", """\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self):
        self._lock.acquire()
        try:
            return 1
        finally:
            self._lock.release()
""")
    hits = _findings(tmp_path, RULE_THREAD_DISCIPLINE)
    assert len(hits) == 1
    assert "acquire" in hits[0].message
    assert hits[0].scope == "Box.bad"


def test_thread_discipline_undeclared_shared_attr(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/shared.py", """\
import threading

class Pump:
    def __init__(self):
        self.failed = False
        self.t = threading.Thread(target=self._run, daemon=True,
                                  name="ds-test-pump")

    def _run(self):
        self.failed = True

    def poll(self):
        return self.failed
""")
    hits = _findings(tmp_path, RULE_THREAD_DISCIPLINE)
    assert len(hits) == 1
    f = hits[0]
    assert "self.failed" in f.message and "lock map" in f.message
    assert f.scope == "Pump._run"


def test_determinism_fixture(tmp_path):
    # planted AT a declared deterministic-plane path
    _plant(tmp_path, "deepspeed_tpu/runtime/resilience/chaos.py", """\
import random
import time


def schedule_jitter():
    return time.time() + random.random()


def sanctioned(seed):
    rng = random.Random(seed)
    time.sleep(0.01)
    return rng.random()
""")
    hits = _findings(tmp_path, RULE_DETERMINISM)
    assert {f.message for f in hits} == {
        "time.time() read inside the deterministic plane",
        "module-level random.random() inside the deterministic plane"}
    for f in hits:
        assert f.severity == "error"
        assert f.path == "deepspeed_tpu/runtime/resilience/chaos.py"
        assert f.line == 6
        assert f.scope == "schedule_jitter"


def test_determinism_ignores_files_outside_the_planes(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/monitor/capture.py",
           "import time\nNOW = time.time()\n")
    assert _findings(tmp_path, RULE_DETERMINISM) == []


def test_degradation_coverage_fixture(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/runtime/swapper.py", """\
def read(path):
    try:
        return open(path).read()
    except Exception as e:
        print(f"read failed ({e}) — using empty fallback")
        return ""
""")
    hits = _findings(tmp_path, RULE_DEGRADATION_COVERAGE)
    assert len(hits) == 1
    f = hits[0]
    assert f.severity == "error"
    assert (f.path, f.line, f.scope) == (
        "deepspeed_tpu/runtime/swapper.py", 4, "read")


def test_degradation_coverage_registered_handler_is_clean(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/runtime/swapper.py", """\
def read(path):
    try:
        return open(path).read()
    except Exception as e:
        from .resilience.degradation import record
        record("swapper", "file", "empty", str(e))
        return ""


def narrow(path):
    try:
        return open(path).read()
    except FileNotFoundError:
        return ""


def rethrows(path):
    try:
        return open(path).read()
    except Exception:
        raise RuntimeError(path)
""")
    assert _findings(tmp_path, RULE_DEGRADATION_COVERAGE) == []


def test_knob_tri_sourcing_fixture(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/constants.py", """\
ORPHANED_KNOB = "orphaned_knob"
ORPHANED_KNOB_DEFAULT = 0
UNDOCUMENTED_KNOB = "undocumented_knob"
UNDOCUMENTED_KNOB_DEFAULT = 1
GOOD_KNOB = "good_knob"
GOOD_KNOB_DEFAULT = 2
NOT_A_KNOB = "no default sibling, not part of the contract"
""")
    _plant(tmp_path, "deepspeed_tpu/config.py",
           "from .constants import GOOD_KNOB, UNDOCUMENTED_KNOB\n")
    _plant(tmp_path, "docs/config_reference.md",
           "`good_knob` does a thing\n")
    hits = _findings(tmp_path, RULE_KNOB_TRI_SOURCING)
    by_name = {f.message.split()[1].rstrip(":"): f for f in hits}
    assert set(by_name) == {"ORPHANED_KNOB", "UNDOCUMENTED_KNOB"}
    assert "no validator module" in by_name["ORPHANED_KNOB"].message
    assert "appears nowhere in docs/" in \
        by_name["UNDOCUMENTED_KNOB"].message
    assert all(f.severity == "error" for f in hits)
    assert all(f.path == "deepspeed_tpu/constants.py" for f in hits)
    assert by_name["ORPHANED_KNOB"].line == 1
    assert by_name["UNDOCUMENTED_KNOB"].line == 3


def test_checkpoint_state_fixture(tmp_path):
    # planted AT the declared TrainingSentinel path: a counter that is
    # mutated but missing from both sides of the round-trip (the
    # onebit_phase bug class)
    _plant(tmp_path, "deepspeed_tpu/runtime/resilience/sentinel.py", """\
class TrainingSentinel:
    def __init__(self):
        self.anomalies_seen = 0
        self.rewinds = 0

    def observe(self, bad):
        if bad:
            self.anomalies_seen += 1
            self.rewinds += 1

    def state_dict(self):
        return {"rewinds": self.rewinds}

    def load_state_dict(self, sd):
        self.rewinds = int(sd.get("rewinds", 0))
""")
    hits = _findings(tmp_path, RULE_CHECKPOINT_STATE)
    assert [f.scope for f in hits] == [
        "TrainingSentinel.anomalies_seen"] * 2  # missing on BOTH sides
    sides = {f.message.split("visible in ")[1].split()[0] for f in hits}
    assert sides == {"save", "load"}
    for f in hits:
        assert f.severity == "error"
        assert f.path == "deepspeed_tpu/runtime/resilience/sentinel.py"


def test_checkpoint_state_roundtrip_is_clean(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/runtime/resilience/sentinel.py", """\
class TrainingSentinel:
    def __init__(self):
        self.anomalies_seen = 0

    def observe(self, bad):
        if bad:
            self.anomalies_seen += 1

    def counters(self):
        return {"anomalies_seen": self.anomalies_seen}

    def state_dict(self):
        return self.counters()

    def load_state_dict(self, sd):
        self.anomalies_seen = int(sd.get("anomalies_seen", 0))
""")
    assert _findings(tmp_path, RULE_CHECKPOINT_STATE) == []


# --------------------------------------------------------------- #
# suppression contract
# --------------------------------------------------------------- #

def test_suppression_with_reason_roundtrip(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/worker.py", """\
# ds-lint: disable=thread-discipline(fixture thread, lifetime is the test)
import threading

def spawn():
    t = threading.Thread(target=print)
    t.start()
""")
    report = run_source_lint(str(tmp_path))
    assert not report.has_errors
    assert report.suppressed == [
        ("deepspeed_tpu/worker.py", "thread-discipline",
         "fixture thread, lifetime is the test")] * 2
    assert report.counts()["error"] == 0


def test_suppression_without_reason_is_an_error(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/worker.py", """\
# ds-lint: disable=thread-discipline
import threading

def spawn():
    t = threading.Thread(target=print)
    t.start()
""")
    report = run_source_lint(str(tmp_path))
    sup = [f for f in report.findings if f.rule == RULE_SUPPRESSION]
    assert len(sup) == 1
    assert sup[0].severity == "error"
    assert "carries no reason" in sup[0].message
    assert sup[0].line == 1
    # and the reasonless entry suppresses NOTHING
    assert [f for f in report.findings
            if f.rule == RULE_THREAD_DISCIPLINE]


def test_stale_suppression_warns(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/clean.py",
           "# ds-lint: disable=determinism(left over from a refactor)\n"
           "X = 1\n")
    report = run_source_lint(str(tmp_path))
    stale = [f for f in report.findings if f.rule == RULE_SUPPRESSION]
    assert len(stale) == 1
    assert stale[0].severity == "warning"
    assert "stale suppression" in stale[0].message
    assert not report.has_errors


def test_docstring_mention_is_not_a_suppression(tmp_path):
    _plant(tmp_path, "deepspeed_tpu/doc.py",
           '"""Syntax example: # ds-lint: disable=determinism."""\n'
           "X = 1\n")
    report = run_source_lint(str(tmp_path))
    assert report.findings == []


# --------------------------------------------------------------- #
# CLI exit-code cells (the tier1.yml subprocess contract)
# --------------------------------------------------------------- #

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", "lint-source",
         *args],
        cwd=REPO, capture_output=True, text=True, timeout=120)


@pytest.mark.parametrize("rule_fixture", [
    ("deepspeed_tpu/worker.py",
     "import threading\n\n"
     "def spawn():\n"
     "    threading.Thread(target=print).start()\n"),
    ("deepspeed_tpu/runtime/resilience/retry.py",
     "import time\n\nDEADLINE = time.time()\n"),
])
def test_cli_exits_nonzero_on_violation_fixture(tmp_path, rule_fixture):
    rel, text = rule_fixture
    _plant(tmp_path, rel, text)
    proc = _cli("--root", str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[ERROR" in proc.stdout


def test_cli_exits_zero_on_shipped_tree_and_emits_json():
    proc = _cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json
    payload = json.loads(proc.stdout)
    assert payload["counts"]["error"] == 0
    assert payload["files_scanned"] > 100
    assert payload["suppressed"] == []
