"""End-to-end engine tests on the 8-device CPU-sim mesh (role of reference
tests/unit/test_fp16.py + test_zero.py smoke paths)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from tests.unit.simple_model import (base_engine_config, random_dataloader,
                                     simple_model_apply, simple_model_params)

HIDDEN = 16


def make_engine(stage=0, gas=1, micro=8, dtype_cfg=None, **overrides):
    cfg = base_engine_config(micro_batch=micro, gas=gas, **(overrides or {}))
    if stage:
        cfg["zero_optimization"] = {"stage": stage}
    if dtype_cfg:
        cfg.update(dtype_cfg)
    params = simple_model_params(HIDDEN)
    engine, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                                    model_parameters=params)
    return engine


def train_steps(engine, n=10, micro=8, seed=5):
    # cycle a small fixed dataset so the loss decrease is deterministic
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    loader = random_dataloader(
        HIDDEN, total_samples=4 * micro * engine.gradient_accumulation_steps(),
        batch_size=micro, seed=seed)
    it = iter(RepeatingLoader(loader))
    losses = []
    for _ in range(n):
        for _ in range(engine.gradient_accumulation_steps()):
            x, y = next(it)
            loss = engine.forward(x, y)
            engine.backward(loss)
            engine.step()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_loss_decreases_all_stages(stage):
    engine = make_engine(stage=stage)
    losses = train_steps(engine, n=15)
    assert losses[-1] < losses[0] * 0.9, f"no learning: {losses}"


def test_stage_parity():
    """All ZeRO stages must produce (near-)identical training trajectories —
    the sharding is a memory layout, not a math change (role of reference
    test_zero.py:233 correctness-vs-baseline)."""
    ref = None
    for stage in [0, 1, 2, 3]:
        engine = make_engine(stage=stage)
        losses = train_steps(engine, n=8, seed=77)
        if ref is None:
            ref = losses
        else:
            np.testing.assert_allclose(losses, ref, rtol=2e-4)


def test_gradient_accumulation_equivalence():
    """gas=2 with micro=4 must match gas=1 with micro=8 (same global batch):
    both consume the same 8 samples per optimizer step, so the parameter
    trajectories must agree."""
    e1 = make_engine(stage=0, gas=1, micro=8)
    e2 = make_engine(stage=0, gas=2, micro=4)
    train_steps(e1, n=6, micro=8, seed=9)
    train_steps(e2, n=6, micro=4, seed=9)
    p1 = jax.tree.map(np.asarray, e1.params)
    p2 = jax.tree.map(np.asarray, e2.params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                 p1, p2)


def test_micro_step_boundary():
    engine = make_engine(stage=0, gas=4, micro=2)
    loader = random_dataloader(HIDDEN, 64, 2)
    it = iter(loader)
    for i in range(4):
        x, y = next(it)
        engine.backward(engine.forward(x, y))
        engine.step()
        if i < 3:
            assert engine.global_steps == 0
    assert engine.global_steps == 1


def test_fp16_dynamic_loss_scale_halves_on_overflow():
    """Overflow must skip the step and halve the scale (role of reference
    test_dynamic_loss_scale.py:315)."""
    cfg = {"fp16": {"enabled": True, "initial_scale_power": 4,
                    "loss_scale_window": 2, "hysteresis": 1,
                    "min_loss_scale": 0.25}}
    engine = make_engine(stage=0, dtype_cfg=cfg)
    assert engine.loss_scale == 16.0
    params_before = jax.tree.map(np.asarray, engine.params)

    x = np.full((8, HIDDEN), np.nan, np.float32)
    y = np.zeros((8,), np.float32)
    engine.backward(engine.forward(x, y))
    engine.step()
    assert engine.overflow
    assert engine.loss_scale == 8.0
    params_after = jax.tree.map(np.asarray, engine.params)
    jax.tree.map(np.testing.assert_array_equal, params_before, params_after)


def test_fp16_scale_doubles_after_window():
    cfg = {"fp16": {"enabled": True, "initial_scale_power": 4,
                    "loss_scale_window": 2, "hysteresis": 1}}
    engine = make_engine(stage=0, dtype_cfg=cfg)
    train_steps(engine, n=2)
    assert engine.loss_scale == 32.0  # 2 clean steps → doubled once


def test_fp16_hysteresis():
    cfg = {"fp16": {"enabled": True, "initial_scale_power": 4,
                    "loss_scale_window": 100, "hysteresis": 2}}
    engine = make_engine(stage=0, dtype_cfg=cfg)
    x = np.full((8, HIDDEN), np.nan, np.float32)
    y = np.zeros((8,), np.float32)
    engine.backward(engine.forward(x, y))
    engine.step()
    assert engine.loss_scale == 16.0  # first overflow burns hysteresis
    engine.backward(engine.forward(x, y))
    engine.step()
    assert engine.loss_scale == 8.0  # second halves


def test_bf16_training():
    engine = make_engine(stage=2, dtype_cfg={"bf16": {"enabled": True}})
    losses = train_steps(engine, n=20)
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_bf16_grads_in_compute_dtype():
    """bf16 gradient buffers (the reference's fp16-grad-buffer analog):
    grads leave the grad program in bf16, training still converges, and
    the fp32 upcast lives in the apply program."""
    engine = make_engine(
        stage=2, dtype_cfg={"bf16": {"enabled": True,
                                     "grads_in_compute_dtype": True}})
    rng = np.random.RandomState(0)
    x = rng.standard_normal((8, HIDDEN)).astype(np.float32)
    y = rng.standard_normal((8,)).astype(np.float32)
    engine.backward(engine.forward(x, y))
    leaves = jax.tree.leaves(engine._grad_acc)
    assert leaves, "no accumulated grads cached"
    for g in leaves:
        assert g.dtype == jnp.bfloat16, g.dtype
    engine.step()
    losses = train_steps(engine, n=20)
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_static_loss_scale():
    cfg = {"fp16": {"enabled": True, "loss_scale": 128.0}}
    engine = make_engine(stage=0, dtype_cfg=cfg)
    assert engine.loss_scale == 128.0
    train_steps(engine, n=3)
    assert engine.loss_scale == 128.0  # static never changes


def test_gradient_clipping_runs():
    engine = make_engine(stage=2, gradient_clipping=0.1)
    losses = train_steps(engine, n=10)
    assert np.isfinite(losses).all()


def test_lamb_optimizer():
    engine = make_engine(
        stage=1,
        optimizer={"type": "Lamb", "params": {"lr": 5e-2,
                                              "max_coeff": 0.3,
                                              "min_coeff": 0.01}})
    losses = train_steps(engine, n=24)
    # compare full cycles over the 4-batch dataset (phase-aligned)
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_scheduler_integration():
    engine = make_engine(
        stage=0,
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2,
                              "warmup_num_steps": 10}})
    train_steps(engine, n=5)
    lr = engine.get_lr()[0]
    assert 0 < lr <= 1e-2


def test_zero3_params_are_sharded():
    engine = make_engine(
        stage=0,  # 0 = don't clobber the explicit zero_optimization override
        zero_optimization={"stage": 3, "stage3_param_persistence_threshold": 0})
    any_sharded = False
    for leaf in jax.tree.leaves(engine.params):
        spec = leaf.sharding.spec
        if any(p is not None for p in spec):
            any_sharded = True
    assert any_sharded, "stage 3 should shard at least the 16x16 weights"


def test_memory_estimator():
    engine0 = make_engine(stage=0)
    engine3 = make_engine(stage=3)
    m0 = engine0.estimate_memory()
    m3 = engine3.estimate_memory()
    assert m3["optimizer"] < m0["optimizer"]
    assert m3["params"] < m0["params"]


def test_train_batch_convenience():
    engine = make_engine(stage=2, gas=2, micro=4)
    loader = random_dataloader(HIDDEN, 128, 4)
    it = iter(loader)
    loss0 = engine.train_batch(it)
    for _ in range(8):
        loss = engine.train_batch(it)
    assert loss < loss0


def test_multi_output_model_uses_first_as_loss():
    """Models returning (loss, aux...) train on out[0] (the reference's
    multi_output_model.py coverage class)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import deepspeed_tpu as ds

    w0 = jnp.ones((4,), jnp.float32)

    def model(p, rng, x, y):
        pred = x @ p["w"]
        loss = jnp.mean((pred - y) ** 2)
        return loss, pred.sum()  # aux output must be ignored by training

    engine, _, _, _ = ds.initialize(
        model=model, model_parameters={"w": w0},
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-1}},
                "steps_per_print": 10 ** 9})
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = (x @ np.array([1., 2., 3., 4.], np.float32)).astype(np.float32)
    losses = []
    for _ in range(10):
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def _assert_fp16_export(engine, tmp_path):
    import jax
    import numpy as np
    path = engine.save_fp16_model(str(tmp_path))
    loaded = np.load(path)
    flat = jax.tree_util.tree_flatten_with_path(engine.params)[0]
    assert len(loaded.files) == len(flat)
    import jax.numpy as jnp
    for key_path, leaf in flat:
        name = jax.tree_util.keystr(key_path)
        arr = loaded[name]
        host = np.asarray(leaf)
        if jnp.issubdtype(host.dtype, jnp.floating):
            assert arr.dtype == np.float16, (name, arr.dtype)
            np.testing.assert_allclose(arr.astype(np.float32),
                                       host.astype(np.float32), rtol=1e-2,
                                       atol=1e-4)
        else:
            np.testing.assert_array_equal(arr, host)


def test_save_fp16_model_export(tmp_path):
    """Consolidated half-precision export (reference save_fp16_model):
    one npz of fp16 weights, loadable and matching the live params —
    including from a ZeRO-3 sharded engine."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=16,
                     num_layers=2, num_heads=2, bf16=True)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
                "steps_per_print": 10 ** 9})
    _assert_fp16_export(engine, tmp_path)


def test_save_fp16_model_export_bf16_offload(tmp_path):
    """ZeRO-Offload stores DEVICE params in the compute dtype (bf16) —
    the export must still emit readable fp16, not raw bf16 bytes (numpy
    would silently serialize ml_dtypes as void)."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=16,
                     num_layers=2, num_heads=2, bf16=True)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {
                    "stage": 2, "offload_optimizer": {"device": "cpu"}},
                "steps_per_print": 10 ** 9})
    assert any(jnp.issubdtype(leaf.dtype, jnp.bfloat16) or
               leaf.dtype == jnp.bfloat16
               for leaf in jax.tree.leaves(engine.params)), \
        "offload engine should hold bf16 device params"
    _assert_fp16_export(engine, tmp_path)
