"""Fused collective-matmul (ops/collective_matmul.py, ISSUE 13,
docs/fused_collective_matmul.md): T3-style per-tile fusion of the
qwZ/qgZ transports with their producer/consumer GEMMs.

Interpret-mode coverage on the 8-device CPU sim mesh — the per-tile GEMM
kernels run under ``pallas_call(interpret=True)`` with the remote-copy
ring mesh-simulated as ``lax.ppermute`` (the flash_attention.py
pattern); the in-kernel RDMA path is chip-only (ROADMAP item 1).

Pinned contracts: fused-vs-modular forward/backward numerics (qwZ gather
BITWISE, qgZ scatter bitwise via the shard-order accumulation contract),
error-feedback round-trip over 6 steps, grad flow through the fused
custom_vjp under the carried streaming scan, the Schedule Auditor's
fused/hidden classification with zero new host_sync/lockstep findings,
and config validation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu import constants as C
from deepspeed_tpu.ops import collective_matmul as cm
from deepspeed_tpu.runtime.comm import low_bandwidth as lb

from .test_zero3_streaming import _mode_cfg, _train_tiny


def _mesh(n=4, name="data"):
    devs = np.array(jax.devices()[:n]).reshape(n)
    return Mesh(devs, (name,))


def _sm(f, mesh, in_specs, out_specs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


# --------------------------------------------------------------------- #
# transport drop-ins: fused vs modular numerics
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("qwz,qgz", [(8, 8), (8, 0), (4, 4), (0, 0)])
def test_fcm_all_gather_forward_bitwise(dtype, qwz, qgz):
    """The fused gather is BITWISE-identical to the modular qwZ path at
    every width (the same quantization runs once at the source, the
    same dequant math per tile) — only the transport schedule differs."""
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 24)).astype(dtype)

    def fused(a):
        return cm.fcm_all_gather(a, ("data",), 0, qwz, qgz, 16)

    def modular(a):
        if qwz or qgz:
            return lb.low_bandwidth_all_gather(a, ("data",), 0, qwz,
                                               qgz, 16)
        return lax.all_gather(a, ("data",), axis=0, tiled=True)

    of = _sm(fused, mesh, P("data"), P("data"))(x)
    om = _sm(modular, mesh, P("data"), P("data"))(x)
    assert of.dtype == om.dtype == dtype
    assert (np.asarray(of) == np.asarray(om)).all()


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("qwz,qgz", [(8, 8), (4, 4)])
def test_fcm_all_gather_backward_bitwise(dtype, qwz, qgz):
    """With qgZ on, the fused custom_vjp's transpose keeps the modular
    accumulation-order contract (dequantized source table summed in
    shard-index order) — grads are bitwise-equal."""
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 24)).astype(dtype)

    def g_of(fn):
        def loss(a):
            y = fn(a)
            return jnp.sum((y.astype(jnp.float32)) ** 2)
        return _sm(jax.grad(loss), mesh, P("data"), P("data"))(x)

    gf = g_of(lambda a: cm.fcm_all_gather(a, ("data",), 0, qwz, qgz, 16))
    gm = g_of(lambda a: lb.low_bandwidth_all_gather(a, ("data",), 0,
                                                    qwz, qgz, 16))
    assert (np.asarray(gf) == np.asarray(gm)).all()


def test_fcm_all_gather_backward_f32_fallback_close():
    """qgz_bits=0: the fused transpose reduces through the per-tile
    table in fp32 with a FIXED shard-index order; the modular
    psum_scatter leaves the order to XLA — equal up to reassociation."""
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 24))

    def g_of(fn):
        def loss(a):
            return jnp.sum(fn(a) ** 2)
        return _sm(jax.grad(loss), mesh, P("data"), P("data"))(x)

    gf = g_of(lambda a: cm.fcm_all_gather(a, ("data",), 0, 8, 0, 16))
    gm = g_of(lambda a: lb.low_bandwidth_all_gather(a, ("data",), 0,
                                                    8, 0, 16))
    np.testing.assert_allclose(gf, gm, rtol=1e-6, atol=1e-6)


def test_fcm_reduce_scatter_matches_modular_bitwise():
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 8, 12))

    def fused(a):
        return cm.fcm_reduce_scatter(a, ("data",), 0, bits=8, block=16)

    def modular(a):
        return lb.quantized_psum_scatter(a, ("data",), 0, bits=8,
                                         block=16)

    of = _sm(fused, mesh, P("data"), P("data"))(x)
    om = _sm(modular, mesh, P("data"), P("data"))(x)
    assert (np.asarray(of) == np.asarray(om)).all()


def test_fcm_multi_axis_gather_matches_joint():
    """Nested per-axis rings reproduce the joint tiled all_gather's
    axis-major index order (the modular path gathers both axes in one
    collective)."""
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("data", "expert"))
    x = jax.random.normal(jax.random.PRNGKey(4), (16, 6))

    def fused(a):
        return cm.fcm_all_gather(a, ("data", "expert"), 0, 8, 0, 8)

    def modular(a):
        return lb.low_bandwidth_all_gather(a, ("data", "expert"), 0,
                                           8, 0, 8)

    spec = P(("data", "expert"))
    of = _sm(fused, mesh, spec, spec)(x)
    om = _sm(modular, mesh, spec, spec)(x)
    assert (np.asarray(of) == np.asarray(om)).all()


# --------------------------------------------------------------------- #
# error feedback
# --------------------------------------------------------------------- #
def test_error_feedback_round_trip_six_steps():
    """The fused qgZ scatter carries the identical error-feedback
    residual as the modular variant: over 6 steps of a persistent
    signal, reduced chunks AND error buffers stay bitwise-equal, and
    the accumulated mean converges on the exact value (the telescoping
    argument both implementations share)."""
    mesh = _mesh()
    world = 4
    signal = jax.random.normal(jax.random.PRNGKey(5), (world, 16, 8))

    def one(fn, a, e):
        r, ne = fn(a[0], e[0], "data", 0, 4, 8)
        return r[None], ne[None]

    run_f = _sm(lambda a, e: one(cm.fcm_qgz_reduce_scatter_inner, a, e),
                mesh, (P("data"), P("data")), (P("data"), P("data")))
    run_m = _sm(lambda a, e: one(lb.qgz_reduce_scatter_inner, a, e),
                mesh, (P("data"), P("data")), (P("data"), P("data")))

    ef = em = jnp.zeros_like(signal)
    acc_f = None
    for step in range(6):
        rf, ef = run_f(signal, ef)
        rm, em = run_m(signal, em)
        assert (np.asarray(rf) == np.asarray(rm)).all(), f"step {step}"
        assert (np.asarray(ef) == np.asarray(em)).all(), f"step {step}"
        acc_f = rf if acc_f is None else acc_f + rf
    # persistent-signal convergence: the 6-step average of the int4
    # quantized reduction approaches the exact sum far beyond one
    # step's quantization error
    exact = jnp.stack([signal[:, 4 * p:4 * (p + 1)].sum(0)[None]
                       for p in range(world)])[:, 0]
    exact = exact.reshape(acc_f.shape)
    err6 = float(jnp.max(jnp.abs(acc_f / 6 - exact)))
    r1, _ = run_f(signal, jnp.zeros_like(signal))
    err1 = float(jnp.max(jnp.abs(r1 - exact)))
    assert err6 < err1 / 2, (err6, err1)


# --------------------------------------------------------------------- #
# GEMM-fused kernels (layer 1), interpret mode
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("qwz", [8, 4, 0])
def test_fused_allgather_matmul_matches_reference(qwz):
    """y = x @ dequant(all_gather(w)): the ring-fused kernel against
    the unfused quantize -> gather -> dequant -> matmul reference
    (qwz=0: native-width tiles ride the ring, no dequant)."""
    mesh = _mesh()
    W, M, K, N = 4, 8, 32, 16
    x = jax.random.normal(jax.random.PRNGKey(6), (M, K))
    w = jax.random.normal(jax.random.PRNGKey(7), (W, K // W, N)) / 4

    def fused(xr, wr):
        return cm.fused_allgather_matmul(xr, wr[0], "data", qwz, 0, 8,
                                         True)[None]

    y = _sm(fused, mesh, (P(), P("data")), P("data"))(x, w)
    if qwz:
        wq = jnp.concatenate([
            lb.blockwise_dequantize(*lb.blockwise_quantize(
                w[i], dim=0, bits=qwz, block=8), w[i].shape, dim=0,
                bits=qwz)
            for i in range(W)], axis=0)
    else:
        wq = w.reshape(K, N)
    np.testing.assert_allclose(y[0], x @ wq, rtol=1e-5, atol=1e-5)


def test_fused_allgather_matmul_grads():
    """The fused custom_vjp: dx re-rings the quantized shards through
    the transposed tile GEMM; dW is the fused matmul-reduce-scatter
    epilogue (straight-through quantizer at qgz_bits=0)."""
    mesh = _mesh()
    W, M, K, N = 4, 8, 32, 16
    x = jax.random.normal(jax.random.PRNGKey(8), (M, K))
    w = jax.random.normal(jax.random.PRNGKey(9), (W, K // W, N)) / 4

    def loss(xr, wr):
        return jnp.sum(cm.fused_allgather_matmul(
            xr, wr[0], "data", 8, 0, 8, True) ** 2)

    gx, gw = _sm(jax.grad(loss, argnums=(0, 1)), mesh,
                 (P(), P("data")), (P(), P("data")))(x, w)
    wq = jnp.concatenate([
        lb.blockwise_dequantize(*lb.blockwise_quantize(
            w[i], dim=0, bits=8, block=8), w[i].shape, dim=0)
        for i in range(W)], axis=0)
    rx, rw = jax.grad(lambda a, b: jnp.sum((a @ b) ** 2),
                      argnums=(0, 1))(x, wq)
    # dx is computed per shard-region replica (x enters replicated)
    np.testing.assert_allclose(gx, rx, rtol=1e-4, atol=1e-4)
    # dW: every replica contributed the same x^T@dy, reduce-scattered —
    # chunk p = W * rows p of the reference grad
    np.testing.assert_allclose(gw.reshape(K, N), W * rw,
                               rtol=1e-3, atol=1e-3)


def test_fused_matmul_reduce_scatter_with_error_feedback():
    """dW = lhs^T @ rhs reduce-scattered per tile, error residual
    intact: new_error == compensated - deq(quant(compensated))."""
    mesh = _mesh()
    W, B, K, N = 4, 16, 32, 12
    lhs = jax.random.normal(jax.random.PRNGKey(10), (B, K))
    rhs = jax.random.normal(jax.random.PRNGKey(11), (B, N))
    err0 = jnp.zeros((K, N))

    def fused(lhs, r, e):
        c, ne = cm.fused_matmul_reduce_scatter(lhs, r, e[0], "data", 8,
                                               16, True)
        return c[None], ne[None]

    chunk, new_err = _sm(fused, mesh, (P(), P(), P("data")),
                         (P("data"), P("data")))(
        lhs, rhs, jnp.broadcast_to(err0, (W,) + err0.shape))
    dw = np.asarray(lhs.T @ rhs)
    tab = dw.reshape(W, K // W, N)
    q, s = lb.blockwise_quantize(jnp.asarray(tab), dim=0, bits=8,
                                 block=16)
    deq = lb.blockwise_dequantize(q, s, tab.shape, dim=0)
    # all W replicas send identical tiles: chunk p sums W copies
    np.testing.assert_allclose(chunk[0], W * np.asarray(deq)[0],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        new_err[0], dw - np.asarray(deq).reshape(K, N),
        rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------- #
# grad flow through the fused custom_vjp under the carried scan
# --------------------------------------------------------------------- #
_FCM_LB = {"low_bandwidth": {"qwz_bits": 8, "qgz_bits": 8,
                             "fused_collective_matmul": True}}
_MOD_LB = {"low_bandwidth": {"qwz_bits": 8, "qgz_bits": 8}}


def test_fcm_carried_scan_training_parity():
    """End-to-end: the carried streamed engine with fused transports
    trains identically to the modular qwZ/qgZ engine — same
    quantization, same accumulation contract, grads flow through the
    fused custom_vjp inside the hand-written carried VJP's forward AND
    backward re-gather sweeps."""
    l_mod, p_mod, _ = _train_tiny(_mode_cfg("carried", _MOD_LB))
    l_fcm, p_fcm, plan = _train_tiny(_mode_cfg("carried", _FCM_LB))
    assert plan.mode == "carried" and plan.prefetch
    np.testing.assert_allclose(l_fcm, l_mod, rtol=1e-6)
    # wide leaves are bitwise (qwZ gather + qgZ shard-order scatter);
    # skinny leaves (biases/LN) fall back dense in BOTH modes but reduce
    # through psum_scatter (modular) vs the fixed-order fp32 table
    # (fused) — fp reassociation at the 1e-7 scale, nothing structural
    for a, b in zip(jax.tree.leaves(p_fcm), jax.tree.leaves(p_mod)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=5e-6)
    assert l_fcm[-1] < l_fcm[0]  # still actually training


def test_fcm_at_use_mode_training_parity():
    """fcm composes with prefetch off (at-use gathers through the scan
    VJP, exercising fcm_all_gather's own custom_vjp under lax.scan
    differentiation)."""
    l_mod, p_mod, _ = _train_tiny(_mode_cfg("off", _MOD_LB))
    l_fcm, p_fcm, plan = _train_tiny(_mode_cfg("off", _FCM_LB))
    assert plan.mode == "off"
    np.testing.assert_allclose(l_fcm, l_mod, rtol=1e-6)
    # same skinny-leaf dense-fallback reassociation note as the carried
    # parity above
    for a, b in zip(jax.tree.leaves(p_fcm), jax.tree.leaves(p_mod)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=5e-6)


# --------------------------------------------------------------------- #
# Schedule Auditor classification
# --------------------------------------------------------------------- #
def _fcm_engine():
    ds.reset_mesh_context()
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    mesh = ds.initialize_mesh(data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=4, num_heads=4, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    conf = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": _mode_cfg("carried", _FCM_LB),
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh)
    return engine


def test_auditor_classifies_fcm_transports_fused_hidden():
    """ISSUE 13 acceptance: on the fused streamed config, every
    hot-loop qwZ/qgZ wire-mover classifies fused/hidden — zero
    serialized hot-loop collectives, zero exposed hot-loop wire bytes
    (the exposed-comm lane's hot-loop share is 0), and the fused bytes
    price into the hidden-comm lane.  No new host_sync or lockstep
    findings ride along."""
    from deepspeed_tpu.analysis import audit_engine
    engine = _fcm_engine()
    try:
        report = audit_engine(engine, multihost=False)
        ov = report.overlap
        assert ov["n_fused"] > 0
        assert ov["n_serialized_hot_loop"] == 0
        fused_recs = [r for r in ov["records"] if r["fused"]]
        assert fused_recs and all(r["hidden_fraction"] == 1.0
                                  and not r["serialized"]
                                  for r in fused_recs)
        assert all(r["prim"] == "ppermute" for r in fused_recs)
        exposed_hot = sum(
            r["wire_bytes"] * r["mult"] * (1.0 - r["hidden_fraction"])
            for r in ov["records"] if r["loop_depth"] > 0)
        assert exposed_hot == 0
        assert report.step_time["wire_bytes_fused"] > 0
        # the fused wire rides the hidden lane in the lower bound
        assert (report.step_time["wire_bytes_hidden"]
                >= report.step_time["wire_bytes_fused"])
        # zero new host_sync / lockstep findings on the fused program
        assert [f for f in report.findings
                if f.rule in ("host_sync", "lockstep")] == []
        # require_overlap strict posture stays green
        from deepspeed_tpu.config import AnalysisConfig
        from deepspeed_tpu.analysis import ProgramAuditor
        from deepspeed_tpu.analysis.auditor import engine_targets
        strict = AnalysisConfig.from_dict(
            {"mode": "warn", "require_overlap": True})
        strict_report = ProgramAuditor(strict).run(
            engine_targets(engine),
            gas=engine.gradient_accumulation_steps())
        assert [f for f in strict_report.findings
                if f.rule == "overlap"] == []
    finally:
        ds.reset_mesh_context()


def test_fcm_wire_accounted_not_zero():
    """The fused ring hops are ACCOUNTED (step_wire_bytes counts
    FCM-scoped ppermutes; collective_wire_bytes reports them under
    fcm_bytes) — a fused config must not report zero wire."""
    mesh = _mesh()
    x = jnp.ones((8, 24), jnp.float32)

    def fused(a):
        return cm.fcm_all_gather(a, ("data",), 0, 8, 0, 16)

    jx = jax.make_jaxpr(
        jax.shard_map(fused, mesh=mesh, in_specs=P("data"),
                      out_specs=P("data"), check_vma=False))(x)
    from deepspeed_tpu.analysis.rules import step_wire_bytes
    total, contributors = step_wire_bytes(jx)
    assert total > 0
    assert any("ppermute" in name for name, _ in contributors)
    wire = lb.collective_wire_bytes(jx)
    assert wire["fcm_bytes"] > 0
    assert wire["gather_bytes"] == 0  # no monolithic gather remains

    # a generic (non-fcm) ppermute stays lockstep-only — unchanged,
    # and a USER scope that merely CONTAINS the marker as a prefix must
    # not hijack the fused classification (component matching, not
    # substring: scope_has_component)
    def plain(a):
        world = 4
        perm = [(i, (i + 1) % world) for i in range(world)]
        with jax.named_scope("fcm_fused_block"):
            return lax.ppermute(a, "data", perm)

    jx2 = jax.make_jaxpr(
        jax.shard_map(plain, mesh=mesh, in_specs=P("data"),
                      out_specs=P("data"), check_vma=False))(x)
    assert step_wire_bytes(jx2)[0] == 0
    assert lb.collective_wire_bytes(jx2)["fcm_bytes"] == 0
    from deepspeed_tpu.analysis import analyze_overlap
    from deepspeed_tpu.config import AnalysisConfig
    recs = analyze_overlap(jx2, AnalysisConfig.from_dict({"mode": "warn"}))
    assert all(not r.fused for r in recs)


# --------------------------------------------------------------------- #
# config validation
# --------------------------------------------------------------------- #
def test_fcm_config_validation():
    from deepspeed_tpu.config import (DeepSpeedConfigError,
                                      ZeroLowBandwidthConfig)
    cfg = ZeroLowBandwidthConfig.from_dict(
        {"fused_collective_matmul": True})
    assert cfg.fused_collective_matmul is True
    # fcm alone engages the low-bandwidth context (native-width rings)
    assert cfg.enabled
    assert not ZeroLowBandwidthConfig.from_dict({}).fused_collective_matmul
    assert not ZeroLowBandwidthConfig.from_dict({}).enabled
    with pytest.raises(DeepSpeedConfigError,
                       match="fused_collective_matmul"):
        ZeroLowBandwidthConfig.from_dict(
            {"fused_collective_matmul": "yes"})
    # constants single-source the knob and the scope marker
    assert C.LOW_BANDWIDTH_FCM == "fused_collective_matmul"
    assert cm.FCM_SCOPE == C.FCM_SCOPE


def test_fcm_autotuning_axis_config():
    from deepspeed_tpu.config import AutotuningConfig
    cfg = AutotuningConfig.from_dict(
        {"chips": 8, "fused_collective_matmul": [False, True]})
    assert cfg.fused_collective_matmul == (False, True)
    assert AutotuningConfig.from_dict(
        {"chips": 8}).fused_collective_matmul == (False,)


def test_fcm_reduce_scatter_rejects_indivisible_dim():
    mesh = _mesh()
    x = jnp.ones((6, 4), jnp.float32)  # 6 rows over a 4-way axis

    def bad(a):
        return cm.fcm_reduce_scatter(a, ("data",), 0, bits=8, block=16)

    with pytest.raises(ValueError, match="divisible"):
        _sm(bad, mesh, P("data"), P("data"))(x)
