"""Inference engine tests: KV-cache decode equivalence, HF module
injection parity (the role of test_cuda_forward.py:333's kernel-vs-HF
checks), int8 quantization, and tensor-parallel serving."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context


def _tiny_gpt2(bf16=False, **kw):
    defaults = dict(vocab_size=128, n_positions=64, hidden_size=32,
                    num_layers=2, num_heads=4, bf16=bf16, embd_dropout=0.0,
                    attn_dropout=0.0, hidden_dropout=0.0)
    defaults.update(kw)
    cfg = GPT2Config(**defaults)
    return cfg, GPT2Model(cfg)


@pytest.fixture
def dp_mesh():
    reset_mesh_context()
    yield initialize_mesh(data=-1)
    reset_mesh_context()


def test_generate_matches_full_recompute(dp_mesh):
    """Greedy KV-cache decode must equal argmax over full re-forward."""
    cfg, model = _tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)

    prompt = np.array([[5, 9, 23, 40], [7, 7, 100, 2]], np.int32)
    out = np.asarray(eng.generate(prompt, max_new_tokens=8))

    # naive reference: recompute the full sequence each step
    ids = prompt.copy()
    ref = []
    for _ in range(8):
        logits = np.asarray(model.logits(params, jnp.asarray(ids),
                                         deterministic=True))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        ref.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    ref = np.stack(ref, axis=1)
    np.testing.assert_array_equal(out, ref)


def test_generate_sampled_shapes(dp_mesh):
    cfg, model = _tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)
    out = eng.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=5,
                       temperature=1.0, rng=jax.random.PRNGKey(7))
    assert out.shape == (1, 5)
    assert (np.asarray(out) < cfg.vocab_size).all()


def test_hf_gpt2_injection_parity(dp_mesh):
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    hf_cfg = HFConfig(vocab_size=96, n_positions=32, n_embd=48, n_layer=2,
                      n_head=4, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()

    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)
    ids = np.array([[3, 17, 60, 2, 9]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(eng.forward(jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_hf_bert_injection_parity(dp_mesh):
    torch = pytest.importorskip("torch")
    from transformers import BertConfig as HFConfig, BertModel as HFBert

    hf_cfg = HFConfig(vocab_size=80, hidden_size=48, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=96,
                      max_position_embeddings=32, type_vocab_size=2,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0,
                      hidden_act="gelu_new")
    torch.manual_seed(0)
    hf = HFBert(hf_cfg).eval()

    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)
    ids = np.array([[2, 9, 33, 70, 1, 0]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).last_hidden_state.numpy()
    # HF applies token_type_embeddings[0] by default; ours is opt-in
    got = np.asarray(eng.forward(
        jnp.asarray(ids, jnp.int32),
        token_type_ids=jnp.zeros((1, ids.shape[1]), jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_hf_gptneo_injection_parity(dp_mesh):
    """GPT-Neo does NOT scale attention scores — injection must compensate
    for our always-scaled flash attention."""
    torch = pytest.importorskip("torch")
    from transformers import GPTNeoConfig, GPTNeoForCausalLM

    hf_cfg = GPTNeoConfig(vocab_size=96, max_position_embeddings=32,
                          hidden_size=48, num_layers=2, num_heads=4,
                          attention_types=[[["global"], 2]],
                          resid_dropout=0.0, embed_dropout=0.0,
                          attention_dropout=0.0)
    torch.manual_seed(0)
    hf = GPTNeoForCausalLM(hf_cfg).eval()

    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)
    ids = np.array([[3, 17, 60, 2, 9]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(eng.forward(jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_generate_rejects_overflow_positions(dp_mesh):
    cfg, model = _tiny_gpt2(n_positions=16)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)
    with pytest.raises(ValueError, match="n_positions"):
        eng.generate(np.zeros((1, 10), np.int32), max_new_tokens=10)


def test_int8_quantization(dp_mesh):
    cfg, model = _tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    fp = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)
    q8 = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh,
                           quantization_setting=4)
    from deepspeed_tpu.ops.quant import QuantizedWeight
    assert isinstance(q8.params["h"]["attn_qkvw"], QuantizedWeight)
    assert q8.params["h"]["attn_qkvw"].qweight.dtype == jnp.int8

    ids = jnp.asarray([[5, 9, 23, 40]], jnp.int32)
    lf = np.asarray(fp.forward(ids))
    lq = np.asarray(q8.forward(ids))
    # int8 is lossy; logits stay close and top-1 usually agrees
    rel = np.abs(lf - lq).max() / np.abs(lf).max()
    assert rel < 0.05, f"int8 relative error too large: {rel}"
    out = q8.generate(np.array([[5, 9]], np.int32), max_new_tokens=4)
    assert out.shape == (1, 4)


def test_tensor_parallel_inference_matches():
    reset_mesh_context()
    cfg, model = _tiny_gpt2(hidden_size=64)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray([[5, 9, 23, 40]], jnp.int32)

    ctx1 = initialize_mesh(data=-1)
    ref = np.asarray(ds.init_inference(
        model, model_parameters=params, mesh=ctx1.mesh).forward(ids))

    reset_mesh_context()
    ctx2 = initialize_mesh(data=-1, model=2)
    eng = ds.init_inference(model, model_parameters=params, mesh=ctx2.mesh,
                            mp_size=2)
    assert eng.mp_world_size == 2
    got = np.asarray(eng.forward(ids))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # TP really sharded the qkv weight over the model axis
    qkvw = eng.params["h"]["attn_qkvw"]
    assert len(qkvw.sharding.device_set) == 8
    reset_mesh_context()


def test_hf_checkpoint_loader_path_greedy_decode_parity(tmp_path, dp_mesh):
    """End-to-end checkpoint injection (VERDICT round-2 #8): GPT-2 weights
    written to a safetensors checkpoint on disk, loaded back through the
    REAL HF loader path (from_pretrained), injected via
    replace_transformer_layer, then GREEDY-DECODE token parity vs the
    source torch model — no network (reference analog:
    module_inject/replace_module.py:89 exercised against real HF models)."""
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    hf_cfg = HFConfig(vocab_size=96, n_positions=48, n_embd=48, n_layer=3,
                      n_head=4, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    torch.manual_seed(0)
    src = GPT2LMHeadModel(hf_cfg).eval()
    ckpt_dir = tmp_path / "gpt2_ckpt"
    src.save_pretrained(ckpt_dir, safe_serialization=True)
    assert (ckpt_dir / "model.safetensors").exists()
    del src

    hf = GPT2LMHeadModel.from_pretrained(ckpt_dir).eval()  # real loader
    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)

    prompt = np.array([[3, 17, 60, 2], [9, 9, 41, 80]], np.int64)
    gen = 10
    out = np.asarray(eng.generate(prompt.astype(np.int32),
                                  max_new_tokens=gen))

    ids = torch.tensor(prompt)
    ref = []
    with torch.no_grad():
        for _ in range(gen):
            nxt = hf(ids).logits[:, -1, :].argmax(-1)
            ref.append(nxt.numpy().astype(np.int32))
            ids = torch.cat([ids, nxt[:, None]], dim=1)
    np.testing.assert_array_equal(out, np.stack(ref, axis=1))


@pytest.mark.parametrize("version", ["v1", "v2"])
def test_megatron_layer_policy_parity(dp_mesh, version):
    """MegatronLayerPolicy (reference: replace_policy.py:146): a
    Megatron-shaped ParallelTransformerLayer (nn.Linear projections,
    input/post_attention layernorms) carrying the SAME weights as an HF
    GPT-2 must inject to identical logits — the HF model is the known-good
    reference for the mapping.  v1 = old source (.attention, qkv stacked
    q/k/v-contiguous [3H, H]); v2 = new source (.self_attention, qkv
    INTERLEAVED per head [heads, 3, head_dim] over rows) — the policy must
    de-interleave v2 back to contiguous."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn
    from types import SimpleNamespace
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel
    from deepspeed_tpu.module_inject.replace_policy import (
        MegatronLayerPolicy)

    H, heads = 48, 4
    hf_cfg = HFConfig(vocab_size=96, n_positions=32, n_embd=H, n_layer=2,
                      n_head=heads, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()

    class Attn(nn.Module):
        def __init__(self):
            super().__init__()
            self.query_key_value = nn.Linear(H, 3 * H)
            self.dense = nn.Linear(H, H)
            self.num_attention_heads = heads

    class MLP(nn.Module):
        def __init__(self):
            super().__init__()
            self.dense_h_to_4h = nn.Linear(H, 4 * H)
            self.dense_4h_to_h = nn.Linear(4 * H, H)

    class ParallelTransformerLayer(nn.Module):
        def __init__(self):
            super().__init__()
            self.input_layernorm = nn.LayerNorm(H)
            self.post_attention_layernorm = nn.LayerNorm(H)
            if version == "v1":
                self.attention = Attn()
            else:  # new Megatron source: renamed block, interleaved qkv
                self.self_attention = Attn()
            self.mlp = MLP()

        @property
        def attn_block(self):
            return getattr(self, "attention", None) or self.self_attention

    def to_megatron_qkv(contiguous):
        """q/k/v-contiguous rows [3, heads, hd] -> stored layout."""
        if version == "v1":
            return contiguous
        rows = contiguous.shape[0]
        hd = rows // (3 * heads)
        rest = contiguous.shape[1:]
        return (contiguous.reshape(3, heads, hd, *rest)
                .swapaxes(0, 1).reshape(rows, *rest))

    class MegatronGPT(nn.Module):
        """Layer stack in Megatron shape; embedding surface in GPT-2 shape
        (the policy maps LAYERS — reference swaps layers in place and
        leaves embeddings to the host model)."""

        def __init__(self):
            super().__init__()
            self.wte = nn.Embedding(hf_cfg.vocab_size, H)
            self.wpe = nn.Embedding(hf_cfg.n_positions, H)
            self.layers = nn.ModuleList(
                [ParallelTransformerLayer() for _ in range(hf_cfg.n_layer)])
            self.ln_f = nn.LayerNorm(H)
            self.config = SimpleNamespace(n_head=heads,
                                          layer_norm_epsilon=1e-5)

    mg = MegatronGPT().eval()
    with torch.no_grad():
        base = hf.transformer
        mg.wte.weight.copy_(base.wte.weight)
        mg.wpe.weight.copy_(base.wpe.weight)
        mg.ln_f.weight.copy_(base.ln_f.weight)
        mg.ln_f.bias.copy_(base.ln_f.bias)
        for ml, hl in zip(mg.layers, base.h):
            att = ml.attn_block
            # HF Conv1D stores [in, out]; Megatron nn.Linear stores
            # [out, in] — transpose when copying (+ per-head interleave
            # for the v2 layout)
            att.query_key_value.weight.copy_(torch.from_numpy(
                to_megatron_qkv(hl.attn.c_attn.weight.T.numpy())))
            att.query_key_value.bias.copy_(torch.from_numpy(
                to_megatron_qkv(hl.attn.c_attn.bias.numpy())))
            att.dense.weight.copy_(hl.attn.c_proj.weight.T)
            att.dense.bias.copy_(hl.attn.c_proj.bias)
            ml.input_layernorm.weight.copy_(hl.ln_1.weight)
            ml.input_layernorm.bias.copy_(hl.ln_1.bias)
            ml.post_attention_layernorm.weight.copy_(hl.ln_2.weight)
            ml.post_attention_layernorm.bias.copy_(hl.ln_2.bias)
            ml.mlp.dense_h_to_4h.weight.copy_(hl.mlp.c_fc.weight.T)
            ml.mlp.dense_h_to_4h.bias.copy_(hl.mlp.c_fc.bias)
            ml.mlp.dense_4h_to_h.weight.copy_(hl.mlp.c_proj.weight.T)
            ml.mlp.dense_4h_to_h.bias.copy_(hl.mlp.c_proj.bias)

    eng = ds.init_inference(mg, dtype=jnp.float32, mesh=dp_mesh.mesh,
                            injection_policy=MegatronLayerPolicy)
    ids = np.array([[3, 17, 60, 2, 9]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(eng.forward(jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
