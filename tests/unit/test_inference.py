"""Inference engine tests: KV-cache decode equivalence, HF module
injection parity (the role of test_cuda_forward.py:333's kernel-vs-HF
checks), int8 quantization, and tensor-parallel serving."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context


def _tiny_gpt2(bf16=False, **kw):
    defaults = dict(vocab_size=128, n_positions=64, hidden_size=32,
                    num_layers=2, num_heads=4, bf16=bf16, embd_dropout=0.0,
                    attn_dropout=0.0, hidden_dropout=0.0)
    defaults.update(kw)
    cfg = GPT2Config(**defaults)
    return cfg, GPT2Model(cfg)


@pytest.fixture
def dp_mesh():
    reset_mesh_context()
    yield initialize_mesh(data=-1)
    reset_mesh_context()


def test_generate_matches_full_recompute(dp_mesh):
    """Greedy KV-cache decode must equal argmax over full re-forward."""
    cfg, model = _tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)

    prompt = np.array([[5, 9, 23, 40], [7, 7, 100, 2]], np.int32)
    out = np.asarray(eng.generate(prompt, max_new_tokens=8))

    # naive reference: recompute the full sequence each step
    ids = prompt.copy()
    ref = []
    for _ in range(8):
        logits = np.asarray(model.logits(params, jnp.asarray(ids),
                                         deterministic=True))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        ref.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    ref = np.stack(ref, axis=1)
    np.testing.assert_array_equal(out, ref)


def test_generate_sampled_shapes(dp_mesh):
    cfg, model = _tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)
    out = eng.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=5,
                       temperature=1.0, rng=jax.random.PRNGKey(7))
    assert out.shape == (1, 5)
    assert (np.asarray(out) < cfg.vocab_size).all()


def test_hf_gpt2_injection_parity(dp_mesh):
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    hf_cfg = HFConfig(vocab_size=96, n_positions=32, n_embd=48, n_layer=2,
                      n_head=4, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()

    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)
    ids = np.array([[3, 17, 60, 2, 9]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(eng.forward(jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_hf_bert_injection_parity(dp_mesh):
    torch = pytest.importorskip("torch")
    from transformers import BertConfig as HFConfig, BertModel as HFBert

    hf_cfg = HFConfig(vocab_size=80, hidden_size=48, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=96,
                      max_position_embeddings=32, type_vocab_size=2,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0,
                      hidden_act="gelu_new")
    torch.manual_seed(0)
    hf = HFBert(hf_cfg).eval()

    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)
    ids = np.array([[2, 9, 33, 70, 1, 0]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).last_hidden_state.numpy()
    # HF applies token_type_embeddings[0] by default; ours is opt-in
    got = np.asarray(eng.forward(
        jnp.asarray(ids, jnp.int32),
        token_type_ids=jnp.zeros((1, ids.shape[1]), jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_hf_gptneo_injection_parity(dp_mesh):
    """GPT-Neo does NOT scale attention scores — injection must compensate
    for our always-scaled flash attention."""
    torch = pytest.importorskip("torch")
    from transformers import GPTNeoConfig, GPTNeoForCausalLM

    hf_cfg = GPTNeoConfig(vocab_size=96, max_position_embeddings=32,
                          hidden_size=48, num_layers=2, num_heads=4,
                          attention_types=[[["global"], 2]],
                          resid_dropout=0.0, embed_dropout=0.0,
                          attention_dropout=0.0)
    torch.manual_seed(0)
    hf = GPTNeoForCausalLM(hf_cfg).eval()

    eng = ds.init_inference(hf, dtype=jnp.float32, mesh=dp_mesh.mesh)
    ids = np.array([[3, 17, 60, 2, 9]], np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(eng.forward(jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_generate_rejects_overflow_positions(dp_mesh):
    cfg, model = _tiny_gpt2(n_positions=16)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)
    with pytest.raises(ValueError, match="n_positions"):
        eng.generate(np.zeros((1, 10), np.int32), max_new_tokens=10)


def test_int8_quantization(dp_mesh):
    cfg, model = _tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    fp = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh)
    q8 = ds.init_inference(model, model_parameters=params, mesh=dp_mesh.mesh,
                           quantization_setting=4)
    from deepspeed_tpu.ops.transformer_inference import QuantizedWeight
    assert isinstance(q8.params["h"]["attn_qkvw"], QuantizedWeight)
    assert q8.params["h"]["attn_qkvw"].qweight.dtype == jnp.int8

    ids = jnp.asarray([[5, 9, 23, 40]], jnp.int32)
    lf = np.asarray(fp.forward(ids))
    lq = np.asarray(q8.forward(ids))
    # int8 is lossy; logits stay close and top-1 usually agrees
    rel = np.abs(lf - lq).max() / np.abs(lf).max()
    assert rel < 0.05, f"int8 relative error too large: {rel}"
    out = q8.generate(np.array([[5, 9]], np.int32), max_new_tokens=4)
    assert out.shape == (1, 4)


def test_tensor_parallel_inference_matches():
    reset_mesh_context()
    cfg, model = _tiny_gpt2(hidden_size=64)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray([[5, 9, 23, 40]], jnp.int32)

    ctx1 = initialize_mesh(data=-1)
    ref = np.asarray(ds.init_inference(
        model, model_parameters=params, mesh=ctx1.mesh).forward(ids))

    reset_mesh_context()
    ctx2 = initialize_mesh(data=-1, model=2)
    eng = ds.init_inference(model, model_parameters=params, mesh=ctx2.mesh,
                            mp_size=2)
    assert eng.mp_world_size == 2
    got = np.asarray(eng.forward(ids))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # TP really sharded the qkv weight over the model axis
    qkvw = eng.params["h"]["attn_qkvw"]
    assert len(qkvw.sharding.device_set) == 8
    reset_mesh_context()
