"""Repo-source static lint as a fast-lane test (ISSUE 14 satellite).

The authoritative linter is ruff, configured in pyproject.toml
([tool.ruff]) and run as its own tier1.yml step so lint failures never
mask test failures.  This test is the in-suite twin: when ruff is
installed it runs the real thing; otherwise it falls back to an
AST-based subset covering the same rule families (F401 unused imports,
F632 is-literal, E711/E712 None/bool comparisons, E713/E714 membership/
identity negation, E722 bare except, E741 ambiguous single-letter
names, F841 unused locals) so the fast lane still fails on a
regression instead of silently skipping — the container this repo
develops in does not ship ruff.
"""

import ast
import re
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
LINT_PATHS = ("deepspeed_tpu", "tests", "bench.py")
# mirrors [tool.ruff.lint.per-file-ignores]: __init__ re-export surfaces
F401_EXEMPT = "__init__.py"


def _iter_sources():
    for root in LINT_PATHS:
        p = REPO / root
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts or "build" in f.parts:
                continue
            yield f


def _unused_imports(tree):
    """F401 subset: module-wide unused import names.  Conservative on
    purpose — a name appearing in ANY Name node or string constant
    (string annotations, doctests, __all__) counts as used."""
    imported = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    mod = f"{node.module}.{a.name}" if node.module else a.name
                    imported[a.asname or a.name] = (node.lineno, mod)
    if not imported:
        return []
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            for n in imported:
                if n in node.value:
                    used.add(n)
    return [(lineno, f"F401 `{mod}` imported as `{name}` but unused")
            for name, (lineno, mod) in sorted(imported.items(),
                                              key=lambda kv: kv[1][0])
            if name not in used]


def _comparison_findings(tree):
    """E711/E712 (== / != against None, True, False), E713/E714
    (`not x in y` / `not x is y`), F632 (`is` against a literal)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(
                        comp, ast.Constant) and (
                        comp.value is None or comp.value is True
                        or comp.value is False):
                    code = "E711" if comp.value is None else "E712"
                    out.append((node.lineno,
                                f"{code} comparison to {comp.value!r} "
                                "with ==/!= (use `is`)"))
                if isinstance(op, (ast.Is, ast.IsNot)) and isinstance(
                        comp, (ast.Constant,)) and isinstance(
                        comp.value, (str, int, float, bytes, tuple)) \
                        and comp.value is not None \
                        and not isinstance(comp.value, bool):
                    out.append((node.lineno,
                                "F632 `is` comparison against a literal"))
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not) \
                and isinstance(node.operand, ast.Compare) \
                and len(node.operand.ops) == 1:
            inner = node.operand.ops[0]
            if isinstance(inner, ast.In):
                out.append((node.lineno,
                            "E713 `not x in y` (use `x not in y`)"))
            elif isinstance(inner, ast.Is):
                out.append((node.lineno,
                            "E714 `not x is y` (use `x is not y`)"))
    return out


def _bare_excepts(tree):
    return [(h.lineno, "E722 bare `except:`")
            for node in ast.walk(tree) if isinstance(node, ast.Try)
            for h in node.handlers if h.type is None]


_AMBIGUOUS = {"l", "O", "I"}


def _ambiguous_names(tree):
    """E741 subset: `l`/`O`/`I` bound as a variable, parameter, or
    exception name (including inside comprehensions and f-strings,
    which the ast sees even where tokenize does not)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in _AMBIGUOUS \
                and isinstance(node.ctx, ast.Store):
            out.append((node.lineno,
                        f"E741 ambiguous variable name `{node.id}`"))
        elif isinstance(node, ast.arg) and node.arg in _AMBIGUOUS:
            out.append((node.lineno,
                        f"E741 ambiguous parameter name `{node.arg}`"))
        elif isinstance(node, ast.ExceptHandler) \
                and node.name in _AMBIGUOUS:
            out.append((node.lineno,
                        f"E741 ambiguous exception name `{node.name}`"))
    return out


def _unused_locals(tree):
    """F841 subset: a simple `name = ...` statement inside a function
    whose name is never loaded anywhere in that function.  Conservative
    on purpose: skips underscore-prefixed names, tuple unpacking,
    augmented assigns, class bodies, and any function using
    locals()/exec/eval."""
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        class_lines = set()
        escape_hatch = False
        for node in ast.walk(fn):
            if isinstance(node, ast.ClassDef):
                for inner in ast.walk(node):
                    if hasattr(inner, "lineno"):
                        class_lines.add(inner.lineno)
            elif isinstance(node, ast.Name) \
                    and node.id in ("locals", "vars", "exec", "eval"):
                escape_hatch = True
        if escape_hatch:
            continue
        assigned = {}
        loaded = set()
        strings = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.lineno not in class_lines:
                name = node.targets[0].id
                if not name.startswith("_"):
                    assigned.setdefault(name, node.lineno)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                loaded.add(node.id)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                loaded.update(node.names)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                strings.append(node.value)
        loaded.update(n for n in assigned
                      if any(n in s for s in strings))
        out.extend((lineno, f"F841 local `{name}` assigned but unused")
                   for name, lineno in sorted(assigned.items(),
                                              key=lambda kv: kv[1])
                   if name not in loaded)
    return out


_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?",
                      re.IGNORECASE)


def _noqa_suppressed(line, code):
    """Mirror ruff's noqa semantics: bare `# noqa` kills every code on
    the line, `# noqa: F401,E402` only the listed ones."""
    m = _NOQA_RE.search(line)
    if not m:
        return False
    codes = m.group("codes")
    return codes is None or code in re.split(r"[,\s]+", codes.strip())


def _fallback_lint():
    findings = []
    for f in _iter_sources():
        text = f.read_text()
        tree = ast.parse(text, filename=str(f))
        src_lines = text.splitlines()
        rel = f.relative_to(REPO)
        hits = (_comparison_findings(tree) + _bare_excepts(tree)
                + _ambiguous_names(tree) + _unused_locals(tree))
        if f.name != F401_EXEMPT:
            hits += _unused_imports(tree)
        findings.extend(
            f"{rel}:{lineno}: {msg}" for lineno, msg in hits
            if not _noqa_suppressed(src_lines[lineno - 1],
                                    msg.split()[0]))
    return findings


def test_repo_sources_lint_clean():
    if shutil.which("ruff"):
        out = subprocess.run(
            ["ruff", "check", *LINT_PATHS], cwd=str(REPO),
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, (
            "ruff check failed:\n" + out.stdout + out.stderr)
        return
    findings = _fallback_lint()
    assert findings == [], (
        "repo-source lint (AST fallback for the pyproject [tool.ruff] "
        "set) found:\n  " + "\n  ".join(findings))


def test_fallback_linter_detects_each_rule(tmp_path):
    """The fallback must actually catch what it claims — one fixture
    per rule family, so a refactor cannot neuter the lint silently."""
    fixture = tmp_path / "bad.py"
    fixture.write_text(
        "import os\n"
        "x = 1\n"
        "if x == None:\n"
        "    pass\n"
        "if x == True:\n"
        "    pass\n"
        "if not x in (1, 2):\n"
        "    pass\n"
        "if not x is None:\n"
        "    pass\n"
        "if x is 'lit':\n"
        "    pass\n"
        "try:\n"
        "    pass\n"
        "except:\n"
        "    pass\n"
        "def f(l):\n"
        "    dead = l + 1\n"
        "    return l\n")
    tree = ast.parse(fixture.read_text())
    codes = {m.split()[0] for _ln, m in
             (_comparison_findings(tree) + _bare_excepts(tree)
              + _unused_imports(tree) + _ambiguous_names(tree)
              + _unused_locals(tree))}
    assert {"E711", "E712", "E713", "E714", "F632", "E722",
            "F401", "E741", "F841"} <= codes


def test_lint_scope_matches_pyproject():
    """The test and pyproject must lint the same surface."""
    try:
        import tomllib
    except ImportError:  # py310: tomllib is 3.11+
        import re
        text = (REPO / "pyproject.toml").read_text()
        m = re.search(r'^\s*select = \[(?P<body>[^\]]*)\]', text,
                      re.MULTILINE)
        assert m, "pyproject [tool.ruff.lint] select vanished"
        codes = set(re.findall(r'"([A-Z]\d+)"', m.group("body")))
    else:
        cfg = tomllib.loads((REPO / "pyproject.toml").read_text())
        codes = set(cfg["tool"]["ruff"]["lint"]["select"])
    assert {"F401", "F632", "E711", "E712", "E713", "E714",
            "E722", "E741", "F841"} == codes, (
        "pyproject ruff select drifted from the fallback's rule "
        "families — update tests/unit/test_repo_lint.py to match")
    assert sys.version_info >= (3, 10)
