"""Worker for the 2-process jax.distributed TRAINING test (reference
analog: tests/unit/common.py:16 distributed_test forks real workers for
every training path, not just checkpointing).

Each process owns 4 virtual CPU devices (global mesh = 8) and feeds ITS
half of a fixed global batch via make_array_from_process_local_data; the
test compares the loss trajectory and final global param norm against the
same training run executed single-process on an 8-device mesh — the
multi-process data/grad sharding must be numerically invisible.

Usage: python distributed_train_worker.py <coord> <num_procs> <proc_id> <dir>
"""

import json
import os
import sys

STEPS = 5


def train_losses(engine, local_ids, steps=STEPS):
    losses = []
    for _ in range(steps):
        loss = engine.forward(local_ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def global_param_norm(params):
    import jax
    import jax.numpy as jnp

    total = 0.0
    for leaf in jax.tree.leaves(params):
        total += float(jnp.sum(jnp.asarray(leaf, jnp.float32) ** 2))
    return float(total) ** 0.5


def build():
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    mesh = ds.initialize_mesh(data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    conf = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(1))
    return engine


def main():
    coord, nprocs, pid, workdir = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=pid)
    import numpy as np
    import deepspeed_tpu as ds

    engine = build()
    full = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (8, 16),
                                         0, 64), np.int32)
    local = full[pid * 4:(pid + 1) * 4]  # engine._shard_batch uses
    # make_array_from_process_local_data under jax.process_count() > 1
    losses = train_losses(engine, local)
    norm = global_param_norm(engine.params)

    out = {"pid": pid, "losses": losses, "param_norm": norm}
    with open(os.path.join(workdir, f"train_p{pid}.json"), "w") as f:
        json.dump(out, f)
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("train_done")


if __name__ == "__main__":
    main()
