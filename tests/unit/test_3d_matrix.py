"""3D composition matrix on the simulated 8-device mesh: pipeline × tensor
parallel × ZeRO × MoE, all at MATCHED GLOBAL BATCH, asserting trajectory
equality against the pipe=1/tp=1 baseline.

Reference: tests/model/run_func_test.py:606 (the Megatron-GPT2 mp × zero ×
ckpt functionality matrix).  Cells that cannot be supported must raise a
clear config error instead of silently computing something.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config
from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

SEQ = 32
GLOBAL_BATCH = 8
MICRO_BATCHES = 4  # gradient_accumulation_steps


def _cfg():
    return GPT2Config(vocab_size=64, n_positions=SEQ, hidden_size=32,
                      num_layers=4, num_heads=4, bf16=False,
                      embd_dropout=0.0, attn_dropout=0.0,
                      hidden_dropout=0.0)


def _train_pipe(pipe, tp, zero_stage, steps=3, expert=1, seq=1):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(pipe=pipe, model=tp, expert=expert, seq=seq,
                              data=-1)
    dp = mesh.data_parallel_world_size
    module = gpt2_pipeline_module(_cfg(), num_stages=pipe)
    conf = {
        "train_batch_size": GLOBAL_BATCH * MICRO_BATCHES,
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "gradient_accumulation_steps": MICRO_BATCHES,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": zero_stage},
        "sequence_parallel": {"mode": "ring"},
        "steps_per_print": 10 ** 9,
    }
    engine = PipelineEngine(
        model=module, config=conf,
        example_input=jnp.zeros((GLOBAL_BATCH, SEQ), jnp.int32),
        rng=jax.random.PRNGKey(3))
    rs = np.random.RandomState(0)
    losses = []
    for step in range(steps):
        micro = []
        for _ in range(MICRO_BATCHES):
            ids = rs.randint(0, 64, size=(GLOBAL_BATCH, SEQ)).astype(
                np.int32)
            micro.append((ids, ids))
        losses.append(engine.train_batch(iter(micro)))
    params = jax.tree.map(np.asarray, engine.params)
    ds.reset_mesh_context()
    return losses, params


BASELINE = {}


def _baseline():
    if "v" not in BASELINE:
        BASELINE["v"] = _train_pipe(pipe=1, tp=1, zero_stage=0)
    return BASELINE["v"]


@pytest.mark.parametrize("pipe,tp,zero", [
    (4, 1, 0),   # pure pipeline
    (4, 1, 1),   # pipe × zero-1
    (2, 2, 0),   # pipe × tp
    (2, 2, 1),   # pipe × tp × zero — 3D
    (1, 2, 2),   # tp × zero-2 (pipeline module, no pipe axis)
    # pipe × zero-2/3: the reference RESTRICTS pipeline parallelism to
    # ZeRO-1 (grad/param partitioning fights its hook-based schedule);
    # sharding-as-policy composes them for free — trajectory-exact
    (4, 1, 2),   # pipe × zero-2 — beyond the reference
    (2, 2, 3),   # pipe × tp × zero-3 — beyond the reference
])
def test_composition_matches_baseline(pipe, tp, zero):
    base_losses, base_params = _baseline()
    losses, params = _train_pipe(pipe=pipe, tp=tp, zero_stage=zero)
    np.testing.assert_allclose(losses, base_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        # blocks are stacked [num_stages, layers_per_stage, ...] — flatten
        # the stage/layer dims (stage-major == global layer order) so cells
        # with different stage counts compare directly
        if a.shape != b.shape:
            a = a.reshape((-1,) + a.shape[2:])
            b = b.reshape((-1,) + b.shape[2:])
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-5)


@pytest.mark.parametrize("pipe,tp,seq,zero", [
    (2, 1, 2, 0),   # pipe × seq (gated, allgather-KV attention)
    (2, 2, 2, 1),   # pipe × seq × tp × zero-1 — 4-axis composition
    (1, 1, 2, 0),   # seq-only through the same gated executor
])
def test_pipe_seq_matches_baseline(pipe, tp, seq, zero):
    """Gated sequence parallelism (round 5): the seq axis joins the
    manual region — seq peers share their pipe row's predicate; the body
    runs psum-allgather-KV attention (the divergent-branch-safe variant)
    and the seq-distributed aux chains slice their own chunk.  Must be
    trajectory-exact vs the pipe=1/seq=1 baseline."""
    base_losses, base_params = _baseline()
    losses, params = _train_pipe(pipe=pipe, tp=tp, zero_stage=zero, seq=seq)
    np.testing.assert_allclose(losses, base_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        if a.shape != b.shape:
            a = a.reshape((-1,) + a.shape[2:])
            b = b.reshape((-1,) + b.shape[2:])
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-5)


from tests.unit.seed_xfails import (  # noqa: E402 — marker for the triaged seed failures
    PARTITION_ID_XFAIL as _PARTITION_ID_XFAIL)


@_PARTITION_ID_XFAIL
def test_plain_body_pipe_expert_matches_baseline():
    """A PLAIN (dense GPT-2) body with an expert axis: the expert axis only
    shards the batch (expert-data parallelism), so the gated executor stays
    on and the trajectory must match — the silent-wrong-answer risk the old
    engine guard protected against, now asserted instead of forbidden."""
    base_losses, base_params = _baseline()
    losses, params = _train_pipe(pipe=2, tp=1, zero_stage=0, expert=2)
    np.testing.assert_allclose(losses, base_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        if a.shape != b.shape:
            a = a.reshape((-1,) + a.shape[2:])
            b = b.reshape((-1,) + b.shape[2:])
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-5)


# ---------------------------------------------------------------------- #
# PP × EP cells (round 5): an MoE pipeline body with the expert axis —
# the composition the reference gets from running MoE under any engine
# (deepspeed/runtime/engine.py:1714-1727 per-group expert-grad reduction).
# ---------------------------------------------------------------------- #
# one config for every MoE-pipeline test in this file (the parity matrix
# and the checkpoint roundtrip must exercise the SAME model)
MOE_PIPE_CFG_KW = dict(
    vocab_size=64, n_positions=SEQ, hidden_size=32, num_layers=4,
    num_heads=4, bf16=False, num_experts=4, top_k=2,
    capacity_factor=2.0, min_capacity=4, moe_every=2,
    embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)


def _build_moe_pipe_engine(pipe, expert, zero_stage, tp=1):
    """Mesh + module + engine for the shared MoE-pipeline config
    (resets the mesh context; caller resets again when done)."""
    from deepspeed_tpu.models import GPTMoEConfig
    from deepspeed_tpu.models.gpt_moe_pipe import gpt_moe_pipeline_module

    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(pipe=pipe, expert=expert, model=tp, data=-1)
    dp = mesh.data_parallel_world_size
    module = gpt_moe_pipeline_module(GPTMoEConfig(**MOE_PIPE_CFG_KW),
                                     num_stages=pipe)
    conf = {
        "train_batch_size": GLOBAL_BATCH * MICRO_BATCHES,
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "gradient_accumulation_steps": MICRO_BATCHES,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 10 ** 9,
    }
    return PipelineEngine(
        model=module, config=conf,
        example_input=jnp.zeros((GLOBAL_BATCH, SEQ), jnp.int32),
        rng=jax.random.PRNGKey(3))


def _train_moe_pipe(pipe, expert, zero_stage=0, steps=3, tp=1):
    engine = _build_moe_pipe_engine(pipe, expert, zero_stage, tp)
    rs = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        micro = []
        for _ in range(MICRO_BATCHES):
            ids = rs.randint(0, 64, size=(GLOBAL_BATCH, SEQ)).astype(
                np.int32)
            micro.append((ids, ids))
        losses.append(engine.train_batch(iter(micro)))
    params = jax.tree.map(np.asarray, engine.params)
    ds.reset_mesh_context()
    return losses, params


MOE_PIPE_BASELINE = {}


def _moe_pipe_baseline():
    if "v" not in MOE_PIPE_BASELINE:
        MOE_PIPE_BASELINE["v"] = _train_moe_pipe(pipe=1, expert=1)
    return MOE_PIPE_BASELINE["v"]


@pytest.mark.parametrize("pipe,expert,zero,tp", [
    (2, 2, 0, 1),   # pipe × expert (masked executor)
    (2, 2, 1, 1),   # pipe × expert × zero-1
    (1, 4, 0, 1),   # expert-only sanity on the same module
    (2, 1, 0, 1),   # MoE body under the GATED executor (expert=1: the aux
                    # channel's cond-gated accumulation + loss_scale vjp
                    # seed at S>1)
    (2, 1, 0, 2),   # gated MoE × manual TP: Megatron-split expert FFNs
                    # with explicit psums + replicated gate (round 5)
])
def test_pipe_expert_matches_baseline(pipe, expert, zero, tp):
    base_losses, base_params = _moe_pipe_baseline()
    losses, params = _train_moe_pipe(pipe=pipe, expert=expert,
                                     zero_stage=zero, tp=tp)
    np.testing.assert_allclose(losses, base_losses, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        if a.shape != b.shape:
            a = a.reshape((-1,) + a.shape[2:])
            b = b.reshape((-1,) + b.shape[2:])
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-5)


# ---------------------------------------------------------------------- #
# MoE × ZeRO cells (dense-equivalent MoE so trajectories are comparable)
# ---------------------------------------------------------------------- #
def _train_moe(zero_stage, steps=8):
    from deepspeed_tpu.moe import MoE

    ds.reset_mesh_context()
    ds.initialize_mesh(expert=4, data=-1)
    D = 32
    moe = MoE(hidden_size=D, num_experts=4, k=1, capacity_factor=4.0,
              min_capacity=64)
    rng = jax.random.PRNGKey(0)
    moe_params = moe.init_params(rng, jnp.zeros((16, D)))
    head = jax.random.normal(jax.random.PRNGKey(1), (D, D)) * 0.3
    params = {"moe": moe_params, "head": head}

    def model(p, rng, x, y):
        h, l_aux, _ = moe.apply(p["moe"], x, rng=rng)
        pred = h @ p["head"]
        return jnp.mean((pred - y) ** 2) + 0.01 * l_aux

    dp = ds.get_mesh_context().data_parallel_world_size
    conf = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 16 // dp,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=conf,
                                    model_parameters=params)
    rs = np.random.RandomState(0)
    w = rs.randn(D, D).astype(np.float32)
    xb = rs.randn(16, D).astype(np.float32)
    yb = xb @ w
    losses = []
    for _ in range(steps):
        loss = engine.forward(xb, yb)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    ds.reset_mesh_context()
    return losses


@pytest.mark.parametrize("zero", [2, 3])
def test_moe_zero_matches_zero0(zero):
    base = _train_moe(0)
    cell = _train_moe(zero)
    np.testing.assert_allclose(cell, base, rtol=2e-5)


def test_moe_pipe_checkpoint_roundtrip(tmp_path):
    """PP x EP checkpoint/resume: the MoE pipeline's stacked
    [stage, layer, expert, ...] leaves must survive save -> fresh-engine
    load -> continue, matching an uninterrupted run's trajectory.
    Same model as the parity matrix (_build_moe_pipe_engine)."""

    def build():
        return _build_moe_pipe_engine(pipe=2, expert=2, zero_stage=1)

    def batches(rs):
        return iter([(ids, ids) for ids in
                     (rs.randint(0, 64, (GLOBAL_BATCH, SEQ)).astype(np.int32)
                      for _ in range(MICRO_BATCHES))])

    # uninterrupted 3-step run
    ds.reset_mesh_context()
    ref = build()
    rs = np.random.RandomState(7)
    ref_losses = [ref.train_batch(batches(rs)) for _ in range(3)]

    # 2 steps -> save -> fresh engine -> load -> 1 more step
    ds.reset_mesh_context()
    eng = build()
    rs = np.random.RandomState(7)
    for _ in range(2):
        eng.train_batch(batches(rs))
    eng.save_checkpoint(str(tmp_path), tag="moe_pipe")

    ds.reset_mesh_context()
    eng2 = build()
    eng2.load_checkpoint(str(tmp_path), tag="moe_pipe")
    assert eng2.global_steps == 2
    loss3 = eng2.train_batch(batches(rs))
    np.testing.assert_allclose(loss3, ref_losses[2], rtol=2e-5)
    ds.reset_mesh_context()
