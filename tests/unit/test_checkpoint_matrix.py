"""Checkpoint round-trip × optimizer × ZeRO stage matrix.

Reference: tests/unit/test_checkpointing.py:897 — round-trips for every
optimizer/stage combination (load_module_only lives in test_checkpointing).
Here each cell
trains, saves, clobbers, restores, and must continue with an IDENTICAL
next-step loss to an uninterrupted run.
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model

SEQ = 16
GLOBAL_BATCH = 8


def _make_engine(opt, stage, offload=False):
    model = GPT2Model(GPT2Config(
        vocab_size=64, n_positions=SEQ, hidden_size=32, num_layers=2,
        num_heads=4, bf16=False, embd_dropout=0.0, attn_dropout=0.0,
        hidden_dropout=0.0))
    mesh = ds.get_mesh_context()
    dp = mesh.data_parallel_world_size
    conf = {
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "optimizer": {"type": opt, "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 10 ** 9,
    }
    if offload:
        conf["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
        conf["optimizer"]["type"] = "Adam"  # host tier is Adam/AdamW
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        rng=jax.random.PRNGKey(7))
    return engine


def _steps(engine, ids, n):
    out = []
    for _ in range(n):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        out.append(float(loss))
    return out


CELLS = [("Adam", 0, False), ("Adam", 1, False), ("Adam", 2, False),
         ("Adam", 3, False), ("AdamW", 2, False), ("Lamb", 1, False),
         ("Lamb", 2, False), ("SGD", 2, False), ("OneBitAdam", 2, False),
         ("Adam", 2, True)]


@pytest.mark.parametrize("opt,stage,offload", CELLS)
def test_roundtrip(opt, stage, offload, tmp_path):
    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                        (GLOBAL_BATCH, SEQ), 0, 64),
                     np.int32)
    # uninterrupted run: 4 steps
    ref = _make_engine(opt, stage, offload)
    ref_losses = _steps(ref, ids, 4)

    # interrupted run: 2 steps, save, new engine, load, 2 more
    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    a = _make_engine(opt, stage, offload)
    _steps(a, ids, 2)
    a.save_checkpoint(str(tmp_path))

    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    b = _make_engine(opt, stage, offload)
    b.load_checkpoint(str(tmp_path))
    assert b.global_steps == 2
    resumed = _steps(b, ids, 2)
    np.testing.assert_allclose(resumed, ref_losses[2:], rtol=1e-6)
    ds.reset_mesh_context()
