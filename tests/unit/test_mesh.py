"""Mesh / groups tests (role of reference tests/unit/test_topology.py for the
mesh substrate)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import MeshContext, groups, resolve_mesh_shape


def test_resolve_wildcard():
    s = resolve_mesh_shape(8, model=2)
    assert s.data == 4 and s.model == 2 and s.total == 8


def test_resolve_explicit():
    s = resolve_mesh_shape(8, pipe=2, data=2, model=2)
    assert s.total == 8


def test_resolve_errors():
    with pytest.raises(ValueError):
        resolve_mesh_shape(8, data=3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        resolve_mesh_shape(8, data=-1, model=-1)
    with pytest.raises(ValueError):
        resolve_mesh_shape(8, pipe=2, data=2, model=4)


def test_mesh_context_sizes():
    ctx = MeshContext.create(pipe=2, expert=2, model=2)
    assert ctx.world_size == 8
    assert ctx.pipe_parallel_world_size == 2
    assert ctx.model_parallel_world_size == 2
    assert ctx.expert_parallel_world_size == 2
    # dense DP spans data×expert
    assert ctx.data_parallel_world_size == 2


def test_groups_initialize_scenarios():
    # Scenario E+D: 8 devices, ep=2 → expert-data=4
    ctx = groups.initialize(ep_size=2)
    assert groups.get_expert_parallel_world_size() == 2
    assert groups.get_expert_data_parallel_world_size() == 4
    assert groups.get_data_parallel_world_size() == 8
    assert ctx.world_size == 8


def test_sharded_psum_over_data_axis():
    """A psum over the data axis must sum contributions from all 8 devices."""
    ctx = MeshContext.create()
    x = jnp.arange(8.0)

    from jax.sharding import PartitionSpec as P

    @jax.jit
    def f(x):
        def body(xs):
            return jax.lax.psum(xs, ("data", "expert"))
        return jax.shard_map(body, mesh=ctx.mesh,
                             in_specs=P(("data", "expert")),
                             out_specs=P(("data", "expert")))(x)

    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 28.0))


def test_data_sharding_placement():
    ctx = MeshContext.create()
    x = jnp.zeros((16, 4))
    y = jax.device_put(x, ctx.data_sharding())
    assert len(y.sharding.device_set) == 8


def test_resolve_hpz_axes_suffix_rule():
    """hpZ (ZeRO++): the secondary-partition group must be the product of
    a SUFFIX of the ZeRO axes — inner axes ride the fastest links."""
    from deepspeed_tpu.runtime.zero.partition import resolve_hpz_axes

    sizes = {"data": 4, "expert": 2}
    assert resolve_hpz_axes(sizes, 2) == ("expert",)
    assert resolve_hpz_axes(sizes, 8) == ("data", "expert")
    # size-1 axes drop out of the returned tuple
    assert resolve_hpz_axes({"data": 8, "expert": 1}, 8) == ("data",)
    assert resolve_hpz_axes({"data": 8, "expert": 1}, 1) == ()
    # non-suffix sizes raise, listing the valid ones
    with pytest.raises(ValueError, match=r"valid sizes.*\[1, 2, 8\]"):
        resolve_hpz_axes(sizes, 4)
    with pytest.raises(ValueError):
        resolve_hpz_axes(sizes, 3)


def test_hpz_secondary_shardings_on_two_axis_mesh():
    """ZeroPartitioner.secondary_shardings: the hpZ secondary weight copy
    shards ONLY within the sub-mesh (inner ZeRO axes), replicated across
    the slow outer axes — so hot-loop gathers never cross them."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner

    ctx = MeshContext.create(data=4, expert=2)
    part = ZeroPartitioner(ctx, stage=3, persistence_threshold=0)
    params = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}

    primary = part.param_shardings(params)
    secondary = part.secondary_shardings(params, hpz_group_size=2)
    # primary spans both ZeRO axes; secondary only the inner one
    assert primary["w"].spec == P(("data", "expert"), None)
    assert secondary["w"].spec == P("expert", None)
    assert secondary["b"].spec == P("expert")
    # full-group size degenerates to the primary partition
    full = part.secondary_shardings(params, hpz_group_size=8)
    assert full["w"].spec == primary["w"].spec
    # a group that doesn't align with whole inner axes is rejected
    with pytest.raises(ValueError, match="hpz_group_size=3"):
        part.secondary_shardings(params, hpz_group_size=3)
