"""Mesh / groups tests (role of reference tests/unit/test_topology.py for the
mesh substrate)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import (MeshContext, groups, initialize_mesh,
                                    resolve_mesh_shape)


def test_resolve_wildcard():
    s = resolve_mesh_shape(8, model=2)
    assert s.data == 4 and s.model == 2 and s.total == 8


def test_resolve_explicit():
    s = resolve_mesh_shape(8, pipe=2, data=2, model=2)
    assert s.total == 8


def test_resolve_errors():
    with pytest.raises(ValueError):
        resolve_mesh_shape(8, data=3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        resolve_mesh_shape(8, data=-1, model=-1)
    with pytest.raises(ValueError):
        resolve_mesh_shape(8, pipe=2, data=2, model=4)


def test_mesh_context_sizes():
    ctx = MeshContext.create(pipe=2, expert=2, model=2)
    assert ctx.world_size == 8
    assert ctx.pipe_parallel_world_size == 2
    assert ctx.model_parallel_world_size == 2
    assert ctx.expert_parallel_world_size == 2
    # dense DP spans data×expert
    assert ctx.data_parallel_world_size == 2


def test_groups_initialize_scenarios():
    # Scenario E+D: 8 devices, ep=2 → expert-data=4
    ctx = groups.initialize(ep_size=2)
    assert groups.get_expert_parallel_world_size() == 2
    assert groups.get_expert_data_parallel_world_size() == 4
    assert groups.get_data_parallel_world_size() == 8
    assert ctx.world_size == 8


def test_sharded_psum_over_data_axis():
    """A psum over the data axis must sum contributions from all 8 devices."""
    ctx = MeshContext.create()
    x = jnp.arange(8.0)

    from jax.sharding import PartitionSpec as P

    @jax.jit
    def f(x):
        def body(xs):
            return jax.lax.psum(xs, ("data", "expert"))
        return jax.shard_map(body, mesh=ctx.mesh,
                             in_specs=P(("data", "expert")),
                             out_specs=P(("data", "expert")))(x)

    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 28.0))


def test_data_sharding_placement():
    ctx = MeshContext.create()
    x = jnp.zeros((16, 4))
    y = jax.device_put(x, ctx.data_sharding())
    assert len(y.sharding.device_set) == 8
