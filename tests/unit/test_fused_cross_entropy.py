"""Chunked fused linear+CE (ops/fused_cross_entropy.py): numerical parity
with the naive logits path for values and gradients, and the GPT-2 loss
switch."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.ops.fused_cross_entropy import fused_linear_cross_entropy


def _naive(h, w, labels):
    logits = (h @ w).astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


@pytest.mark.parametrize("n,hid,vocab,chunk", [
    (32, 16, 64, 16),      # evenly divisible chunks
    (32, 16, 64, 64),      # single chunk
    (32, 16, 64, 7),       # non-divisor chunk: vocab padded to 10x7=70
    (17, 16, 96, 32),      # odd token count
])
def test_matches_naive_fp32(n, hid, vocab, chunk):
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(n, hid), jnp.float32)
    w = jnp.asarray(rng.randn(hid, vocab) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.randint(0, vocab, n), jnp.int32)

    loss_f = fused_linear_cross_entropy(h, w, labels, chunk)
    loss_n = _naive(h, w, labels)
    np.testing.assert_allclose(loss_f, loss_n, rtol=1e-6)

    gf = jax.grad(lambda hh, ww: fused_linear_cross_entropy(
        hh, ww, labels, chunk), argnums=(0, 1))(h, w)
    gn = jax.grad(lambda hh, ww: _naive(hh, ww, labels),
                  argnums=(0, 1))(h, w)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_prime_vocab_pads_not_degrades():
    """Non-divisible (e.g. GPT-2's prime 50257) vocabularies pad up to
    whole chunks with -inf masking — values/grads still match, and the
    scan must have ceil(V/chunk) steps, not V steps."""
    rng = np.random.RandomState(3)
    vocab = 97  # prime
    h = jnp.asarray(rng.randn(16, 8), jnp.float32)
    w = jnp.asarray(rng.randn(8, vocab) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.randint(0, vocab, 16), jnp.int32)
    loss_f = fused_linear_cross_entropy(h, w, labels, 32)
    np.testing.assert_allclose(loss_f, _naive(h, w, labels), rtol=1e-6)
    gf = jax.grad(lambda hh, ww: fused_linear_cross_entropy(
        hh, ww, labels, 32), argnums=(0, 1))(h, w)
    gn = jax.grad(lambda hh, ww: _naive(hh, ww, labels),
                  argnums=(0, 1))(h, w)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # chunked, not degraded to one column per step
    from deepspeed_tpu.ops.fused_cross_entropy import _plan
    c, n_chunks, padded = _plan(vocab, 32, h.shape[0])
    assert c == 32 and n_chunks == 4 and padded == 128
    # auto policy (chunk_size=None): large budget / few tokens -> one chunk
    c, n_chunks, padded = _plan(vocab, None, h.shape[0])
    assert c == vocab and n_chunks == 1 and padded == vocab
    # auto policy under a huge token count stays above the floor
    c, _, _ = _plan(10 ** 6, None, 10 ** 9)
    assert c == 4096


def test_matches_naive_bf16_inputs():
    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(64, 32), jnp.bfloat16)
    w = jnp.asarray(rng.randn(32, 128) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 128, 64), jnp.int32)
    loss_f = fused_linear_cross_entropy(h, w, labels, 32)
    loss_n = _naive(h, w, labels)
    np.testing.assert_allclose(float(loss_f), float(loss_n), rtol=2e-2)
    gf = jax.grad(lambda hh: fused_linear_cross_entropy(
        hh, w, labels, 32))(h)
    gn = jax.grad(lambda hh: _naive(hh, w, labels))(h)
    np.testing.assert_allclose(np.asarray(gf, np.float32),
                               np.asarray(gn, np.float32),
                               rtol=0.1, atol=1e-3)


def test_gpt2_fused_loss_matches_naive():
    """The GPT-2 fused_loss switch is numerics-neutral (values + grads)."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    def build(fused):
        cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                         num_layers=2, num_heads=4, bf16=False,
                         embd_dropout=0.0, attn_dropout=0.0,
                         hidden_dropout=0.0, fused_loss=fused,
                         fused_loss_chunk=16)
        return GPT2Model(cfg)

    m_f, m_n = build(True), build(False)
    params = m_f.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (4, 16)),
                      jnp.int32)
    lf = m_f.loss(params, None, ids)
    ln = m_n.loss(params, None, ids)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-6)
    gf = jax.grad(lambda p: m_f.loss(p, None, ids))(params)
    gn = jax.grad(lambda p: m_n.loss(p, None, ids))(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gn)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-7)


def test_no_full_logits_in_fused_jaxpr():
    """The fused path must never materialize an [N, V] fp32 tensor."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=512, n_positions=16, hidden_size=32,
                     num_layers=1, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0,
                     fused_loss=True, fused_loss_chunk=64)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.zeros((4, 16), jnp.int32)
    jaxpr = str(jax.make_jaxpr(
        jax.grad(lambda p: model.loss(p, None, ids)))(params))
    n_tokens = 4 * 15
    assert f"f32[{n_tokens},512]" not in jaxpr
    assert f"f32[4,15,512]" not in jaxpr and "f32[4,16,512]" not in jaxpr
