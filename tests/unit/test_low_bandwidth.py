"""ZeRO++-style low-bandwidth collectives (runtime/comm/low_bandwidth.py):
qwZ quantized weight all-gather, qgZ quantized grad reduce-scatter with
error feedback, hpZ secondary partitioning — plus the end-to-end
acceptance check: loss-trajectory parity with the fp32 path over 20
optimizer steps AND a ~4x wire-byte reduction visible in the jaxpr.

Reference: ZeRO++ (arXiv:2306.10209) qwZ/qgZ/hpZ; Frontier low-bandwidth
partitioning (arXiv:2501.04266).  All on the 8-device CPU sim mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.comm.low_bandwidth import (
    as_quantized_weight, blockwise_dequantize, blockwise_quantize,
    collective_wire_bytes, init_error_feedback, low_bandwidth_all_gather,
    pack_int4, qgz_reduce_scatter, qgz_reduce_scatter_inner,
    quantized_gather_saves_bytes, quantized_psum_scatter, unpack_int4)


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


# --------------------------------------------------------------------- #
# blockwise quantization primitives
# --------------------------------------------------------------------- #
def test_blockwise_roundtrip_error_bounds():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 96)).astype(np.float32))
    for bits, tol in ((8, 0.02), (4, 0.35)):
        q, scale = blockwise_quantize(x, dim=0, bits=bits, block=32)
        assert scale.shape == (4, 3)  # 96/32 blocks per row
        y = blockwise_dequantize(q, scale, x.shape, dim=0, bits=bits)
        assert y.shape == x.shape and y.dtype == x.dtype
        # symmetric quantizer: |err| <= scale/2 per element; amax/qmax
        # scale bounds the relative error blockwise
        assert float(jnp.max(jnp.abs(x - y))) < tol
    # int8 payload really is int8 on the wire
    q, _ = blockwise_quantize(x, dim=0, bits=8, block=32)
    assert q.dtype == jnp.int8 and q.shape == (4, 3, 32)
    # int4 packs two-per-byte
    q4, _ = blockwise_quantize(x, dim=0, bits=4, block=32)
    assert q4.shape == (4, 3, 16)


def test_blockwise_handles_awkward_shapes():
    rng = np.random.default_rng(1)
    for shape in ((8,), (3, 7), (2, 5, 9)):
        x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
        for bits in (8, 4):
            q, s = blockwise_quantize(x, dim=0, bits=bits, block=16)
            y = blockwise_dequantize(q, s, x.shape, dim=0, bits=bits)
            assert y.shape == x.shape
            assert float(jnp.max(jnp.abs(x - y))) < 0.6
    # zero input stays exactly zero (scale guard against amax == 0)
    z = jnp.zeros((4, 8), jnp.float32)
    q, s = blockwise_quantize(z, dim=0, bits=8)
    assert float(jnp.max(jnp.abs(
        blockwise_dequantize(q, s, z.shape, dim=0)))) == 0.0


def test_int4_pack_roundtrip():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.integers(-7, 8, size=(3, 5, 8)).astype(np.int8))
    p = pack_int4(q)
    assert p.shape == (3, 5, 4)
    assert (unpack_int4(p) == q).all()


def test_quantized_gather_saves_bytes_heuristic():
    """The wire-cost gate behind _gather_leaf: wide leaves win, skinny
    leaves (per-element fp32 scales) lose and must fall back dense."""
    # (1, h) gathered along dim 1: rest == 1 → one fp32 scale per int8
    # element, 5 bytes/elem vs 4 native — quantizing inflates traffic
    assert not quantized_gather_saves_bytes((1, 128), 1, jnp.float32, 8)
    # same leaf in a 2-layer group amortizes the scale over 2 elements
    assert quantized_gather_saves_bytes((2, 128), 1, jnp.float32, 8)
    # bf16 native halves the bar: a 2-element block (1 + 4/2 bytes vs 4)
    # still loses, a full block wins
    assert not quantized_gather_saves_bytes((2, 128), 1, jnp.bfloat16, 8)
    assert quantized_gather_saves_bytes((256, 128), 1, jnp.bfloat16, 8)
    # a weight matrix wins in every layout
    assert quantized_gather_saves_bytes((1, 64, 256), 1, jnp.float32, 8)
    assert quantized_gather_saves_bytes((128, 512), 0, jnp.float32, 4)


def test_as_quantized_weight_bridge():
    """blockwise_quantize with one block per row IS ops/quant.py's
    per-row QuantizedWeight — the fused dequant-matmul kernels accept
    the gathered payload directly."""
    from deepspeed_tpu.ops.quant import dequant
    rng = np.random.default_rng(12)
    w = jnp.asarray(rng.normal(size=(16, 48)).astype(np.float32))
    q, scale = blockwise_quantize(w, dim=0, bits=8, block=48)
    assert q.shape == (16, 1, 48) and scale.shape == (16, 1)
    qw = as_quantized_weight(q, scale)
    assert qw.qweight.shape == w.shape and qw.scale.shape == (16, 1)
    np.testing.assert_allclose(
        np.asarray(dequant(qw, jnp.float32)),
        np.asarray(blockwise_dequantize(q, scale, w.shape, dim=0)),
        rtol=1e-6)
    # multi-block rows have no per-row scale — the bridge refuses
    q2, s2 = blockwise_quantize(w, dim=0, bits=8, block=16)
    with pytest.raises(ValueError, match="blockwise"):
        as_quantized_weight(q2, s2)


# --------------------------------------------------------------------- #
# qwZ: quantized weight all-gather
# --------------------------------------------------------------------- #
def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def test_qwz_all_gather_close_to_fp32():
    mesh = _mesh((8,), ("data",))
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    ref = _shard_map(
        lambda a: jax.lax.all_gather(a, ("data",), axis=0, tiled=True),
        mesh, P("data"), P())(x)
    for bits, tol in ((8, 0.03), (4, 0.5)):
        got = _shard_map(
            lambda a: low_bandwidth_all_gather(a, ("data",), 0, bits, 0, 64),
            mesh, P("data"), P())(x)
        assert got.shape == ref.shape
        assert float(jnp.max(jnp.abs(ref - got))) < tol
    # bits=0 is the exact native gather
    got = _shard_map(
        lambda a: low_bandwidth_all_gather(a, ("data",), 0, 0, 0, 64),
        mesh, P("data"), P())(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_qwz_backward_transport_identical_to_fp32():
    """With qgZ off, the quantized gather's VJP is the SAME fp32
    reduce-scatter as _all_gather_f32grad (straight-through quantizer).
    A loss LINEAR in the gathered value isolates the transport: its
    cotangent is independent of the (quantized) forward value, so the
    grads must be bit-identical, not merely close."""
    mesh = _mesh((8,), ("data",))
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))

    def grad_of(gather):
        def loss(a):
            return jnp.sum(gather(a) * w)
        return _shard_map(jax.grad(loss), mesh, P("data"), P("data"))(x)

    g_ref = grad_of(
        lambda a: jax.lax.all_gather(a, ("data",), axis=0, tiled=True))
    g_q = grad_of(
        lambda a: low_bandwidth_all_gather(a, ("data",), 0, 8, 0, 64))
    np.testing.assert_array_equal(np.asarray(g_ref), np.asarray(g_q))


# --------------------------------------------------------------------- #
# qgZ: quantized gradient reduce-scatter
# --------------------------------------------------------------------- #
def test_qgz_psum_scatter_close_to_fp32():
    mesh = _mesh((8,), ("data",))
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    ref = _shard_map(
        lambda a: jax.lax.psum_scatter(a, ("data",), scatter_dimension=0,
                                       tiled=True),
        mesh, P(None), P("data"))(x)
    got = _shard_map(
        lambda a: quantized_psum_scatter(a, ("data",), 0, bits=8, block=64),
        mesh, P(None), P("data"))(x)
    assert got.shape == ref.shape
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(ref - got))) / scale < 0.01


def test_qgz_multi_axis_reduce_scatter():
    """Two ZeRO axes (data=4, expert=2) reduce sequentially — result
    stays close to the joint fp32 psum_scatter."""
    mesh = _mesh((4, 2), ("data", "expert"))
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    axes = ("data", "expert")
    ref = _shard_map(
        lambda a: jax.lax.psum_scatter(a, axes, scatter_dimension=0,
                                       tiled=True),
        mesh, P(None), P(axes))(x)
    got = _shard_map(
        lambda a: quantized_psum_scatter(a, axes, 0, bits=8, block=64),
        mesh, P(None), P(axes))(x)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(ref - got))) / scale < 0.02


def test_qgz_error_feedback_running_mean_converges():
    """Error feedback telescopes: sum_t out_t = reduce(T*x + e_0 - e_T),
    so the RUNNING MEAN of repeated reductions of a persistent signal
    converges to the exact reduction at O(1/T) — the same argument as
    1-bit Adam's worker error compensation, now multi-bit.  int4 makes
    the effect visible in few steps."""
    mesh = _mesh((8,), ("data",))
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    exact = _shard_map(
        lambda a: jax.lax.psum_scatter(a, ("data",), scatter_dimension=0,
                                       tiled=True),
        mesh, P(None), P("data"))(x)

    # jit once: an unjitted shard_map re-lowers on every call (12 calls
    # would spend >1 min compiling the same program)
    step = jax.jit(_shard_map(
        lambda a, e: qgz_reduce_scatter_inner(a, e, "data", dim=0, bits=4,
                                              block=64),
        mesh, (P(None), P(None)), (P("data"), P(None))))

    err = jnp.zeros_like(x)
    total = jnp.zeros_like(exact)
    means = []
    for t in range(1, 13):
        out, err = step(x, err)
        total = total + out
        means.append(float(jnp.max(jnp.abs(total / t - exact))))
    # one-shot int4 error vs the telescoped mean after 12 rounds: the
    # residual is the carried buffer / T, i.e. O(1/T)
    assert means[-1] < means[0] / 3
    assert means[-1] < 0.2
    # the error buffer stays bounded (quantizer granularity), not growing
    assert float(jnp.max(jnp.abs(err))) < 2.0


def test_qgz_stacked_wrapper_matches_inner():
    """Worker-stacked convenience API (compressed_allreduce calling
    convention): row i of the result is worker i's reduced chunk."""
    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    rng = np.random.default_rng(8)
    W = 8
    x = jnp.asarray(rng.normal(size=(W, 16, 6)).astype(np.float32))
    err = init_error_feedback(x)
    reduced, new_err = qgz_reduce_scatter(x, err, bits=8, block=48)
    assert reduced.shape == (W, 2, 6)  # 16/8 chunk per worker
    assert new_err.shape == x.shape
    # against a numpy reference: chunk i of the sum over workers
    full = np.asarray(x).sum(axis=0)  # [16, 6]
    for i in range(W):
        approx = np.asarray(reduced)[i]
        want = full[2 * i:2 * (i + 1)]
        assert np.max(np.abs(approx - want)) / max(
            1e-9, np.max(np.abs(want))) < 0.02
    ds.reset_mesh_context()


# --------------------------------------------------------------------- #
# wire-byte accounting
# --------------------------------------------------------------------- #
def test_collective_wire_bytes_walker():
    mesh = _mesh((4, 2), ("data", "model"))
    x = jnp.ones((16, 24), np.float32)

    def f(a):  # a is [4, 24] per shard over "data"
        g = jax.lax.all_gather(a, ("data",), axis=0, tiled=True)
        s = jax.lax.psum_scatter(g, ("data",), scatter_dimension=0,
                                 tiled=True)
        return g.sum() + s.sum()

    jx = jax.make_jaxpr(_shard_map(f, mesh, P("data"), P()))(x)
    bytes_ = collective_wire_bytes(jx)
    # gather output: [16, 24] fp32 = 1536 B; reduce operand: same
    assert bytes_["gather_bytes"] == 16 * 24 * 4
    assert bytes_["reduce_bytes"] == 16 * 24 * 4


# --------------------------------------------------------------------- #
# config block
# --------------------------------------------------------------------- #
def test_low_bandwidth_config_parsing():
    from deepspeed_tpu.config import (DeepSpeedConfigError,
                                      ZeroLowBandwidthConfig)
    off = ZeroLowBandwidthConfig.from_dict(None)
    assert not off.enabled and off.qwz_bits == 0 and off.qgz_bits == 0
    cfg = ZeroLowBandwidthConfig.from_dict(
        {"qwz_bits": 8, "qgz_bits": 4, "hpz_group_size": 2,
         "block_size": 128})
    assert cfg.enabled and cfg.qwz_bits == 8 and cfg.qgz_bits == 4
    assert cfg.hpz_group_size == 2 and cfg.block_size == 128
    # each knob independently enables
    assert ZeroLowBandwidthConfig.from_dict({"qwz_bits": 8}).enabled
    assert ZeroLowBandwidthConfig.from_dict({"hpz_group_size": 4}).enabled
    for bad in ({"qwz_bits": 3}, {"qgz_bits": 16}, {"block_size": 0}):
        with pytest.raises(DeepSpeedConfigError):
            ZeroLowBandwidthConfig.from_dict(bad)
    # rides inside zero_optimization
    from deepspeed_tpu.config import ZeroConfig
    z = ZeroConfig.from_dict(
        {"stage": 3, "low_bandwidth": {"qgz_bits": 8}})
    assert z.low_bandwidth.qgz_bits == 8 and z.low_bandwidth.enabled


# --------------------------------------------------------------------- #
# end-to-end acceptance: parity + ~4x byte reduction
# --------------------------------------------------------------------- #
def _train_small(zero_cfg, steps, mesh_kwargs=None, bf16=False):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(**(mesh_kwargs or {"data": -1}))
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=bf16,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": zero_cfg,
                "steps_per_print": 10 ** 9},
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (8, 16),
                                        0, 64), np.int32)
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))

    def loss_fn(p):
        return model.loss(p, None, ids)

    jaxpr = jax.make_jaxpr(jax.grad(loss_fn))(engine.params)
    stream = engine._zero3_stream
    ds.reset_mesh_context()
    return losses, jaxpr, stream


_Z3 = {"stage": 3, "stage3_param_persistence_threshold": 0,
       "stage3_max_live_parameters": 1, "stage3_prefetch_bucket_size": 0}


def test_e2e_quantized_parity_and_byte_reduction():
    """THE acceptance check: with qwz_bits=8 + qgz_bits=8, the loss
    trajectory stays within tolerance of the fp32 path over 20 optimizer
    steps, and the grad jaxpr moves ~4x fewer gathered-weight and
    reduce-scattered-grad bytes."""
    steps = 20
    l_f, jx_f, _ = _train_small(dict(_Z3), steps)
    l_q, jx_q, stream = _train_small(
        dict(_Z3, low_bandwidth={"qwz_bits": 8, "qgz_bits": 8}), steps)
    assert stream is not None and stream.active and stream.lbc is not None

    # parity: int8 blockwise noise must not bend the trajectory
    rel = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(l_f, l_q))
    assert rel < 0.02, (rel, l_f, l_q)
    assert l_q[-1] < l_q[0]  # still actually training

    # wire bytes: int8 payload + fp32 scales vs fp32 — ~4x on both
    # directions (scales and the all-to-all transport keep it under 4)
    bf = collective_wire_bytes(jx_f)
    bq = collective_wire_bytes(jx_q)
    assert bf["gather_bytes"] > 0 and bf["reduce_bytes"] > 0
    assert bf["gather_bytes"] / bq["gather_bytes"] > 3.0, (bf, bq)
    assert bf["reduce_bytes"] / bq["reduce_bytes"] > 3.0, (bf, bq)


def test_e2e_hpz_exact_parity_on_two_axis_mesh():
    """hpZ alone changes WHERE the weight gathers run (sub-mesh only),
    not their numerics: fp32 trajectories match to float tolerance, and
    the stream's param gathers are confined to the inner ZeRO axis."""
    steps = 4
    l_f, _, _ = _train_small(dict(_Z3), steps,
                             mesh_kwargs={"data": 4, "expert": 2})
    l_h, _, stream = _train_small(
        dict(_Z3, low_bandwidth={"hpz_group_size": 2}), steps,
        mesh_kwargs={"data": 4, "expert": 2})
    assert stream.param_manual == frozenset({"expert"})
    assert stream.manual == frozenset({"data", "expert"})
    np.testing.assert_allclose(l_h, l_f, rtol=1e-5)


def test_e2e_hpz_bf16_trains_on_cpu():
    """hpZ + bf16: every leaf's gathers stop at the sub-mesh, so every
    half-precision leaf takes the fp32-widened entry (boundary grad psum
    over the slow axes) — this must trace and train on CPU, where a
    half-precision reduction collective hard-aborts XLA."""
    losses, _, stream = _train_small(
        dict(_Z3, low_bandwidth={"hpz_group_size": 2}), 3,
        mesh_kwargs={"data": 4, "expert": 2}, bf16=True)
    assert stream.param_manual == frozenset({"expert"})
    assert losses[-1] < losses[0]


def test_engine_warns_low_bandwidth_below_stage3(monkeypatch):
    """low_bandwidth under stage < 3 is inert — the engine says so
    instead of silently ignoring the config.  (The repo logger sets
    propagate=False, so capture the call, not the root-logger record.)"""
    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32))

    def model(p, rng_, x, y):
        return jnp.mean((x @ p - y) ** 2)

    from deepspeed_tpu.runtime import engine as engine_mod
    warnings_seen = []
    monkeypatch.setattr(
        engine_mod.logger, "warning",
        lambda msg, *a, **k: warnings_seen.append(str(msg)))
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 2, "low_bandwidth": {"qwz_bits": 8}},
                "steps_per_print": 10 ** 9},
        model_parameters=w)
    assert any("low_bandwidth" in m for m in warnings_seen)
    # stage 3 with a model that lacks install_zero3_streaming is the
    # OTHER inert case — it must warn too, not silently no-op
    warnings_seen.clear()
    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3, "low_bandwidth": {"qwz_bits": 8}},
                "steps_per_print": 10 ** 9},
        model_parameters=w)
    assert any("install_zero3_streaming" in m for m in warnings_seen)
    ds.reset_mesh_context()
