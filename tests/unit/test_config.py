"""Config parsing tests (modeled on reference tests/unit/test_config.py)."""

import json

import pytest

from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError


def base_config():
    return {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 0.001}},
        "fp16": {"enabled": True},
    }


def test_batch_triple_all_given():
    cfg = DeepSpeedConfig(base_config(), world_size=4)
    assert cfg.train_batch_size == 16
    assert cfg.train_micro_batch_size_per_gpu == 2
    assert cfg.gradient_accumulation_steps == 2


def test_batch_infer_gas():
    d = base_config()
    del d["gradient_accumulation_steps"]
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_infer_micro():
    d = base_config()
    del d["train_micro_batch_size_per_gpu"]
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_batch_infer_train():
    d = base_config()
    del d["train_batch_size"]
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.train_batch_size == 16


def test_batch_only_train_given():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 2
    assert cfg.gradient_accumulation_steps == 1


def test_batch_only_micro_given():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 3}, world_size=4)
    assert cfg.train_batch_size == 12
    assert cfg.gradient_accumulation_steps == 1


def test_batch_none_given():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=1)


def test_batch_inconsistent():
    d = base_config()
    d["train_batch_size"] = 17
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(d, world_size=4)


def test_config_from_file(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text(json.dumps(base_config()))
    cfg = DeepSpeedConfig(str(p), world_size=4)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 0.001


def test_config_duplicate_keys(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 4}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=1)


def test_fp16_defaults():
    cfg = DeepSpeedConfig(base_config(), world_size=4)
    assert cfg.fp16.enabled
    assert cfg.fp16.dynamic_loss_scale
    assert cfg.fp16.initial_scale_power == 32
    assert cfg.fp16.loss_scale_window == 1000


def test_zero_config_stages():
    for stage in (0, 1, 2, 3):
        d = base_config()
        d["zero_optimization"] = {"stage": stage}
        cfg = DeepSpeedConfig(d, world_size=4)
        assert cfg.zero_optimization_stage == stage
        assert cfg.zero_enabled == (stage > 0)


def test_zero_overlap_comm_stage_default():
    d = base_config()
    d["zero_optimization"] = {"stage": 3}
    assert DeepSpeedConfig(d, world_size=4).zero_config.overlap_comm
    d["zero_optimization"] = {"stage": 2}
    assert not DeepSpeedConfig(d, world_size=4).zero_config.overlap_comm


def test_zero_offload_legacy_flag():
    d = base_config()
    d["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.zero_config.offload_optimizer is not None
    assert cfg.zero_config.offload_optimizer.device == "cpu"


def test_zero_offload_dicts():
    d = base_config()
    d["zero_optimization"] = {
        "stage": 3,
        "offload_param": {"device": "nvme", "nvme_path": "/tmp/nvme",
                          "buffer_count": 7},
        "offload_optimizer": {"device": "nvme", "pipeline_read": True},
    }
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.zero_config.offload_param.device == "nvme"
    assert cfg.zero_config.offload_param.buffer_count == 7
    assert cfg.zero_config.offload_optimizer.pipeline


def test_scheduler_config():
    d = base_config()
    d["scheduler"] = {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}}
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.scheduler_name == "WarmupLR"
    assert cfg.scheduler_params["warmup_num_steps"] == 10


def test_bf16_config():
    d = base_config()
    del d["fp16"]
    d["bf16"] = {"enabled": True}
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.bf16.enabled and not cfg.fp16.enabled


def test_aio_defaults():
    cfg = DeepSpeedConfig(base_config(), world_size=4)
    assert cfg.aio_config.block_size == 1048576
    assert cfg.aio_config.queue_depth == 8
    assert cfg.aio_config.overlap_events


def test_mesh_config():
    d = base_config()
    d["mesh"] = {"model": 2, "pipe": 2}
    cfg = DeepSpeedConfig(d, world_size=4)
    assert cfg.mesh_config.model == 2
    assert cfg.mesh_config.pipe == 2
    assert cfg.mesh_config.data == -1


def test_add_config_arguments_roundtrip():
    """CLI argument surface (reference: deepspeed/__init__.py:216 +
    tests/unit/test_ds_arguments.py): add_config_arguments wires
    --deepspeed/--deepspeed_config into an existing parser without
    clobbering user args."""
    import argparse

    import deepspeed_tpu as ds

    parser = argparse.ArgumentParser()
    parser.add_argument("--user_flag", type=int, default=3)
    parser = ds.add_config_arguments(parser)
    args = parser.parse_args(
        ["--user_flag", "7", "--deepspeed", "--deepspeed_config", "c.json"])
    assert args.user_flag == 7
    assert args.deepspeed is True
    assert args.deepspeed_config == "c.json"
    # defaults: off
    args2 = parser.parse_args([])
    assert args2.deepspeed is False and args2.deepspeed_config is None


def test_prng_impl_config_knob():
    """prng_impl selects the default engine PRNG stream implementation
    (rbg = fast on TPU; threefry = bit-reproducible across backends)."""

    from deepspeed_tpu.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                           "prng_impl": "threefry"}, world_size=1)
    assert cfg.prng_impl == "threefry"
    # default stays the measured-fast TPU choice
    cfg2 = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1},
                           world_size=1)
    assert cfg2.prng_impl == "rbg"
