"""Resilience subsystem tests: atomic checksummed checkpoints,
crash-mid-save recovery (both checkpoint layouts), manifest verification
+ fallback, retention GC, the preemption handler, and the
training-health sentinel.  All deterministic via the fault-injection
harness (runtime/resilience/fault_injection.py) — fast lane."""

import json
import os
import signal

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.resilience import (atomic, fault_injection,
                                              recovery)
from deepspeed_tpu.runtime.resilience.fault_injection import (
    InjectedCrash, crash_after_bytes, measure_save_bytes, poison_batch)
from deepspeed_tpu.runtime.resilience.preemption import TrainingInterrupted
from deepspeed_tpu.runtime.resilience.sentinel import (SentinelAbort,
                                                       TrainingSentinel)
from tests.unit.simple_model import (base_engine_config, random_dataloader,
                                     simple_model_apply, simple_model_params)

HIDDEN = 16
RES_ON = {"enabled": True}


def make_engine(**overrides):
    cfg = base_engine_config(micro_batch=8, gas=1, **(overrides or {}))
    params = simple_model_params(HIDDEN)
    engine, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                                    model_parameters=params)
    return engine


def run_steps(engine, n, seed=3):
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    it = iter(RepeatingLoader(random_dataloader(HIDDEN, 32, 8, seed=seed)))
    for _ in range(n):
        x, y = next(it)
        engine.backward(engine.forward(x, y))
        engine.step()
    return it


def np_params(engine):
    return jax.tree.map(np.asarray, engine.params)


def assert_params_equal(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


# --------------------------------------------------------------------- #
# package export sanity (the per-module import smoke lives in
# test_collection_smoke.py, which owns the module list)
# --------------------------------------------------------------------- #
def test_resilience_package_exports():
    from deepspeed_tpu.runtime import resilience
    for name in resilience.__all__:
        assert getattr(resilience, name) is not None


# --------------------------------------------------------------------- #
# atomic commit primitives
# --------------------------------------------------------------------- #
def test_write_latest_atomic_and_manifest_roundtrip(tmp_path):
    d = str(tmp_path)
    atomic.write_latest_atomic(d, "tagA")
    with open(os.path.join(d, "latest")) as f:
        assert f.read() == "tagA"
    atomic.write_latest_atomic(d, "tagB")
    with open(os.path.join(d, "latest")) as f:
        assert f.read() == "tagB"
    # no stray tmp files left behind
    assert os.listdir(d) == ["latest"]

    ck = tmp_path / "tag1"
    ck.mkdir()
    (ck / "a.bin").write_bytes(b"hello world")
    (ck / "b.bin").write_bytes(b"x" * 1000)
    atomic.write_manifest(str(ck))
    assert atomic.verify_manifest(str(ck)) == []
    # flip one byte -> CRC mismatch reported
    raw = bytearray((ck / "b.bin").read_bytes())
    raw[500] ^= 0xFF
    (ck / "b.bin").write_bytes(bytes(raw))
    problems = atomic.verify_manifest(str(ck))
    assert problems and "CRC32 mismatch" in problems[0]
    # truncate -> size mismatch
    (ck / "a.bin").write_bytes(b"hell")
    assert any("size mismatch" in p for p in atomic.verify_manifest(str(ck)))


def test_commit_tag_dir_replaces_existing(tmp_path):
    d = str(tmp_path)
    old = tmp_path / "tag"
    old.mkdir()
    (old / "stale.bin").write_bytes(b"old")
    tmp = atomic.tmp_tag_dir(d, "tag")
    with open(os.path.join(tmp, "fresh.bin"), "wb") as f:
        f.write(b"new")
    final = atomic.commit_tag_dir(d, "tag", tmp)
    assert sorted(os.listdir(final)) == ["fresh.bin", "manifest.json"]
    assert not any(atomic.is_tmp_dir(n) for n in os.listdir(d))


def test_retry_io_retries_oserror_not_injected_crash():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert atomic.retry_io(flaky, retries=3, backoff_seconds=0.0,
                           sleep=lambda _: None) == "ok"
    assert calls["n"] == 3

    def crash():
        raise InjectedCrash("boom")

    with pytest.raises(InjectedCrash):
        atomic.retry_io(crash, retries=5, backoff_seconds=0.0,
                        sleep=lambda _: None)

    def always():
        raise OSError("permanent")

    with pytest.raises(OSError):
        atomic.retry_io(always, retries=2, backoff_seconds=0.0,
                        sleep=lambda _: None)


# --------------------------------------------------------------------- #
# recovery: tag scanning, fallback resolution, GC
# --------------------------------------------------------------------- #
def _fake_tag(root, name, step_ts):
    d = root / name
    d.mkdir()
    (d / "data.bin").write_bytes(b"payload-" + name.encode())
    atomic.write_manifest(str(d))
    os.utime(d, (step_ts, step_ts))
    return d


def test_resolve_intact_tag_fallback_and_tmp_ignored(tmp_path):
    _fake_tag(tmp_path, "global_step1", 1000)
    _fake_tag(tmp_path, "global_step2", 2000)
    bad = _fake_tag(tmp_path, "global_step3", 3000)
    (tmp_path / "global_step9.tmp.dead").mkdir()  # in-flight junk

    assert recovery.list_tags(str(tmp_path)) == [
        "global_step3", "global_step2", "global_step1"]

    # intact request resolves to itself
    tag, problems = recovery.resolve_intact_tag(str(tmp_path), "global_step2")
    assert (tag, problems) == ("global_step2", [])

    # corrupt the newest -> fallback to next-newest intact
    (bad / "data.bin").write_bytes(b"garbage!")
    tag, problems = recovery.resolve_intact_tag(
        str(tmp_path), None, latest_tag="global_step3")
    assert tag == "global_step2"
    assert problems

    # everything corrupt -> loud FileNotFoundError naming the dir
    for name in ("global_step1", "global_step2"):
        (tmp_path / name / "data.bin").write_bytes(b"garbage!")
    with pytest.raises(FileNotFoundError, match="no intact checkpoint"):
        recovery.resolve_intact_tag(str(tmp_path), None,
                                    latest_tag="global_step3")


def test_gc_respects_latest_and_keep_every(tmp_path):
    for i, step in enumerate([10, 20, 30, 40, 50]):
        _fake_tag(tmp_path, f"global_step{step}", 1000 + i)
    # latest deliberately points at an OLD tag
    atomic.write_latest_atomic(str(tmp_path), "global_step10")
    deleted = recovery.gc_checkpoints(
        str(tmp_path), keep_last_n=2, keep_every=30,
        latest_tag="global_step10")
    # newest two (50, 40) kept; 30 kept by keep_every; 10 is latest; 20 goes
    assert deleted == ["global_step20"]
    assert sorted(recovery.list_tags(str(tmp_path))) == [
        "global_step10", "global_step30", "global_step40", "global_step50"]


def test_rescue_interrupted_re_save_of_same_tag(tmp_path):
    """Crash inside commit_tag_dir's re-save window (old dir renamed
    aside, new dir not yet promoted): the intact aside copy is restored
    on the next load instead of being invisible/swept."""
    _fake_tag(tmp_path, "ckpt.old.abc12345", 1000)  # renamed-aside copy
    (tmp_path / "ckpt.tmp.dead").mkdir()            # unpromoted staging
    atomic.write_latest_atomic(str(tmp_path), "ckpt")
    tag, problems = recovery.resolve_intact_tag(str(tmp_path), None,
                                                latest_tag="ckpt")
    assert tag == "ckpt" and problems == []
    assert (tmp_path / "ckpt" / "data.bin").is_file()
    # cleanup never touches .old. copies (only .tmp. staging dirs)
    _fake_tag(tmp_path, "other.old.deadbeef", 2000)
    atomic.cleanup_tmp_dirs(str(tmp_path))
    assert (tmp_path / "other.old.deadbeef").is_dir()
    assert not (tmp_path / "ckpt.tmp.dead").exists()


def test_reserved_tag_markers_rejected(tmp_path):
    e = make_engine()
    run_steps(e, 1)
    for bad in ("model.tmp.v2", "x.old.y"):
        with pytest.raises(ValueError, match="reserved"):
            e.save_checkpoint(str(tmp_path), tag=bad)


def test_finalize_checkpoint_retry_idempotent(tmp_path):
    """A retry wrapper may re-invoke finalize after the commit rename
    succeeded (e.g. a transient `latest`-write error): the second call
    must complete instead of failing on the vanished staging dir."""
    from deepspeed_tpu.runtime.sharded_checkpoint import finalize_checkpoint
    tmp = atomic.tmp_tag_dir(str(tmp_path), "t")
    with open(os.path.join(tmp, "x.bin"), "wb") as f:
        f.write(b"data")
    finalize_checkpoint(str(tmp_path), "t", {"global_steps": 1},
                        tmp_dir=tmp)
    assert not os.path.isdir(tmp)
    finalize_checkpoint(str(tmp_path), "t", {"global_steps": 1},
                        tmp_dir=tmp)  # re-entry after commit
    with open(tmp_path / "latest") as f:
        assert f.read() == "t"
    assert recovery.tag_problems(str(tmp_path), "t") == []


# --------------------------------------------------------------------- #
# crash-mid-save -> resume loads the newest intact tag (acceptance:
# a kill between ANY two file writes leaves the run resumable)
# --------------------------------------------------------------------- #
def _crash_sweep(tmp_path, sharded):
    cfg = {"resilience": dict(RES_ON)}
    if sharded:
        cfg["checkpoint"] = {"sharded": True}
    saver = make_engine(**cfg)
    run_steps(saver, 2)
    saver.save_checkpoint(str(tmp_path), tag="ckpt1")
    snap1 = np_params(saver)
    run_steps(saver, 1)
    snap2 = np_params(saver)

    total = measure_save_bytes(
        lambda: saver.save_checkpoint(str(tmp_path / "probe"), tag="ckpt2"),
        path_prefix=str(tmp_path / "probe"))
    assert total > 0
    loader = make_engine(**cfg)

    budgets = sorted({0, 1, total // 4, total // 2, (3 * total) // 4,
                      total - 1})
    for budget in budgets:
        with crash_after_bytes(budget, path_prefix=str(tmp_path)):
            with pytest.raises(InjectedCrash):
                saver.save_checkpoint(str(tmp_path), tag="ckpt2")
        path, client = loader.load_checkpoint(str(tmp_path), tag=None)
        loaded_tag = os.path.basename(path)
        assert loaded_tag in ("ckpt1", "ckpt2"), path
        want = snap1 if loaded_tag == "ckpt1" else snap2
        assert_params_equal(np_params(loader), want)
        assert client["global_steps"] == (2 if loaded_tag == "ckpt1" else 3)


def test_crash_mid_save_resumes_dense(tmp_path):
    _crash_sweep(tmp_path, sharded=False)


def test_crash_mid_save_resumes_sharded(tmp_path):
    _crash_sweep(tmp_path, sharded=True)


def test_crc_corruption_falls_back_to_previous_tag(tmp_path):
    e = make_engine(resilience=dict(RES_ON))
    run_steps(e, 2)
    e.save_checkpoint(str(tmp_path), tag="ckpt1")
    snap1 = np_params(e)
    run_steps(e, 1)
    e.save_checkpoint(str(tmp_path), tag="ckpt2")

    # flip a byte inside ckpt2's model file: manifest CRC catches it
    model = tmp_path / "ckpt2" / "mp_rank_00_model_states.npz"
    raw = bytearray(model.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    model.write_bytes(bytes(raw))

    loader = make_engine(resilience=dict(RES_ON))
    path, client = loader.load_checkpoint(str(tmp_path), tag=None)
    assert os.path.basename(path) == "ckpt1"
    assert client["global_steps"] == 2
    assert_params_equal(np_params(loader), snap1)


def test_explicit_corrupt_tag_fails_fast_no_substitution(tmp_path):
    """An explicitly requested tag is a contract: verification failure
    must raise (naming the tag and the alternatives), never silently
    load different weights.  Fallback is reserved for tag=None resume."""
    e = make_engine(resilience=dict(RES_ON))
    run_steps(e, 2)
    e.save_checkpoint(str(tmp_path), tag="ckpt1")
    run_steps(e, 1)
    e.save_checkpoint(str(tmp_path), tag="ckpt2")
    model = tmp_path / "ckpt2" / "mp_rank_00_model_states.npz"
    raw = bytearray(model.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    model.write_bytes(bytes(raw))

    loader = make_engine(resilience=dict(RES_ON))
    with pytest.raises(FileNotFoundError) as ei:
        loader.load_checkpoint(str(tmp_path), tag="ckpt2")
    msg = str(ei.value)
    assert "ckpt2" in msg and "ckpt1" in msg and "verification" in msg


def test_engine_gc_keeps_recent_and_latest(tmp_path):
    e = make_engine(resilience={"enabled": True, "keep_last_n": 2})
    run_steps(e, 1)
    for _ in range(4):
        e.save_checkpoint(str(tmp_path))  # default tag global_step1
        run_steps(e, 1)
    tags = recovery.list_tags(str(tmp_path))
    assert len(tags) == 2
    from deepspeed_tpu.runtime.checkpoint import read_latest_tag
    assert read_latest_tag(str(tmp_path)) in tags


# --------------------------------------------------------------------- #
# fail-fast load errors (satellite: name the tag, the dir, the options)
# --------------------------------------------------------------------- #
def test_missing_tag_error_names_tag_dir_and_available(tmp_path):
    e = make_engine()
    run_steps(e, 1)
    e.save_checkpoint(str(tmp_path), tag="have")
    with pytest.raises(FileNotFoundError) as ei:
        e.load_checkpoint(str(tmp_path), tag="nope")
    msg = str(ei.value)
    assert "nope" in msg and str(tmp_path) in msg and "have" in msg


def test_partial_tag_error_mentions_partial(tmp_path):
    e = make_engine()
    run_steps(e, 1)
    e.save_checkpoint(str(tmp_path), tag="t")
    os.remove(tmp_path / "t" / "mp_rank_00_model_states.npz")
    with pytest.raises(FileNotFoundError, match="partial"):
        e.load_checkpoint(str(tmp_path), tag="t")


# --------------------------------------------------------------------- #
# training-health sentinel (bf16: the fp16 overflow skip never fires)
# --------------------------------------------------------------------- #
def sentinel_engine(policy, budget=3, **res_extra):
    return make_engine(
        bf16={"enabled": True},
        resilience={"enabled": True,
                    "sentinel": dict({"enabled": True, "policy": policy,
                                      "anomaly_budget": budget,
                                      "warmup_steps": 50}, **res_extra)})


def test_sentinel_unit_ewma_and_ksigma():
    s = TrainingSentinel(ewma_alpha=0.1, k_sigma=4.0, warmup_steps=5,
                         policy="skip_step", anomaly_budget=3)
    for i in range(20):
        assert not s.observe(i, 1.0 + 0.01 * (i % 3), grad_norm=0.5)
    assert s.observe(20, 100.0, grad_norm=0.5)  # k-sigma spike
    assert s.consecutive_anomalies == 1
    # spike did NOT poison the baseline
    assert s.loss_stat.mean < 2.0
    assert not s.observe(21, 1.0, grad_norm=0.5)
    assert s.consecutive_anomalies == 0
    # NaN flags even during a fresh warmup
    s2 = TrainingSentinel(warmup_steps=100)
    assert s2.observe(0, float("nan"))
    # state round-trips
    sd = s.state_dict()
    s3 = TrainingSentinel()
    s3.load_state_dict(sd)
    assert s3.anomalies_seen == s.anomalies_seen
    assert s3.loss_stat.mean == pytest.approx(s.loss_stat.mean)


def test_sentinel_nan_bf16_skips_then_aborts(tmp_path):
    e = sentinel_engine("skip_step", budget=3)
    it = run_steps(e, 2)
    snap = np_params(e)
    x, y = next(it)
    bad = poison_batch((x, y))

    # two poisoned steps: skipped via the per-leaf select path, weights
    # and optimizer state untouched, counters advance
    for k in range(2):
        e.backward(e.forward(*bad))
        e.step()
        assert e.sentinel.consecutive_anomalies == k + 1
    assert_params_equal(np_params(e), snap)
    assert e.skipped_steps == 2
    assert e.sentinel.counters() == {"anomalies_seen": 2,
                                     "steps_skipped": 2, "rewinds": 0,
                                     "health_events": 0}

    # third consecutive anomaly exhausts the budget -> structured abort
    e.backward(e.forward(*bad))
    with pytest.raises(SentinelAbort) as ei:
        e.step()
    diag = ei.value.diagnostic
    assert diag["consecutive_anomalies"] == 3
    assert diag["anomaly_budget"] == 3
    assert any("non-finite" in r for r in diag["reasons"])
    json.dumps(diag, default=str)  # structured = machine-readable

    # a healthy batch after recovery still trains (engine not wedged)
    e2 = sentinel_engine("skip_step")
    run_steps(e2, 2)
    assert e2.sentinel.anomalies_seen == 0


def test_sentinel_rewind_restores_last_good_checkpoint(tmp_path):
    e = sentinel_engine("rewind", budget=5)
    it = run_steps(e, 2)
    e.save_checkpoint(str(tmp_path), tag="good")
    snap = np_params(e)
    run_steps(e, 1)
    assert e.global_steps == 3

    x, y = next(it)
    e.backward(e.forward(*poison_batch((x, y))))
    e.step()
    assert e.global_steps == 2  # rewound
    assert_params_equal(np_params(e), snap)
    assert e.sentinel.rewinds == 1
    # anomaly bookkeeping survives the rewind (budget still counts down)
    assert e.sentinel.consecutive_anomalies == 1
    run_steps(e, 1)
    assert e.global_steps == 3
    assert e.sentinel.consecutive_anomalies == 0


def test_sentinel_warn_adapts_baseline_on_level_shift():
    """Policy 'warn' trains straight through a spike, so the baseline
    must follow a legitimate permanent level-shift (LR decay, curriculum
    boundary) and finite spikes must never exhaust the abort budget."""
    s = TrainingSentinel(ewma_alpha=0.2, k_sigma=4.0, warmup_steps=5,
                         policy="warn", anomaly_budget=3)
    for i in range(20):
        s.observe(i, 2.0)
    # permanent drop to 1.0: flagged at first, but the baseline adapts
    flagged = sum(bool(s.observe(20 + i, 1.0)) for i in range(30))
    assert flagged >= 1
    assert s.consecutive_anomalies == 0      # finite spikes never abort
    assert not s.over_budget
    assert s.loss_stat.mean == pytest.approx(1.0, abs=0.05)
    # non-finite still counts toward the budget under warn
    for i in range(3):
        s.observe(60 + i, float("nan"))
    assert s.over_budget


def test_sentinel_defers_fp16_scale_warmup_to_scaler():
    """fp16 dynamic loss scaling overflows scaled grads on purpose while
    the scale anneals down — the scaler skips those steps itself, and the
    sentinel must not count them toward the abort budget."""
    e = make_engine(
        fp16={"enabled": True},
        resilience={"enabled": True,
                    "sentinel": {"enabled": True, "policy": "skip_step",
                                 "anomaly_budget": 2}})
    run_steps(e, 6)  # would raise SentinelAbort if warmup overflow counted
    assert e.sentinel.anomalies_seen == 0
    assert e.skipped_steps > 0  # the scaler, not the sentinel, skipped


def test_sentinel_counters_roundtrip_through_checkpoint(tmp_path):
    e = sentinel_engine("skip_step", budget=10)
    it = run_steps(e, 2)
    x, y = next(it)
    e.backward(e.forward(*poison_batch((x, y))))
    e.step()
    assert e.skipped_steps == 1
    e.save_checkpoint(str(tmp_path), tag="c")

    e2 = sentinel_engine("skip_step", budget=10)
    e2.load_checkpoint(str(tmp_path), tag="c")
    assert e2.skipped_steps == 1
    assert e2.sentinel.counters() == {"anomalies_seen": 1,
                                      "steps_skipped": 1, "rewinds": 0,
                                      "health_events": 0}


# --------------------------------------------------------------------- #
# preemption: SIGTERM -> graceful stop + emergency tag -> resume
# --------------------------------------------------------------------- #
def test_sigterm_takes_emergency_checkpoint_and_resumes(tmp_path):
    cfg = {"resilience": {"enabled": True,
                          "preemption": {"enabled": True, "reraise": False,
                                         "save_dir": str(tmp_path)}}}
    e = make_engine(**cfg)
    try:
        it = run_steps(e, 2)
        os.kill(os.getpid(), signal.SIGTERM)  # delivered to our handler
        assert e._preemption.triggered
        x, y = next(it)
        e.backward(e.forward(x, y))
        with pytest.raises(TrainingInterrupted) as ei:
            e.step()  # step 3 applies, then the boundary hook fires
        tag = ei.value.emergency_tag
        assert tag == "emergency_step3"
        assert os.path.isdir(tmp_path / tag)

        e2 = make_engine(**cfg)
        path, client = e2.load_checkpoint(str(tmp_path), tag=None)
        assert os.path.basename(path) == tag
        assert e2.global_steps == 3
        assert_params_equal(np_params(e2), np_params(e))
        run_steps(e2, 1)  # resumes cleanly
        assert e2.global_steps == 4
    finally:
        for eng in (e, locals().get("e2")):
            if eng is not None and eng._preemption is not None:
                eng._preemption.uninstall()


def test_preemption_signals_config_validated():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
    base = {"train_micro_batch_size_per_gpu": 8}
    ok = DeepSpeedConfig(
        {**base, "resilience": {"preemption": {"signals": "SIGTERM"}}})
    assert ok.resilience_config.preemption.signals == ("SIGTERM",)
    with pytest.raises(DeepSpeedConfigError, match="SIGTREM"):
        DeepSpeedConfig(
            {**base, "resilience": {"preemption": {"signals": ["SIGTREM"]}}})


def test_preemption_handler_restores_prior_handlers():
    from deepspeed_tpu.runtime.resilience.preemption import PreemptionHandler
    prior = signal.getsignal(signal.SIGTERM)
    h = PreemptionHandler(signals=("SIGTERM",), reraise=False).install()
    assert signal.getsignal(signal.SIGTERM) == h._on_signal
    h.request_stop(signal.SIGTERM)
    with pytest.raises(TrainingInterrupted):
        h.finalize()
    assert signal.getsignal(signal.SIGTERM) == prior


# --------------------------------------------------------------------- #
# disabled-path regression: resilience off == pre-resilience behavior
# (except the atomic `latest` rename bugfix)
# --------------------------------------------------------------------- #
def test_disabled_layout_and_outputs_unchanged(tmp_path):
    e = make_engine()  # no resilience block at all
    assert e.sentinel is None and e._preemption is None
    run_steps(e, 2)
    e.save_checkpoint(str(tmp_path), tag="plain")
    # exact legacy file layout: no manifest, no tmp dirs, atomic latest
    assert sorted(os.listdir(tmp_path)) == ["latest", "plain"]
    assert sorted(os.listdir(tmp_path / "plain")) == [
        "ds_meta.json", "mp_rank_00_model_states.npz",
        "zero_pp_rank_0_mp_rank_00_optim_states.npz"]
    with open(tmp_path / "latest") as f:
        assert f.read() == "plain"
    with open(tmp_path / "plain" / "ds_meta.json") as f:
        assert "sentinel" not in json.load(f)["client_state"]

    # step outputs are identical with the block present-but-disabled
    e_dis = make_engine(resilience={"enabled": False})
    run_steps(e_dis, 2)
    assert_params_equal(np_params(e), np_params(e_dis))

    # ...and with atomic commits on, only the layout gains the manifest
    e_at = make_engine(resilience=dict(RES_ON))
    run_steps(e_at, 2)
    assert_params_equal(np_params(e), np_params(e_at))
    e_at.save_checkpoint(str(tmp_path / "at"), tag="plain")
    assert sorted(os.listdir(tmp_path / "at" / "plain")) == [
        "ds_meta.json", "manifest.json", "mp_rank_00_model_states.npz",
        "zero_pp_rank_0_mp_rank_00_optim_states.npz"]
    with np.load(tmp_path / "at" / "plain" / "mp_rank_00_model_states.npz",
                 allow_pickle=False) as a, \
            np.load(tmp_path / "plain" / "mp_rank_00_model_states.npz",
                    allow_pickle=False) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k])
