"""HLO-level SPMD audit (deepspeed_tpu/analysis/hlo_audit.py; ISSUE 14).

Three layers of coverage:

  * parser fixtures over synthetic optimized-HLO text — replica-group
    forms (explicit + iota), async start/done dedup, while trip-count
    weighting, conditional worst-branch accounting;
  * real-XLA fixtures that PROVOKE silent resharding — a mis-annotated
    pjit out_sharding forcing a compiler-inserted all-gather, a weight
    annotated sharded while the consumer needs it replicated — asserting
    the `silent_reshard` finding fires with source provenance (warning
    by default, error under analysis.require_spmd_match), plus clean
    traced-collective programs reconciling at divergence_ratio 1.0;
  * the cross-accounting regression over every docs/examples config:
    jaxpr-predicted wire within a tolerance band of the HLO-measured
    bytes, or carrying a named, asserted waiver — so future transports
    cannot silently fork the two accountings.
"""

import json
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.analysis import (
    AuditTarget, ProgramAuditError, RULE_SILENT_RESHARD,
    RULE_SPMD_DIVERGENCE, SpmdWaiver, audit_target_hlo, step_wire_bytes,
    walk_hlo_collectives)
from deepspeed_tpu.analysis.hlo_audit import HloProgram
from deepspeed_tpu.config import AnalysisConfig

REPO = Path(__file__).resolve().parents[2]
EXAMPLES = REPO / "docs" / "examples"
GOLDEN_HLO = REPO / "tests" / "unit" / "golden" / "gpt2_hlo_audit.json"


def _cfg(**kw) -> AnalysisConfig:
    return AnalysisConfig.from_dict(dict({"mode": "warn"}, **kw))


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("data",))


def _target(fn, *args, label="fixture", jit_kw=None, **target_kw):
    jit_kw = jit_kw or {}
    return AuditTarget(
        label, jax.make_jaxpr(fn)(*args),
        lower=lambda: jax.jit(fn, **jit_kw).lower(
            *args).compile().as_text(),
        **target_kw)


# --------------------------------------------------------------------- #
# parser fixtures: synthetic optimized-HLO text
# --------------------------------------------------------------------- #
_SYNTH_HLO = """\
HloModule jit_f, is_scheduled=true, num_partitions=8

%region_add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

%body.1 (p: (s32[], f32[16,32])) -> (s32[], f32[16,32]) {
  %p = (s32[], f32[16,32]) parameter(0)
  %gte = f32[16,32]{1,0} get-tuple-element((s32[], f32[16,32]) %p), index=1
  %ag = f32[128,32]{1,0} all-gather(f32[16,32]{1,0} %gte), channel_id=1, replica_groups=[1,8]<=[8], dimensions={0}, metadata={op_name="jit(f)/jit(main)/while/body/all_gather" source_file="a.py" source_line=3}
  %ar = f32[16,32]{1,0} all-reduce(f32[16,32]{1,0} %gte), channel_id=2, replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%region_add, metadata={op_name="jit(f)/jit(main)/while/body/psum" source_file="a.py" source_line=4}
  %c = s32[] constant(1)
  %i = s32[] get-tuple-element((s32[], f32[16,32]) %p), index=0
  %ip = s32[] add(s32[] %i, s32[] %c)
  ROOT %tup = (s32[], f32[16,32]) tuple(s32[] %ip, f32[16,32] %ar)
}

%cond.1 (p: (s32[], f32[16,32])) -> pred[] {
  %p = (s32[], f32[16,32]) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[16,32]) %p), index=0
  %n = s32[] constant(5)
  ROOT %lt = pred[] compare(s32[] %i, s32[] %n), direction=LT
}

ENTRY %main_spmd (param: f32[16,32]) -> f32[16,32] {
  %param = f32[16,32]{1,0} parameter(0)
  %ags = (f32[16,32]{1,0}, f32[128,32]{1,0}) all-gather-start(f32[16,32]{1,0} %param), channel_id=3, replica_groups=[1,8]<=[8], dimensions={0}
  %agd = f32[128,32]{1,0} all-gather-done((f32[16,32]{1,0}, f32[128,32]{1,0}) %ags)
  %deg = f32[16,32]{1,0} all-reduce(f32[16,32]{1,0} %param), channel_id=4, replica_groups={{0},{1},{2},{3},{4},{5},{6},{7}}, to_apply=%region_add
  %tup = (s32[], f32[16,32]) tuple(s32[] %deg, f32[16,32] %param)
  %w = (s32[], f32[16,32]) while((s32[], f32[16,32]) %tup), condition=%cond.1, body=%body.1, backend_config={"known_trip_count":{"n":"5"}}
  ROOT %out = f32[16,32]{1,0} get-tuple-element((s32[], f32[16,32]) %w), index=1
}
"""


def test_parser_walks_synthetic_module():
    prog = HloProgram(_SYNTH_HLO)
    assert prog.num_partitions == 8
    assert prog.entry == "main_spmd"
    recs = walk_hlo_collectives(prog, "synth")
    by_name = {r.name: r for r in recs}
    # async pair deduped to the start; gather priced at group-sized
    # output (operand 16*32*4 = 2048 B x 8 participants)
    assert "agd" not in by_name
    start = by_name["ags"]
    assert start.opcode == "all-gather" and start.wire_bytes == 2048 * 8
    assert start.mult == 1 and not start.traced
    # while body collectives trip-weighted by known_trip_count
    ag = by_name["ag"]
    assert ag.mult == 5 and ag.traced and ag.counted
    assert ag.wire_bytes == 2048 * 8
    assert ag.source == "a.py:3"
    # explicit replica groups: 2 groups of 4
    ar = by_name["ar"]
    assert (ar.group_size, ar.n_groups) == (4, 2)
    assert ar.traced and ar.counted and ar.wire_bytes == 2048
    # degenerate single-participant groups move no wire
    deg = by_name["deg"]
    assert deg.degenerate and deg.wire_bytes == 0


def test_parser_conditional_takes_worst_branch():
    text = """\
HloModule jit_c, num_partitions=4

%region_add (a: f32[], b: f32[]) -> f32[] {
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

%true.1 (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  ROOT %ar = f32[8]{0} all-reduce(f32[8]{0} %p), replica_groups={{0,1,2,3}}, to_apply=%region_add, metadata={op_name="jit(c)/psum"}
}

%false.1 (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  %ag = f32[32]{0} all-gather(f32[8]{0} %p), replica_groups={{0,1,2,3}}, dimensions={0}, metadata={op_name="jit(c)/all_gather"}
  ROOT %sl = f32[8]{0} slice(f32[32]{0} %ag), slice={[0:8]}
}

ENTRY %main (pr: pred[], p: f32[8]) -> f32[8] {
  %pr = pred[] parameter(0)
  %p = f32[8]{0} parameter(1)
  ROOT %c = f32[8]{0} conditional(pred[] %pr, f32[8]{0} %p, f32[8]{0} %p), true_computation=%true.1, false_computation=%false.1
}
"""
    recs = walk_hlo_collectives(HloProgram(text), "cond")
    assert {r.opcode for r in recs} == {"all-reduce", "all-gather"}
    # the gather branch is the worst (operand 8 elems * 4 B, output-
    # priced x4 participants = 128 B vs the reduce's 32 B): only it is
    # charged into the totals; the other branch keeps its TRUE wire
    # (the reshard classifier must still see it) but charged=False
    by_op = {r.opcode: r for r in recs}
    assert by_op["all-gather"].wire_bytes == 8 * 4 * 4
    assert by_op["all-gather"].charged
    assert by_op["all-reduce"].wire_bytes == 8 * 4
    assert not by_op["all-reduce"].charged
    assert all(r.in_branch for r in recs)


def test_uncharged_branch_reshard_still_flags():
    """A compiler-inserted gather in the CHEAPER conditional branch
    must still produce a silent_reshard finding — only one branch
    executes per step, but both are real code that can run."""
    text = """\
HloModule jit_c, num_partitions=4

%region_add (a: f32[], b: f32[]) -> f32[] {
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

%true.1 (p: f32[65536]) -> f32[65536] {
  %p = f32[65536]{0} parameter(0)
  ROOT %ar = f32[65536]{0} all-reduce(f32[65536]{0} %p), replica_groups={{0,1,2,3}}, to_apply=%region_add, metadata={op_name="jit(c)/psum"}
}

%false.1 (p: f32[65536]) -> f32[65536] {
  %p = f32[65536]{0} parameter(0)
  %sl0 = f32[4096]{0} slice(f32[65536]{0} %p), slice={[0:4096]}
  %ag = f32[16384]{0} all-gather(f32[4096]{0} %sl0), replica_groups={{0,1,2,3}}, dimensions={0}
  ROOT %pd = f32[65536]{0} pad(f32[16384]{0} %ag, f32[] %p), padding=0_49152
}

ENTRY %main (pr: pred[], p: f32[65536]) -> f32[65536] {
  %pr = pred[] parameter(0)
  %p = f32[65536]{0} parameter(1)
  ROOT %c = f32[65536]{0} conditional(pred[] %pr, f32[65536]{0} %p, f32[65536]{0} %p), true_computation=%true.1, false_computation=%false.1
}
"""
    target = AuditTarget("cond", jax.make_jaxpr(lambda x: x + 1)(1.0),
                         lower=lambda: text)
    # traced psum in the worst branch is charged; the inserted gather
    # in the cheaper branch is uncharged but still classified
    cfg = _cfg(spmd_reshard_min_mb=0.0001, require_spmd_match=True)
    audit, findings = audit_target_hlo(target, cfg, jaxpr_wire_bytes=0)
    reshards = [f for f in findings if f.rule == RULE_SILENT_RESHARD]
    assert reshards and reshards[0].severity == "error"
    assert audit.n_silent_reshards == 1
    # ...without contaminating the charged byte totals: only the worst
    # branch's traced psum (65536 f32 operand) is charged
    assert audit.reshard_bytes == 0
    assert audit.matched_wire_bytes == 65536 * 4
    assert audit.hlo_wire_bytes == 65536 * 4


def test_unverified_targets_do_not_skew_divergence():
    """An errored/skipped target's jaxpr wire must not drag the summary
    divergence ratio below 1 — unverified is its own state, not
    'XLA optimized the wire away'."""
    from deepspeed_tpu.analysis import summarize_hlo
    from deepspeed_tpu.analysis.hlo_audit import HloTargetAudit
    ok = HloTargetAudit(target="good", jaxpr_wire_bytes=1000,
                        matched_wire_bytes=1000)
    bad = HloTargetAudit(target="doomed", jaxpr_wire_bytes=1000,
                         error="XlaRuntimeError: UNIMPLEMENTED")
    payload = summarize_hlo([(ok, 1), (bad, 1)])
    assert payload["divergence_ratio"] == 1.0
    assert payload["n_unverified_targets"] == 1
    assert payload["targets"]["doomed"]["verified"] is False
    assert payload["targets"]["doomed"]["divergence_ratio"] is None
    assert bad.divergence_ratio is None


def test_compile_failure_escalates_under_require_spmd_match():
    """The gate posture must FAIL when a target cannot be
    cross-checked, not pass with the audit silently disabled."""
    def boom():
        raise RuntimeError("UNIMPLEMENTED: PartitionId")
    target = AuditTarget("doomed", jax.make_jaxpr(lambda x: x + 1)(1.0),
                         lower=boom)
    _audit, findings = audit_target_hlo(
        target, _cfg(require_spmd_match=True), 0)
    assert findings and findings[0].severity == "error"
    # a wire-carrying target with NO lowering hook is equally unverified
    hookless = AuditTarget("bare", jax.make_jaxpr(lambda x: x + 1)(1.0))
    audit2, findings2 = audit_target_hlo(
        hookless, _cfg(require_spmd_match=True), 4096)
    assert audit2.skipped and findings2
    assert "no lowering hook" in findings2[0].message
    # ...but fixture targets under the default posture stay silent
    _a, none = audit_target_hlo(hookless, _cfg(), 4096)
    assert none == []


# --------------------------------------------------------------------- #
# real-XLA fixtures: silent reshards provoked and caught
# --------------------------------------------------------------------- #
def test_misannotated_out_sharding_flags_silent_reshard():
    """The ISSUE 14 acceptance fixture: a pjit out_sharding demanding
    replication of data-sharded compute makes GSPMD insert an
    all-gather AFTER tracing — the jaxpr sees zero collectives, the
    compiled program moves the whole tensor.  warning by default,
    error-severity under require_spmd_match."""
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    def f(x, w):
        return x @ w

    xs = jax.ShapeDtypeStruct((64, 128), jnp.float32, sharding=sh)
    ws = jax.ShapeDtypeStruct((128, 256), jnp.float32, sharding=rep)
    target = _target(f, xs, ws, jit_kw={"out_shardings": rep})
    # the jaxpr-level story is empty — that is the blind spot
    assert step_wire_bytes(target.closed_jaxpr)[0] == 0

    cfg = _cfg(spmd_reshard_min_mb=0.001)
    audit, findings = audit_target_hlo(target, cfg, jaxpr_wire_bytes=0)
    reshards = [f for f in findings if f.rule == RULE_SILENT_RESHARD]
    assert reshards, [f.format() for f in findings]
    assert all(f.severity == "warning" for f in reshards)
    assert audit.n_silent_reshards > 0 and audit.reshard_bytes > 0
    assert "all-gather" in reshards[0].message
    assert "jaxpr-level wire accounting never saw" in reshards[0].message

    # escalation: the CI posture
    cfg_err = _cfg(spmd_reshard_min_mb=0.001, require_spmd_match=True)
    _audit, findings_err = audit_target_hlo(target, cfg_err,
                                            jaxpr_wire_bytes=0)
    assert any(f.rule == RULE_SILENT_RESHARD and f.severity == "error"
               for f in findings_err)
    with pytest.raises(ProgramAuditError):
        from deepspeed_tpu.analysis import enforce, AuditReport
        enforce(AuditReport(findings=findings_err), "error")


def test_layout_flip_on_replicated_weight_matmul_flags_reshard():
    """Second fixture class: a replicated-weight matmul whose output
    annotation disagrees with the layout the math produces (row-sharded
    activations in, column-sharded output demanded) — GSPMD inserts a
    layout-flip transport (all-to-all / collective-permute /
    all-gather) the jaxpr never traced.  Every finding names a cause:
    the inserted op's own metadata, or the sharding-boundary wording."""
    mesh = _mesh()
    rows = NamedSharding(mesh, P("data", None))
    cols = NamedSharding(mesh, P(None, "data"))
    rep = NamedSharding(mesh, P())

    def f(x, w):
        return jnp.tanh(x) @ w

    xs = jax.ShapeDtypeStruct((64, 512), jnp.float32, sharding=rows)
    ws = jax.ShapeDtypeStruct((512, 512), jnp.float32, sharding=rep)
    target = _target(f, xs, ws, jit_kw={"out_shardings": cols})
    cfg = _cfg(spmd_reshard_min_mb=0.0001)
    audit, findings = audit_target_hlo(target, cfg, jaxpr_wire_bytes=0)
    reshards = [f for f in findings if f.rule == RULE_SILENT_RESHARD]
    assert reshards and audit.reshard_bytes > 0, \
        [(r.opcode, r.wire_bytes, r.op_name) for r in audit.collectives]
    # provenance: either the causing op's name or the sharding-boundary
    # wording — never a bare unexplained hit
    assert any(("inserted for" in f.message)
               or ("sharding boundary" in f.message) for f in reshards)


def test_named_waiver_absorbs_expected_resharding():
    """A declared sharding-contract waiver (the ZeRO param re-gather
    path) absorbs inserted gathers up to its byte budget — and is
    reported by name so tests can pin WHY the config is clean."""
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    def f(x):
        return x * 2.0

    xs = jax.ShapeDtypeStruct((64, 128), jnp.float32, sharding=sh)
    budget = 64 * 128 * 4 * 2
    target = _target(f, xs, jit_kw={"out_shardings": rep},
                     spmd_waivers=(SpmdWaiver("declared_regather",
                                              budget),))
    cfg = _cfg(spmd_reshard_min_mb=0.0, require_spmd_match=True)
    audit, findings = audit_target_hlo(target, cfg, jaxpr_wire_bytes=0)
    assert not [f for f in findings if f.rule == RULE_SILENT_RESHARD]
    assert audit.n_silent_reshards == 0
    assert audit.waived_reshard_bytes > 0
    assert audit.waivers and audit.waivers[0]["name"] == "declared_regather"
    assert audit.waivers[0]["absorbed_bytes"] == audit.waived_reshard_bytes


def test_traced_collectives_reconcile_at_ratio_one():
    """Clean program: explicit shard_map collectives inside a scan —
    the jaxpr wire accounting and the compiled program agree exactly
    (trip counts included), so no divergence finding fires."""
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data"))

    def region(x):
        g = jax.lax.all_gather(x, "data", tiled=True)
        return (x + g.sum(axis=0, keepdims=True)[:x.shape[0]]) * 0.5

    def f(x):
        def body(c, _):
            r = shard_map(region, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data"), check_rep=False)(c)
            return r, None
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    xs = jax.ShapeDtypeStruct((64, 128), jnp.float32, sharding=sh)
    target = _target(f, xs)
    jaxpr_wire, _ = step_wire_bytes(target.closed_jaxpr)
    assert jaxpr_wire > 0
    cfg = _cfg(require_spmd_match=True)
    audit, findings = audit_target_hlo(target, cfg,
                                       jaxpr_wire_bytes=jaxpr_wire)
    assert findings == [], [f.format() for f in findings]
    assert audit.matched_wire_bytes == jaxpr_wire
    assert audit.divergence_ratio == pytest.approx(1.0)
    # the scan survived as a while loop: trip weighting engaged
    assert any(r.mult == 5 for r in audit.collectives)


def test_divergence_finding_names_direction():
    """A target whose jaxpr claims wire the compiled program does not
    move trips the divergence rule and names the overprediction."""
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data"))

    def f(x):
        return x + 1.0

    xs = jax.ShapeDtypeStruct((64, 128), jnp.float32, sharding=sh)
    target = _target(f, xs)
    cfg = _cfg()
    audit, findings = audit_target_hlo(
        target, cfg, jaxpr_wire_bytes=10_000_000)
    div = [f for f in findings if f.rule == RULE_SPMD_DIVERGENCE]
    assert div and "OVERPREDICTION" in div[0].message
    assert audit.divergence_ratio == 0.0


def test_compile_failure_is_surfaced_not_fatal():
    """XLA refusing a program (the PartitionId seed-xfail class) must
    produce a warning finding naming the failure, never crash."""
    def boom():
        raise RuntimeError("UNIMPLEMENTED: PartitionId instruction is "
                           "not supported for SPMD partitioning")
    target = AuditTarget("doomed", jax.make_jaxpr(lambda x: x + 1)(1.0),
                         lower=boom)
    audit, findings = audit_target_hlo(target, _cfg(), 0)
    assert "PartitionId" in audit.error
    assert len(findings) == 1
    assert findings[0].severity == "warning"
    assert "UNVERIFIED" in findings[0].message


def test_hlo_only_wire_prices_into_exposed_lane():
    """The undercount fix: HLO-only wire raises the step-time lower
    bound through the exposed-comm lane."""
    from deepspeed_tpu.analysis import build_step_time_model
    cfg = _cfg()
    base = build_step_time_model(10 ** 9, 10 ** 6, [], cfg)
    with_hlo = build_step_time_model(10 ** 9, 10 ** 6, [], cfg,
                                     hlo_only_wire_bytes=10 ** 8)
    assert with_hlo["wire_bytes_hlo_only"] == 10 ** 8
    extra = 10 ** 8 / (cfg.hw_ici_gbps * 1e9)
    assert with_hlo["predicted_step_time_lb_s"] == pytest.approx(
        base["predicted_step_time_lb_s"] + extra)
    assert with_hlo["t_comm_exposed_s"] > base["t_comm_exposed_s"]


# --------------------------------------------------------------------- #
# engine-level: the audited programs the engine actually dispatches
# --------------------------------------------------------------------- #
def _tiny_engine(config_overrides=None):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    raw = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 6e-4}},
        "zero_optimization": {"stage": 2},
        "analysis": {"mode": "off"},
        "steps_per_print": 10 ** 9,
    }
    raw.update(config_overrides or {})
    cfg = GPT2Config(hidden_size=64, num_layers=2, num_heads=4,
                     n_positions=64, vocab_size=256)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    engine, _, _, _ = ds.initialize(model=model, config=raw,
                                    model_parameters=params)
    return engine


def test_engine_hlo_audit_clean_and_priced():
    """A clean stage-2 engine cross-checks with zero silent reshards;
    the compiled program's GSPMD wire (DP grad combine + ZeRO param
    re-gather) is surfaced and priced into the exposed lane, raising
    the lower bound vs the jaxpr-only model."""
    from deepspeed_tpu.analysis import audit_engine
    engine = _tiny_engine()
    cfg = _cfg(require_spmd_match=True)
    without = audit_engine(engine, cfg=cfg, multihost=False, hlo=False)
    report = audit_engine(engine, cfg=cfg, multihost=False, hlo=True)
    assert report.hlo["n_silent_reshards"] == 0
    assert not [f for f in report.findings
                if f.rule in (RULE_SILENT_RESHARD, RULE_SPMD_DIVERGENCE)]
    assert report.hlo_wire_bytes_per_step > 0
    assert report.hlo_collective_count > 0
    # stage-2: no explicit collectives traced, everything is HLO-only
    assert report.wire_bytes_per_step == 0
    assert report.hlo_divergence_ratio == 1.0
    assert (report.step_time["wire_bytes_hlo_only"]
            == report.hlo["hlo_only_wire_bytes_per_step"] > 0)
    assert (report.predicted_step_time_lb_s
            > without.predicted_step_time_lb_s)
    # the ZeRO param re-gather is absorbed by its NAMED waiver
    apply_audit = report.hlo["targets"]["apply_step"]
    assert any(w["name"] == "zero_param_regather"
               for w in apply_audit["waivers"])


def test_engine_init_runs_hlo_audit_from_config():
    """analysis.hlo_audit in the engine config runs the cross-check at
    init (the same surface CI's error mode gates)."""
    engine = _tiny_engine({"analysis": {
        "mode": "warn", "hlo_audit": True, "require_spmd_match": True}})
    assert engine.program_audit is not None
    assert engine.program_audit.hlo, "init audit must carry hlo payload"
    assert engine.program_audit.hlo["n_silent_reshards"] == 0


# --------------------------------------------------------------------- #
# cross-accounting regression (ISSUE 14 satellite): every example
# config's jaxpr wire within a tolerance band of the HLO-measured
# bytes — or carrying a NAMED, asserted waiver.  Future transports
# cannot silently fork the two accountings.
# --------------------------------------------------------------------- #
# config name -> (ratio_band, reason).  A waived config must land
# INSIDE its band — the waiver is itself an assertion, not an opt-out.
WIRE_WAIVERS = {
    # XLA unrolls the 2-group streamed layer scan on this tiny trace
    # model and CSEs the carried reverse-scan re-gathers; replicated
    # psums strength-reduce to multiplies.  The compiled program moves
    # LESS traced wire than the jaxpr predicts — overprediction, never
    # under.
    "gpt2_zero3_stream_analysis.json": ((0.55, 1.0), "xla_cse_regathers"),
    "gpt2_zero3_stream_fcm.json": ((0.55, 1.0), "xla_cse_regathers"),
}
WIRE_TOLERANCE = 0.05


@pytest.mark.slow
def test_examples_jaxpr_vs_hlo_wire_within_band(capsys):
    """Error-mode gate with the HLO cross-check enabled over every
    example config (the in-process twin of tier1.yml's workflow step),
    plus the wire-accounting band: zero unexplained divergence."""
    from deepspeed_tpu.analysis.cli import main as cli_main
    examples = sorted(EXAMPLES.glob("*.json"))
    assert (EXAMPLES / "gpt2_hlo_audit.json") in examples
    golden = json.loads(GOLDEN_HLO.read_text())
    for cfg_path in examples:
        ds.reset_mesh_context()
        rc = cli_main(["--config", str(cfg_path), "--mode", "error",
                       "--hlo-audit", "--json"])
        stdout = capsys.readouterr().out
        assert rc == 0, (f"{cfg_path.name} failed the error-mode "
                         f"HLO-audit gate:\n{stdout}")
        payload = json.loads(stdout[stdout.index("{\n"):])
        # a 1-bit-tier config is TWO audited programs (warmup +
        # compressed, cli.py); the wire band gates each phase
        phases = ([payload["phase_warmup"], payload["phase_compressed"]]
                  if "phase_warmup" in payload else [payload])
        for ph in phases:
            hlo = ph["hlo"]
            # zero UNEXPLAINED divergence: no silent reshards anywhere
            assert hlo["n_silent_reshards"] == 0, (cfg_path.name, hlo)
            assert hlo["reshard_bytes_per_step"] == 0
            ratio = hlo["divergence_ratio"]
            waiver = WIRE_WAIVERS.get(cfg_path.name)
            if waiver is not None:
                (lo, hi), reason = waiver
                assert lo <= ratio <= hi, (
                    f"{cfg_path.name} waived as {reason!r} but ratio "
                    f"{ratio} left its asserted band [{lo}, {hi}]")
            else:
                assert abs(ratio - 1.0) <= WIRE_TOLERANCE, (
                    f"{cfg_path.name}: jaxpr and HLO wire accountings "
                    f"forked (ratio {ratio}) with no named waiver")
        if cfg_path.name == "gpt2_hlo_audit.json":
            # the golden pins the clean compiled wire story exactly
            assert payload["signature"] == golden["signature"]
            assert (hlo["hlo_wire_bytes_per_step"]
                    == golden["hlo_wire_bytes_per_step"])
            assert (hlo["hlo_collective_count"]
                    == golden["hlo_collective_count"])
            assert golden["n_silent_reshards"] == 0
            assert golden["divergence_ratio"] == 1.0


def test_config_validation():
    from deepspeed_tpu.config import DeepSpeedConfigError
    cfg = _cfg(hlo_audit=True, require_spmd_match=True,
               spmd_reshard_min_mb=0.5, spmd_match_tolerance=0.1)
    assert cfg.hlo_audit and cfg.require_spmd_match
    assert cfg.spmd_reshard_min_mb == 0.5
    assert cfg.spmd_match_tolerance == 0.1
    with pytest.raises(DeepSpeedConfigError):
        _cfg(spmd_reshard_min_mb=-1)
    with pytest.raises(DeepSpeedConfigError):
        _cfg(spmd_match_tolerance=-0.1)
