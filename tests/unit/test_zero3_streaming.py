"""Explicit ZeRO-3 streaming (stage3_streaming.py): the
stage3_max_live_parameters / stage3_prefetch_bucket_size consumers.

Reference behavior being mirrored: stage3.py:294
PartitionedParameterCoordinator (gather-at-use, bounded live set, prefetch)
— here asserted as (a) plan math honoring the knobs, (b) trajectory equality
with the non-streamed baseline across group sizes / prefetch / TP.
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.zero.stage3_streaming import plan_layer_streaming

GLOBAL_BATCH = 8
SEQ = 32


def test_plan_honors_max_live():
    # 8 layers x 100 params; max_live 250 -> groups of 2, no prefetch room
    plan = plan_layer_streaming(num_layers=8, params_per_layer=100,
                                max_live_parameters=250,
                                prefetch_bucket_size=0)
    assert plan.layers_per_step == 2 and not plan.prefetch
    assert plan.live_parameters <= 250

    # prefetch halves the per-group budget (double buffer)
    plan = plan_layer_streaming(8, 100, 400, prefetch_bucket_size=100)
    assert plan.prefetch and plan.layers_per_step == 2
    assert plan.live_parameters <= 400

    # prefetch bucket smaller than a layer -> no prefetch
    plan = plan_layer_streaming(8, 100, 400, prefetch_bucket_size=50)
    assert not plan.prefetch and plan.layers_per_step == 4

    # budget can't hold two groups: max_live wins over prefetch
    plan = plan_layer_streaming(8, 100, 150, prefetch_bucket_size=100)
    assert not plan.prefetch and plan.layers_per_step == 1
    assert plan.live_parameters <= 150

    # group size always divides the layer count
    plan = plan_layer_streaming(6, 100, 500, 0)
    assert 6 % plan.layers_per_step == 0 and plan.layers_per_step == 3


def test_plan_degenerate():
    # max_live below one layer still streams one layer at a time
    plan = plan_layer_streaming(4, 1000, 10, 0)
    assert plan.layers_per_step == 1
    # unconstrained budget with prefetch: split into two overlapped groups
    # (same live set as one giant group, but the gathers overlap compute)
    plan = plan_layer_streaming(4, 10, 10 ** 9, 10 ** 9)
    assert plan.layers_per_step == 2 and plan.prefetch
    # carried mode has NO even-group-count constraint: 18 layers at a
    # 6-group budget take groups of 6 (3 groups) — the larger group size
    # the unrolled mode was forfeiting
    plan = plan_layer_streaming(18, 100, 1300, 100)
    assert plan.prefetch and plan.mode == "carried"
    assert plan.layers_per_step == 6
    # unrolled mode keeps the even constraint (18//6 = 3 is odd -> g=3)
    plan = plan_layer_streaming(18, 100, 1300, 100,
                                prefetch_mode="unrolled")
    assert not plan.prefetch or (18 // plan.layers_per_step) % 2 == 0


def test_plan_prefetch_modes():
    # off: never prefetches even with room to spare
    plan = plan_layer_streaming(8, 100, 10 ** 9, 10 ** 9,
                                prefetch_mode="off")
    assert not plan.prefetch and plan.mode == "off"
    assert plan.forfeited is None  # off was requested, nothing forfeited
    # unrolled on an odd prime layer count FORFEITS prefetch and says why
    plan = plan_layer_streaming(7, 100, 10 ** 9, 10 ** 9,
                                prefetch_mode="unrolled")
    assert not plan.prefetch and plan.mode == "off"
    assert plan.forfeited is not None and "EVEN" in plan.forfeited
    assert "carried" in plan.forfeited  # names the fix
    # carried handles the same shape: groups of 1, 7 carried steps
    plan = plan_layer_streaming(7, 100, 10 ** 9, 10 ** 9)
    assert plan.prefetch and plan.mode == "carried"
    assert plan.layers_per_step == 1
    # carried cannot form 2 groups from a single layer: forfeits loudly
    plan = plan_layer_streaming(1, 100, 10 ** 9, 10 ** 9)
    assert not plan.prefetch and plan.forfeited is not None
    # a bucket that asks for prefetch which max_live cannot double-buffer
    # is a forfeit too (bucket < one layer stays the silent off switch)
    plan = plan_layer_streaming(8, 100, 150, prefetch_bucket_size=100)
    assert not plan.prefetch and plan.forfeited is not None
    assert "double buffer" in plan.forfeited
    plan = plan_layer_streaming(8, 100, 150, prefetch_bucket_size=50)
    assert not plan.prefetch and plan.forfeited is None
    with pytest.raises(ValueError, match="stage3_prefetch_mode"):
        plan_layer_streaming(8, 100, 400, 100, prefetch_mode="eager")


def test_body_closing_over_tracers_is_diagnosed(monkeypatch):
    """NO streaming mode can differentiate a body that captures traced
    values (shard_map cannot transpose captured tracers; the carried
    custom_vjp differentiates only explicit inputs) — scan() must log
    the actionable diagnosis up front instead of leaving the user with
    a bare NotImplementedError / UnexpectedTracerError from deep inside
    grad.  A clean body stays carried and silent.  (The repo logger
    sets propagate=False, so capture the log_dist call itself.)"""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.zero import stage3_streaming as s3
    from deepspeed_tpu.runtime.zero.stage3_streaming import (
        Zero3StreamContext, _body_closes_over_tracers)

    logged = []
    monkeypatch.setattr(
        s3, "log_dist", lambda msg, *a, **k: logged.append(str(msg)))
    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    ctx = ds.get_mesh_context()
    stream = Zero3StreamContext(ctx, 10 ** 9, 10 ** 9)
    stacked = jnp.asarray(np.random.RandomState(0).randn(4, 8, 8),
                          jnp.float32) * 0.1
    x = jnp.ones((8, 8), jnp.float32)

    def loss(params, tied):
        def body(c, xs):
            return jnp.tanh(c @ xs[0]["w"] * tied), None  # tied: captured

        return stream.scan(body, x, {"w": params}, ()).sum()

    with pytest.raises(Exception):  # the pre-existing grad failure
        jax.jit(jax.grad(loss, argnums=(0, 1)))(stacked, jnp.float32(0.7))
    assert any("closes over traced values" in m for m in logged), logged
    logged.clear()

    # a clean body (everything threaded through the scan) stays carried
    # and does not warn
    stream2 = Zero3StreamContext(ctx, 10 ** 9, 10 ** 9)

    def clean_loss(params):
        def body(c, xs):
            return jnp.tanh(c @ xs[0]["w"]), None

        return stream2.scan(body, x, {"w": params}, ()).sum()

    jax.jit(jax.grad(clean_loss))(stacked)
    assert stream2.last_plan.mode == "carried"
    assert not any("closes over traced values" in m for m in logged)
    assert not _body_closes_over_tracers(lambda c, xs: (c, None))
    ds.reset_mesh_context()


def _train(zero_cfg: dict, tp: int = 1, steps: int = 3, num_layers: int = 4):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1, model=tp)
    cfg = GPT2Config(vocab_size=128, n_positions=SEQ, hidden_size=64,
                     num_layers=num_layers, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    dp = mesh.data_parallel_world_size
    conf = {
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zero_cfg,
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                        (GLOBAL_BATCH, SEQ), 0, 128),
                     np.int32)
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    final = jax.tree.map(np.asarray, engine.params)
    stream = engine._zero3_stream
    ds.reset_mesh_context()
    return losses, final, stream


# one layer of the test model ~ 4*64*64 + 2*64*256 + 9*64 + 256 = 50k params
LAYER_PARAMS = 4 * 64 * 64 + 2 * 64 * 256 + 9 * 64 + 256


@pytest.mark.parametrize("stream_cfg", [
    # one layer per group, no prefetch
    {"stage3_max_live_parameters": LAYER_PARAMS,
     "stage3_prefetch_bucket_size": 0},
    # one layer per group + double-buffer prefetch
    {"stage3_max_live_parameters": 2 * LAYER_PARAMS,
     "stage3_prefetch_bucket_size": 2 * LAYER_PARAMS},
    # two layers per group
    {"stage3_max_live_parameters": 2 * LAYER_PARAMS,
     "stage3_prefetch_bucket_size": 0},
])
def test_streaming_matches_baseline(stream_cfg):
    base_losses, base_params, _ = _train({"stage": 0})
    cfg = dict(stage=3, stage3_param_persistence_threshold=0, **stream_cfg)
    losses, params, stream = _train(cfg)
    assert stream is not None and stream.active
    plan = stream.plan_for(
        {"dummy": np.zeros((4,) + (LAYER_PARAMS,), np.float32)})
    # max_live honored by construction (one-layer floor: the stream cannot
    # gather less than a whole layer)
    assert plan.live_parameters <= max(stream.max_live_parameters,
                                       plan.params_per_layer)
    np.testing.assert_allclose(losses, base_losses, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_backward_regathers_instead_of_saving():
    """The gathered layer params must NOT be saved as scan residuals (that
    would materialize the full unsharded stack and defeat max_live); the
    backward pass re-gathers (reference: stage3.py:546 PreBackwardFunction
    re-fetch).  Visible in the jaxpr as all_gathers in both the forward
    scan body and the remat backward body."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)
    cfg = GPT2Config(vocab_size=128, n_positions=SEQ, hidden_size=64,
                     num_layers=4, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    conf = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 3, "stage3_param_persistence_threshold": 0,
            "stage3_max_live_parameters": LAYER_PARAMS,
            "stage3_prefetch_bucket_size": 0},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=conf,
                                    model_parameters=params, mesh=mesh,
                                    rng=jax.random.PRNGKey(7))
    ids = np.zeros((GLOBAL_BATCH, SEQ), np.int32)

    def loss_fn(p):
        return model.loss(p, None, ids)

    jaxpr = str(jax.make_jaxpr(jax.grad(loss_fn))(engine.params))
    assert jaxpr.count("all_gather") >= 2, \
        "expected all_gathers in both the forward scan and the remat backward"
    ds.reset_mesh_context()


def test_streaming_with_tensor_parallel():
    base_losses, base_params, _ = _train({"stage": 0})
    losses, params, stream = _train(
        {"stage": 3, "stage3_param_persistence_threshold": 0,
         "stage3_max_live_parameters": LAYER_PARAMS,
         "stage3_prefetch_bucket_size": LAYER_PARAMS}, tp=2)
    assert stream is not None and stream.active
    # TP=2 re-partitions the matmuls (and the chunked fused CE reassociates
    # its vocab sums) — the tolerance admits fp32 summation-order noise
    # but nothing structural.
    np.testing.assert_allclose(losses, base_losses, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(base_params)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_zero3_bf16_streams_on_cpu():
    """z3 + bf16 must run the EXPLICIT streaming path on every backend
    (regression: XLA CPU's AllReducePromotion used to hard-abort on the
    half-precision reduce-scatter the region's backward emits, forcing a
    GSPMD fallback; _all_gather_f32grad now runs that collective in fp32)."""
    import jax
    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=16,
                     num_layers=2, num_heads=2, bf16=True, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0},
                "steps_per_print": 10 ** 9},
        mesh=mesh, rng=jax.random.PRNGKey(7))
    stream = engine._zero3_stream
    assert stream is not None and stream.active
    # the streamed region really engages for the bf16 carry (no fallback)
    dummy_carry = jax.numpy.zeros((8, 16, 16), "bfloat16")
    assert stream.usable(dummy_carry, params=engine.params)
    ids = np.random.RandomState(0).randint(0, 64, (8, 16)).astype(np.int32)

    # the compiled grad graph must contain the streaming all_gathers
    def loss_fn(p):
        return model.loss(p, None, ids)
    jaxpr = str(jax.make_jaxpr(jax.grad(loss_fn))(engine.params))
    assert jaxpr.count("all_gather") >= 2, \
        "bf16 ZeRO-3 must take the explicit streaming path, not GSPMD"

    losses = []
    for _ in range(5):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def _train_tiny(zero_cfg, bf16=False, num_layers=5, steps=2,
                mesh_axes=None, seed_ids=1):
    """Fast trainer for the prefetch-mode parity matrix: tiny model, two
    steps, losses + final params.  Modes are compared against each other
    (same gather/quantization structure), so tolerances stay tight."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(**(mesh_axes or {"data": -1}))
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=num_layers, num_heads=4, bf16=bf16,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    conf = {
        "train_micro_batch_size_per_gpu": 8 // mesh.data_parallel_world_size
        if mesh.data_parallel_world_size <= 8 else 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zero_cfg,
        "steps_per_print": 10 ** 9,
    }
    if bf16:
        conf["bf16"] = {"enabled": True}
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(seed_ids),
                                        (8, 16), 0, 64), np.int32)
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    final = jax.tree.map(np.asarray, engine.params)
    plan = engine._zero3_stream.last_plan
    ds.reset_mesh_context()
    return losses, final, plan


def _mode_cfg(mode, extra=None):
    cfg = {"stage": 3, "stage3_param_persistence_threshold": 0,
           "stage3_max_live_parameters": 2 * 12832,
           "stage3_prefetch_bucket_size": 2 * 12832,
           "stage3_prefetch_mode": mode}
    cfg.update(extra or {})
    return cfg


@pytest.mark.parametrize("mode", ["carried", "unrolled"])
def test_carried_mode_parity_fp32(mode):
    """Prefetch-mode parity (ISSUE 7): the carried double-buffer program
    and the unrolled program must train identically to the at-use
    gather-per-group program — 5 layers, an ODD group count only the
    carried structure can prefetch."""
    l_off, p_off, plan_off = _train_tiny(_mode_cfg("off"))
    assert plan_off.mode == "off" and not plan_off.prefetch
    l_m, p_m, plan_m = _train_tiny(_mode_cfg(mode))
    if mode == "carried":
        assert plan_m.mode == "carried" and plan_m.prefetch
        assert plan_m.num_layers // plan_m.layers_per_step == 5
    np.testing.assert_allclose(l_m, l_off, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_m), jax.tree.leaves(p_off)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_carried_mode_parity_bf16():
    l_off, p_off, _ = _train_tiny(_mode_cfg("off"), bf16=True)
    l_car, p_car, plan = _train_tiny(_mode_cfg("carried"), bf16=True)
    assert plan.mode == "carried"
    # bf16 rounds differently under the two program structures (XLA
    # fuses the carried and at-use bodies differently); the tolerance
    # admits half-precision noise, nothing structural
    np.testing.assert_allclose(l_car, l_off, rtol=2e-4)
    # Adam normalizes bf16-rounded grads into O(lr) updates — a sign
    # flip on a near-zero gradient element diverges by 2 x lr x steps =
    # 4e-3 worst case — so params get an Adam-noise-ceiling atol while
    # the losses above carry the tight parity signal; a structural bug
    # would diff at O(1)
    for a, b in zip(jax.tree.leaves(p_car), jax.tree.leaves(p_off)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-3)
    assert l_car[-1] < l_car[0]  # still actually training


def test_carried_low_bandwidth_parity():
    """Carried prefetch composes with the qwZ quantized wire: both modes
    quantize identically (same blockwise layout, straight-through
    backward), so the trajectories match tightly."""
    lb = {"low_bandwidth": {"enabled": True, "qwz_bits": 8}}
    l_off, p_off, _ = _train_tiny(_mode_cfg("off", lb))
    l_car, p_car, plan = _train_tiny(_mode_cfg("carried", lb))
    assert plan.mode == "carried"
    np.testing.assert_allclose(l_car, l_off, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_car), jax.tree.leaves(p_off)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_carried_hpz_parity():
    """Carried prefetch composes with the hpZ sub-mesh fast path: the
    hot-loop gathers stay confined to the secondary axes in both modes,
    and the trajectories match."""
    lb = {"low_bandwidth": {"enabled": True, "hpz_group_size": 2}}
    mesh_axes = {"data": 4, "expert": 2}
    l_off, p_off, _ = _train_tiny(_mode_cfg("off", lb),
                                  mesh_axes=mesh_axes)
    l_car, p_car, plan = _train_tiny(_mode_cfg("carried", lb),
                                     mesh_axes=mesh_axes)
    assert plan.mode == "carried"
    np.testing.assert_allclose(l_car, l_off, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_car), jax.tree.leaves(p_off)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_stream_context_low_bandwidth_wiring():
    """Zero3StreamContext consumes the ZeroLowBandwidthConfig: hpZ
    confines the param manual set (and spec sizes) to the resolved
    sub-mesh; qwZ/qgZ route leaf gathers through the quantized
    collective (jaxpr shows the int8 payload riding the wire)."""
    import jax.numpy as jnp
    from deepspeed_tpu.config import ZeroLowBandwidthConfig
    from deepspeed_tpu.runtime.zero.stage3_streaming import Zero3StreamContext

    ds.reset_mesh_context()
    ds.initialize_mesh(data=4, expert=2)
    ctx = ds.get_mesh_context()

    # hpZ: param gathers confined to the inner axis, grads still span all
    lbc = ZeroLowBandwidthConfig(hpz_group_size=2)
    stream = Zero3StreamContext(ctx, 10 ** 9, 0, low_bandwidth=lbc)
    assert stream.manual == frozenset({"data", "expert"})
    assert stream.param_manual == frozenset({"expert"})
    assert stream.param_axis_sizes["data"] == 1
    assert stream.param_axis_sizes["expert"] == 2

    # qwZ: the quantized gather traces an int8 all_gather + fp32 scales
    lbc = ZeroLowBandwidthConfig(qwz_bits=8)
    stream = Zero3StreamContext(ctx, 10 ** 9, 0, low_bandwidth=lbc)

    def body(shard):
        return stream._gather_leaf(shard, ("data", "expert"), 0)

    from jax.sharding import PartitionSpec as P
    x = jnp.zeros((16, 8), jnp.float32)
    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        body, mesh=ctx.mesh, in_specs=P(("data", "expert")), out_specs=P(),
        check_vma=False))(x))
    assert "i8" in jaxpr and "all_gather" in jaxpr
    # off (or integer leaves) falls back to the fp32-transpose gather
    stream_off = Zero3StreamContext(ctx, 10 ** 9, 0)
    jaxpr_off = str(jax.make_jaxpr(jax.shard_map(
        lambda s: stream_off._gather_leaf(s, ("data", "expert"), 0),
        mesh=ctx.mesh, in_specs=P(("data", "expert")), out_specs=P(),
        check_vma=False))(x))
    assert "i8" not in jaxpr_off
    ds.reset_mesh_context()


def test_stream_context_per_direction_wire_gate():
    """_leaf_wire_bits degrades each direction independently: the
    forward gate compares against the leaf's native width, the backward
    against the fp32 wire the dense fallback actually moves
    (f32_psum_scatter promotes half grads) — so a bf16 leaf too skinny
    for qwZ still gets its qgZ reduce-scatter, and a truly skinny leaf
    (per-element scales) goes fully dense."""
    import jax.numpy as jnp
    from deepspeed_tpu.config import ZeroLowBandwidthConfig
    from deepspeed_tpu.runtime.zero.stage3_streaming import Zero3StreamContext

    ds.reset_mesh_context()
    ds.initialize_mesh(data=-1)
    ctx = ds.get_mesh_context()
    lbc = ZeroLowBandwidthConfig(qwz_bits=8, qgz_bits=8)
    stream = Zero3StreamContext(ctx, 10 ** 9, 0, low_bandwidth=lbc)

    wide = jnp.zeros((1, 64, 256), jnp.float32)
    assert stream._leaf_wire_bits(wide, 1) == (8, 8)
    # (2, 128) bf16 gathered along dim 1: rest=2 → fwd int8+scales (6B)
    # loses to native bf16 (4B) but beats the fp32 backward wire (8B)
    half = jnp.zeros((2, 128), jnp.bfloat16)
    assert stream._leaf_wire_bits(half, 1) == (0, 8)
    # rest=1 (bias, one layer per group): per-element scales lose to
    # both wires — fully dense
    bias = jnp.zeros((1, 128), jnp.float32)
    assert stream._leaf_wire_bits(bias, 1) == (0, 0)
    # integer leaves never quantize
    ints = jnp.zeros((1, 64, 256), jnp.int32)
    assert stream._leaf_wire_bits(ints, 1) == (0, 0)
    # lbc off → always dense
    off = Zero3StreamContext(ctx, 10 ** 9, 0)
    assert off._leaf_wire_bits(wide, 1) == (0, 0)
    ds.reset_mesh_context()


def test_stream_context_rejects_misaligned_hpz():
    """An hpz_group_size that doesn't match a ZeRO-axis suffix fails at
    context build with the valid sizes listed (engine-build-time error,
    not a mid-training trace surprise)."""
    from deepspeed_tpu.config import ZeroLowBandwidthConfig
    from deepspeed_tpu.runtime.zero.stage3_streaming import Zero3StreamContext

    ds.reset_mesh_context()
    ds.initialize_mesh(data=4, expert=2)
    ctx = ds.get_mesh_context()
    with pytest.raises(ValueError, match="hpz_group_size=3.*valid sizes"):
        Zero3StreamContext(ctx, 10 ** 9, 0,
                           low_bandwidth=ZeroLowBandwidthConfig(
                               hpz_group_size=3))
    ds.reset_mesh_context()
