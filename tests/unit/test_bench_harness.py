"""bench.py wedge-survival harness (the round-2 failure mode: a stale TPU
claim held the tunnel's single slot and jax.devices() hung forever in the
bench process — BENCH_r02 recorded 0.0).

These tests exercise the three safety nets on the CPU backend:
  1. subprocess slot probe (killable, unlike an in-process hang),
  2. the retry loop that waits out a stale claim,
  3. the SIGTERM handler that still emits the one-JSON-line contract when
     the driver times the bench out.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def test_probe_succeeds_on_cpu(monkeypatch):
    # the env's sitecustomize routes a bare jax.devices() at the real TPU
    # tunnel — tests must never touch it, so pin the probe to CPU
    monkeypatch.setenv("DS_BENCH_PROBE_PLATFORM", "cpu")
    ok, hung, info = bench._probe_tpu(timeout=120)
    assert ok, info
    assert not hung


def test_probe_kills_hung_subprocess(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_CODE", "import time; time.sleep(600)")
    t0 = time.time()
    ok, hung, info = bench._probe_tpu(timeout=2)
    assert not ok and hung
    assert time.time() - t0 < 60  # killed, not waited out


def test_await_slot_retries_until_reaped(monkeypatch):
    """Probes fail (stale claim) until the 'relay reaps' it; the loop must
    keep retrying and succeed once the slot frees."""
    calls = {"n": 0}

    def fake_probe(timeout):
        calls["n"] += 1
        if calls["n"] < 3:
            return False, False, "stale claim"
        return True, False, "cpu"

    monkeypatch.setattr(bench, "_probe_tpu", fake_probe)
    ok, info, waited, wedged = bench._await_tpu_slot(budget=60,
                                                     retry_delay=0.05)
    assert ok and calls["n"] == 3
    assert not wedged


def test_await_slot_caps_hung_probes(monkeypatch):
    """Round-4 failure mode (BENCH_r04): 8 x 180 s hung probes exhausted
    the driver window before the stale fallback spoke.  A probe that hangs
    to its timeout means a wedged transport, which never recovers within a
    bench window — the loop must give up after max_hung (2) hung probes
    even with budget to spare, while fast failures keep retrying."""
    monkeypatch.delenv("DS_BENCH_MAX_HUNG_PROBES", raising=False)
    monkeypatch.delenv("DS_BENCH_CONFIRM_PROBE_TIMEOUT", raising=False)
    calls = {"n": 0}
    timeouts = []

    def hung_probe(timeout):
        calls["n"] += 1
        timeouts.append(timeout)
        return False, True, f"probe hung >{timeout:.0f}s (stale TPU claim?)"

    monkeypatch.setattr(bench, "_probe_tpu", hung_probe)
    ok, info, waited, wedged = bench._await_tpu_slot(budget=3600,
                                                     retry_delay=0.05)
    assert not ok and calls["n"] == 2
    assert "wedged" in info
    assert wedged  # structured flag, not stderr sniffing
    # the stale claim is DETECTED once at the full probe window; the
    # confirmation probe runs at the short confirm_timeout (fail fast:
    # ~probe_timeout + confirm_timeout worst case, not 2 full windows)
    assert timeouts[0] == 180.0 and timeouts[1] == 60.0
    # fast failures (no hang) are NOT capped at 2 — they ride the budget,
    # even when the error text happens to contain the word "hung"
    calls["n"] = 0
    monkeypatch.setattr(
        bench, "_probe_tpu",
        lambda timeout: (calls.__setitem__("n", calls["n"] + 1),
                         (False, False,
                          "probe rc=1: remote end hung up unexpectedly"))[1])
    ok, info, waited, wedged = bench._await_tpu_slot(budget=0.5,
                                                     retry_delay=0.1)
    assert not ok and calls["n"] >= 2
    assert not wedged
    # env override widens the cap
    calls["n"] = 0
    monkeypatch.setenv("DS_BENCH_MAX_HUNG_PROBES", "4")
    monkeypatch.setattr(bench, "_probe_tpu", hung_probe)
    ok, info, waited, wedged = bench._await_tpu_slot(budget=3600,
                                                     retry_delay=0.05)
    assert not ok and calls["n"] == 4 and wedged


def test_await_slot_gives_up_at_budget(monkeypatch):
    monkeypatch.setattr(bench, "_probe_tpu",
                        lambda timeout: (False, False, "stale claim"))
    t0 = time.time()
    ok, info, waited, wedged = bench._await_tpu_slot(budget=1.0,
                                                     retry_delay=0.2)
    assert not ok and not wedged
    assert time.time() - t0 < 30
    # a single early hang followed by fast failures until the budget runs
    # out is a transport that ANSWERED again — budget exhaustion must not
    # stamp the wedge verdict (only the hung-probe cap may)
    monkeypatch.delenv("DS_BENCH_MAX_HUNG_PROBES", raising=False)
    calls = {"n": 0}

    def hang_then_fast(timeout):
        calls["n"] += 1
        if calls["n"] == 1:
            return False, True, "probe hung (transient stall)"
        return False, False, "probe rc=1: backend busy"

    monkeypatch.setattr(bench, "_probe_tpu", hang_then_fast)
    ok, info, waited, wedged = bench._await_tpu_slot(budget=0.5,
                                                     retry_delay=0.1)
    assert not ok and calls["n"] >= 2
    assert not wedged


def test_await_slot_hang_count_resets_on_fast_failure(monkeypatch):
    """Only CONSECUTIVE hangs are the wedge signature (BENCH_r04 was 8 in
    a row): a fast failure between two hangs proves the transport
    answered, so the hang count AND the shortened confirm window both
    reset — two unrelated transient stalls across a long budget must not
    stamp the wedge verdict."""
    monkeypatch.delenv("DS_BENCH_MAX_HUNG_PROBES", raising=False)
    monkeypatch.delenv("DS_BENCH_CONFIRM_PROBE_TIMEOUT", raising=False)
    calls = {"n": 0}
    timeouts = []

    def alternating(timeout):
        calls["n"] += 1
        timeouts.append(timeout)
        if calls["n"] % 2 == 1:
            return False, True, "probe hung (transient stall)"
        return False, False, "probe rc=1: backend busy"

    class FakeTime:
        # fake clock: keeps `remaining` above the probe window so the
        # min(limit, max(30, remaining)) clamp doesn't mask which window
        # the loop picked, without sleeping for real
        def __init__(self):
            self.t = 0.0

        def time(self):
            return self.t

        def sleep(self, s):
            self.t += s

    monkeypatch.setattr(bench, "_probe_tpu", alternating)
    monkeypatch.setattr(bench, "time", FakeTime())
    ok, info, waited, wedged = bench._await_tpu_slot(budget=1000.0,
                                                     retry_delay=30.0)
    assert not ok and not wedged
    assert calls["n"] >= 4  # two non-consecutive hangs rode the budget
    # after the fast failure resets the count, the window is FULL again
    # (a slow-but-alive backend probe is not miscounted as hang #2)
    assert timeouts[0] == 180.0 and timeouts[1] == 60.0
    assert timeouts[2] == 180.0 and timeouts[3] == 60.0


def test_sigterm_emits_one_diagnostic_json_line():
    """Driver-timeout path: TERM mid-run must still produce exactly one
    JSON line with the metric name and an error field.

    The probe platform is bogus so the bench sits in its slot-retry loop
    (an interruptible sleep) when the TERM arrives — TERMing inside a
    native XLA compile would defer the Python handler, which is fine for
    the real driver (its KILL grace is minutes) but would flake here."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DS_BENCH_PROBE_PLATFORM"] = "no_such_platform"
    env["DS_BENCH_ITERS"] = "1"
    # hermetic ladder: the stale-fallback assertion must not depend on
    # the repo's live (mutable, rotatable) results log
    import tempfile
    ladder = tempfile.NamedTemporaryFile(
        "w", suffix=".jsonl", delete=False)
    ladder.write(json.dumps(
        {"metric": "gpt2_124m_train_tokens_per_sec_1chip",
         "value": 99999.0, "unit": "tokens/s", "vs_baseline": 1.3,
         "platform": "tpu", "commit": "abc1234"}) + "\n")
    ladder.close()
    env["DS_BENCH_LADDER"] = ladder.name
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), "--config", "gpt2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=str(REPO))
    time.sleep(10)  # first probe fails (~5s), bench sleeps before retry
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, out
    payload = json.loads(lines[0])
    os.unlink(ladder.name)
    assert payload["metric"] == "gpt2_124m_train_tokens_per_sec_1chip"
    # outage-shaped failures degrade to the last on-chip measurement,
    # clearly labeled stale — not to an information-free 0.0
    assert payload["stale"] is True
    assert payload["value"] == 99999.0
    assert payload["stale_commit"] == "abc1234"
    assert payload["stale_source"] == ladder.name  # the file actually read
    assert "signal" in payload["error"]


def test_wedged_slot_marks_payload(tmp_path):
    """A wedged-transport slot failure (hung probes exhausted) stamps the
    structured `wedge_reason` marker on the one emitted JSON line, so
    watchers key on a field instead of grepping the error text."""
    script = (
        "import sys\n"
        "import bench\n"
        "bench._probe_tpu = lambda timeout: (False, True, 'probe hung')\n"
        # skip only the short retry_delay sleeps; the watchdog thread's
        # giant sleep must stay real or it wins the emission race
        "_sleep = bench.time.sleep\n"
        "bench.time.sleep = lambda s: None if s < 600 else _sleep(s)\n"
        "sys.argv = ['bench.py', '--config', 'gpt2']\n"
        "bench.main()\n"
    )
    env = dict(os.environ)
    env.pop("DS_BENCH_MAX_HUNG_PROBES", None)
    env.pop("DS_BENCH_SKIP_PROBE", None)
    env["DS_BENCH_WATCHDOG"] = str(10 ** 9)
    env["DS_BENCH_LADDER"] = str(tmp_path / "missing.jsonl")
    out = subprocess.run([sys.executable, "-c", script], cwd=str(REPO),
                         capture_output=True, text=True, timeout=120,
                         env=env)
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, out.stdout + out.stderr
    payload = json.loads(lines[0])
    assert payload["wedge_reason"] == "stale TPU claim / wedged transport"
    assert "hung probes" in payload["error"]
    assert payload["value"] == 0.0  # no ladder file -> diagnostic row


def test_last_measured_picks_latest_tpu_row(tmp_path, monkeypatch):
    """_last_measured returns the LAST real-chip row for the metric,
    skipping cpu rows, zero-value rows, and junk lines."""
    ladder = tmp_path / "benchmarks" / "ladder_results.jsonl"
    ladder.parent.mkdir()
    rows = [
        {"metric": "m", "value": 1.0, "platform": "tpu"},
        "not json at all",
        {"metric": "m", "value": 0.0, "platform": "tpu"},   # failed run
        {"metric": "m", "value": 7.0, "platform": "cpu"},   # not the chip
        {"metric": "other", "value": 9.0, "platform": "tpu"},
        {"metric": "m", "value": None, "platform": "tpu"},  # junk value
        {"metric": "m", "value": "x", "platform": "tpu"},   # junk value
        {"metric": "m", "value": 2.5, "platform": "tpu"},   # the winner
        # stale fallbacks / diagnostics must never be re-laundered
        {"metric": "m", "value": 9.9, "platform": "tpu", "stale": True},
        {"metric": "m", "value": 8.8, "platform": "tpu",
         "error": "watchdog"},
    ]
    ladder.write_text("\n".join(
        r if isinstance(r, str) else json.dumps(r) for r in rows) + "\n")
    monkeypatch.setenv("DS_BENCH_LADDER", str(ladder))
    row = bench._last_measured("m")
    assert row["value"] == 2.5
    assert bench._last_measured("absent") is None
    # no ladder file at all -> None (callers fall back to 0.0)
    monkeypatch.setenv("DS_BENCH_LADDER", str(tmp_path / "missing.jsonl"))
    assert bench._last_measured("m") is None


def test_degraded_retry_on_mosaic_failure(monkeypatch, capsys):
    """A compile-shaped failure (Mosaic/pallas in the message) triggers
    ONE retry with Pallas kernels disabled, and the emitted payload says
    so; a non-compile failure still takes the 0.0 diagnostic path."""
    from deepspeed_tpu.ops import dispatch

    calls = []

    def flaky_bench():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError(
                "INTERNAL: Mosaic failed to compile TPU kernel: boom")
        return {"metric": "gpt2_124m_train_tokens_per_sec_1chip",
                "value": 123.0, "unit": "tokens/s", "vs_baseline": 0.1}

    class FakeDev:
        platform = "cpu"
        device_kind = "fake"

    monkeypatch.setitem(bench.BENCHES, "gpt2", flaky_bench)
    monkeypatch.setattr(bench, "_init_backend", lambda: [FakeDev()])
    monkeypatch.setenv("DS_BENCH_SKIP_PROBE", "1")
    # in-process main(): neutralize its watchdog (a daemon thread that
    # would os._exit(0) the PYTEST process when the default 3000 s
    # expires) and restore the signal handlers it installs
    monkeypatch.setenv("DS_BENCH_WATCHDOG", str(10 ** 9))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--config", "gpt2"])
    prev_force = dispatch._force_xla
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    try:
        bench.main()
    finally:
        dispatch.force_xla_kernels(prev_force)
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(out) == 1, out
    payload = json.loads(out[-1])
    assert payload["value"] == 123.0
    assert "pallas kernels disabled" in payload["degraded"]
    assert len(calls) == 2

    # non-compile failure: no retry, diagnostic line
    calls.clear()

    def broken_bench():
        calls.append(1)
        raise ValueError("some unrelated failure")

    monkeypatch.setitem(bench.BENCHES, "gpt2", broken_bench)
    try:
        with pytest.raises(SystemExit):
            bench.main()
    finally:
        dispatch.force_xla_kernels(prev_force)
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    payload = json.loads(out[-1])
    assert payload["value"] == 0.0
    assert "unrelated" in payload["error"]
    assert len(calls) == 1

    # a message that merely MENTIONS pallas (dispatcher config errors)
    # is not compile-shaped: no degraded retry, the real error surfaces
    calls.clear()

    def config_error_bench():
        calls.append(1)
        raise RuntimeError(
            "impl='pallas' requested but pallas TPU support unavailable")

    monkeypatch.setitem(bench.BENCHES, "gpt2", config_error_bench)
    try:
        with pytest.raises(SystemExit):
            bench.main()
    finally:
        dispatch.force_xla_kernels(prev_force)
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    payload = json.loads(out[-1])
    assert payload["value"] == 0.0
    assert "unavailable" in payload["error"]
    assert len(calls) == 1  # no retry


def test_time_steps_gas_alignment(monkeypatch):
    """DS_BENCH_ITERS overrides are re-rounded to the accumulation
    boundary (align=gas), keeping whole optimizer steps in the window."""
    calls = {"n": 0}

    def step():
        calls["n"] += 1
        return 0.0

    monkeypatch.setenv("DS_BENCH_ITERS", "12")
    dt, _, n = bench._time_steps(step, warmup=1, iters=10, align=8)
    assert n == 16 and calls["n"] == 17  # 12 rounded up to 2 full cycles
    calls["n"] = 0
    monkeypatch.delenv("DS_BENCH_ITERS")
    dt, _, n = bench._time_steps(step, warmup=1, iters=10, align=3)
    assert n == 12 and calls["n"] == 13


def test_wall_budget_emits_and_exits_zero_before_driver_timeout():
    """Round-4 regression (BENCH_r04 rc=124): the probe loop outlived the
    driver's window, so the diagnostic line arrived only via the TERM
    handler and the run was still recorded as a timeout kill.  With
    DS_BENCH_WALL_BUDGET the bench must emit its one JSON line and exit 0
    ON ITS OWN CLOCK — no external signal."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DS_BENCH_PROBE_PLATFORM"] = "no_such_platform"  # wedge the probes
    env["DS_BENCH_WALL_BUDGET"] = "3"
    env.pop("DS_BENCH_LADDER", None)
    env["DS_BENCH_LADDER"] = "/nonexistent/ladder.jsonl"  # hermetic: 0.0 path
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--config", "gpt2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=str(REPO), timeout=120)
    elapsed = time.time() - t0
    assert proc.returncode == 0, proc.stdout
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    payload = json.loads(lines[0])
    assert payload["metric"] == "gpt2_124m_train_tokens_per_sec_1chip"
    assert "wall-clock budget" in payload["error"]
    # the whole point: the bench beat the (simulated) driver window
    assert elapsed < 60, f"budgeted bench took {elapsed:.0f}s"


def test_benches_and_metric_names_stay_in_sync():
    """Every --config has an error-path metric entry and vice versa, and
    the success-path metric a bench emits matches it — a drifted entry
    makes the failure JSON carry a DIFFERENT metric name than the
    success row, orphaning the stale-fallback lookup (bench.py's
    _last_measured matches by metric name)."""
    import bench
    assert set(bench.BENCHES) == set(bench.METRIC_NAMES)
    # spot-verify the parameterized rows' success metric == error metric
    assert bench.METRIC_NAMES["bert_s512"][0] == \
        "bert_large_z2_s512_samples_per_sec_1chip"
    assert bench.METRIC_NAMES["bert_z2"][0] == \
        "bert_large_z2_samples_per_sec_1chip"
    assert bench.METRIC_NAMES["gpt2_b16"][0] == \
        "gpt2_124m_b16_train_tokens_per_sec_1chip"
    assert bench.METRIC_NAMES["gpt2_b32"][0] == \
        "gpt2_124m_b32_train_tokens_per_sec_1chip"
    assert bench.METRIC_NAMES["gpt2_medium"][0] == \
        "gpt2_355m_train_tokens_per_sec_1chip"
    assert bench.METRIC_NAMES["gpt2_large"][0] == \
        "gpt2_774m_train_tokens_per_sec_1chip"
