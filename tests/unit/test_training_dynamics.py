"""MoQ quantizer, eigenvalue, curriculum, PLD, CSR, activation
checkpointing (reference tests: test_lr_schedulers/test_pld-style unit
coverage; activation ckpt equivalence mirrors
test_activation_checkpointing.py:289)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.csr_tensor import CSRTensor
from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.quantize import quantize_dequantize
from deepspeed_tpu.runtime import activation_checkpointing as ac


# ---------------------------------------------------------------------- #
# quantize
# ---------------------------------------------------------------------- #
def test_quantize_dequantize_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 64))
    for bits in (8, 4):
        q = quantize_dequantize(x, bits, groups=4)
        step = (2 * float(jnp.abs(x).max())) / (2 ** bits - 2)
        assert float(jnp.abs(q - x).max()) <= step

    asym = quantize_dequantize(x, 8, groups=2, symmetric=False)
    assert float(jnp.abs(asym - x).max()) < 0.05


def test_quantizer_schedule():
    cfg = ds.DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "quantize_training": {
            "enabled": True,
            "quantize_schedule": {
                "quantize_period": 10,
                "schedule_offset": 0},
            "quantize_groups": 2,
            "quantize_bits": {"start_bits": 16, "target_bits": 4},
            "quantize_verbose": False,
        },
    })
    from deepspeed_tpu.runtime.quantize import Quantizer
    qz = Quantizer(cfg.quantize_training_config)
    bits = [qz.update_bits(s) for s in range(0, 80, 5)]
    assert bits[0] == 16
    assert min(bits) == 4
    assert sorted(set(bits), reverse=True) == [16, 8, 4]


def test_engine_moq_integration():
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    params = {"w": np.random.RandomState(0).randn(8, 4).astype(np.float32)}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "quantize_training": {
            "enabled": True,
            "quantize_schedule": {"quantize_period": 1,
                                  "schedule_offset": 0},
            "quantize_bits": {"start_bits": 8, "target_bits": 8},
        },
        "steps_per_print": 10 ** 9,
    }
    eng, _, _, _ = ds.initialize(model=model, config=cfg,
                                 model_parameters=params, mesh=mesh)
    assert eng.quantizer is not None
    rs = np.random.RandomState(1)
    x, y = rs.randn(8, 8).astype(np.float32), rs.randn(8, 4).astype(
        np.float32)
    for _ in range(3):
        loss = eng.forward(x, y); eng.backward(loss); eng.step()
    # post-step weights live on an 8-bit grid
    w = np.asarray(eng.params["w"], np.float64)
    scale = np.abs(w).max() / 127.0
    np.testing.assert_allclose(w / scale, np.round(w / scale), atol=1e-3)


# ---------------------------------------------------------------------- #
# eigenvalue
# ---------------------------------------------------------------------- #
def test_eigenvalue_quadratic():
    """For loss = x^T A x / 2, the Hessian is A — power iteration must find
    its dominant eigenvalue."""
    evals = np.array([5.0, 2.0, 1.0], np.float32)
    a = np.diag(evals)

    def loss(params):
        x = params["x"]
        return 0.5 * x @ jnp.asarray(a) @ x

    est, vec = Eigenvalue(max_iter=50, tol=1e-4).compute_eigenvalue(
        loss, {"x": jnp.ones((3,), jnp.float32)}, jax.random.PRNGKey(0))
    assert abs(est - 5.0) < 0.1
    v = np.abs(np.asarray(vec["x"]))
    assert v[0] > 0.99  # eigenvector along the dominant axis


# ---------------------------------------------------------------------- #
# curriculum
# ---------------------------------------------------------------------- #
def test_curriculum_fixed_linear():
    sch = CurriculumScheduler({
        "curriculum_type": "fixed_linear",
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    assert sch.update_difficulty(0) == 8
    assert sch.update_difficulty(50) == 32
    assert sch.update_difficulty(100) == 64
    assert sch.update_difficulty(500) == 64


def test_curriculum_fixed_discrete():
    sch = CurriculumScheduler({
        "curriculum_type": "fixed_discrete",
        "min_difficulty": 4, "max_difficulty": 16,
        "schedule_config": {"difficulty": [4, 8, 16],
                            "max_step": [10, 20]}})
    assert sch.update_difficulty(5) == 4
    assert sch.update_difficulty(15) == 8
    assert sch.update_difficulty(25) == 16


def test_engine_curriculum_truncates():
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)
    seen = []

    def model(params, rng, ids):
        seen.append(ids.shape)
        return jnp.mean((params["w"][ids]) ** 2)

    params = {"w": np.random.RandomState(0).randn(32, 4).astype(np.float32)}
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "curriculum_learning": {
            "enabled": True,
            "curriculum_type": "fixed_linear",
            "min_difficulty": 8, "max_difficulty": 16,
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 8}},
        "steps_per_print": 10 ** 9,
    }
    eng, _, _, _ = ds.initialize(model=model, config=cfg,
                                 model_parameters=params, mesh=mesh)
    ids = np.zeros((2, 16), np.int32)
    for _ in range(5):
        loss = eng.forward(ids); eng.backward(loss); eng.step()
    lens = sorted({s[1] for s in seen})
    assert lens[0] == 8 and lens[-1] == 16  # grew with difficulty


# ---------------------------------------------------------------------- #
# PLD
# ---------------------------------------------------------------------- #
def test_pld_theta_decay():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0
    thetas = [pld.update_state(s) for s in (0, 100, 1000, 10 ** 6)]
    assert thetas[0] == pytest.approx(1.0)
    assert all(a >= b for a, b in zip(thetas, thetas[1:]))
    assert thetas[-1] == pytest.approx(0.5, abs=1e-3)
    assert pld.get_state()["progressive_layer_drop"] is True


def test_engine_pld_injected_into_gpt2():
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=32, hidden_size=32,
                     num_layers=4, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    conf = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "progressive_layer_drop": {"enabled": True, "theta": 0.1,
                                   "gamma": 0.001},
        "steps_per_print": 10 ** 9,
    }
    eng, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh)
    assert eng.pld_enabled()
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (2, 16),
                                        0, 64), np.int32)
    for _ in range(2):
        loss = eng.forward(ids); eng.backward(loss); eng.step()
    assert eng.pld_theta() < 1.0 or eng.global_steps == 2
    # with theta=1.0 (keep everything) the PLD path must equal the plain one
    p0 = model.init_params(jax.random.PRNGKey(0))
    r = jax.random.PRNGKey(2)
    plain = model.loss(p0, r, ids)
    pld1 = model.loss(p0, r, ids, pld_theta=1.0)
    np.testing.assert_allclose(float(plain), float(pld1), rtol=1e-6)
    # theta near 0 drops deep layers -> different loss
    pld0 = model.loss(p0, r, ids, pld_theta=0.01)
    assert abs(float(pld0) - float(plain)) > 1e-6


# ---------------------------------------------------------------------- #
# CSR
# ---------------------------------------------------------------------- #
def test_csr_roundtrip_and_add():
    dense = np.zeros((10, 4), np.float32)
    dense[2] = 1.0
    dense[7] = 2.0
    csr = CSRTensor.from_dense(jnp.asarray(dense))
    assert csr.nnz_rows == 2
    assert csr.sparsity() == pytest.approx(0.8)
    np.testing.assert_array_equal(np.asarray(csr.to_dense()), dense)

    other = np.zeros((10, 4), np.float32)
    other[7] = 3.0
    total = csr.add(CSRTensor.from_dense(jnp.asarray(other)))
    np.testing.assert_allclose(np.asarray(total.to_dense())[7], 5.0)


# ---------------------------------------------------------------------- #
# activation checkpointing
# ---------------------------------------------------------------------- #
def test_checkpoint_equivalence():
    """Remat must not change values or gradients (reference:
    test_activation_checkpointing.py:289)."""
    ac.reset()
    ac.configure(partition_activations=False)

    w = jax.random.normal(jax.random.PRNGKey(0), (16, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

    def block(w, x):
        return jnp.tanh(x @ w) @ w.T

    def loss_plain(w):
        return jnp.sum(block(w, x) ** 2)

    def loss_ckpt(w):
        return jnp.sum(ac.checkpoint(block, w, x) ** 2)

    np.testing.assert_allclose(float(loss_plain(w)), float(loss_ckpt(w)),
                               rtol=1e-6)
    g1 = jax.grad(loss_plain)(w)
    g2 = jax.grad(loss_ckpt)(w)
    # remat guarantees mathematical, not bitwise, equality: the
    # recomputed forward fuses differently (fma/reassociation), so the
    # backward drifts O(1e-5) relative on the CPU backend (seed ledger,
    # docs/COVERAGE.md).  1e-4 still catches a wrong-residual bug, which
    # shows up orders of magnitude larger.
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4)
    assert ac.is_configured()
    ac.reset()


def test_checkpoint_policies_selectable():
    ac.reset()
    ac.configure(partition_activations=True)
    assert ac.get_partition_policy() is jax.checkpoint_policies.dots_saveable
    ac.configure(checkpoint_in_cpu=True)
    assert ac.get_partition_policy() is not None
    ac.reset()
    ac.configure(deepspeed_config={
        "activation_checkpointing": {"partition_activations": True,
                                     "contiguous_memory_optimization": True}})
    assert ac.get_partition_policy() is jax.checkpoint_policies.dots_saveable
    ac.reset()
