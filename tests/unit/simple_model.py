"""Test model fixtures (modeled on reference tests/unit/simple_model.py:234 —
SimpleModel, random/linear dataset generators, args helpers)."""

import numpy as np

import jax
import jax.numpy as jnp


def simple_model_params(hidden_dim: int, nlayers: int = 2, seed: int = 0,
                        dtype=jnp.float32):
    """An MLP regression model: x → (Linear+relu)*n → Linear(1)."""
    rng = np.random.RandomState(seed)
    params = {}
    for i in range(nlayers):
        params[f"layer_{i}"] = {
            "w": jnp.asarray(
                rng.normal(0, 0.1, (hidden_dim, hidden_dim)), dtype),
            "b": jnp.zeros((hidden_dim,), dtype),
        }
    params["head"] = {
        "w": jnp.asarray(rng.normal(0, 0.1, (hidden_dim, 1)), dtype),
        "b": jnp.zeros((1,), dtype),
    }
    return params


def simple_model_apply(params, rng, x, y):
    """Returns MSE loss — the model-returns-loss contract of the engine."""
    h = x
    n = len([k for k in params if k.startswith("layer_")])
    for i in range(n):
        p = params[f"layer_{i}"]
        h = jax.nn.relu(h @ p["w"] + p["b"])
    pred = h @ params["head"]["w"] + params["head"]["b"]
    return jnp.mean((pred.squeeze(-1) - y) ** 2)


def random_dataset(total_samples: int, hidden_dim: int, seed: int = 12,
                   dtype=np.float32) -> list:
    rng = np.random.RandomState(seed)
    xs = rng.normal(0, 1, (total_samples, hidden_dim)).astype(dtype)
    w_true = rng.normal(0, 1.0 / np.sqrt(hidden_dim),
                        (hidden_dim,)).astype(dtype)
    ys = (xs @ w_true).astype(dtype)
    return [(xs[i], ys[i]) for i in range(total_samples)]


def random_dataloader(model_dim: int, total_samples: int, batch_size: int,
                      seed: int = 12):
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
    ds = random_dataset(total_samples, model_dim, seed)
    return DeepSpeedDataLoader(ds, batch_size=batch_size)


def base_engine_config(micro_batch: int = 8, gas: int = 1, **overrides):
    cfg = {
        "train_micro_batch_size_per_gpu": micro_batch,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(overrides)
    return cfg
