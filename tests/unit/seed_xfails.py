"""Shared markers for the triaged pre-existing seed failures
(ledger: docs/COVERAGE.md "Known failures").

One definition so that when the underlying fix lands, deleting the
marker here surfaces every silently-skipped test at once — a stale
per-file copy would keep its tests skipped after the bug is gone.
"""

import pytest

# The gated 1F1B executor's stage-index lowering emits a PartitionId
# instruction that XLA-CPU's SPMD partitioner rejects (UNIMPLEMENTED:
# "PartitionId instruction is not supported for SPMD partitioning").
# Deterministic compile-time error on this backend, so run=False; the
# real fix (stage ids as a sharded operand, or full-manual meshes) is a
# pipeline-executor PR of its own.
PARTITION_ID_XFAIL = pytest.mark.xfail(
    reason="XLA-CPU SPMD partitioner rejects the gated 1F1B executor's "
           "PartitionId lowering (pre-existing seed failure, "
           "docs/COVERAGE.md)", run=False)
