"""Shared markers for the triaged pre-existing seed failures
(ledger: docs/COVERAGE.md "Known failures").

One definition so that when the underlying fix lands, deleting the
marker here surfaces every silently-skipped test at once — a stale
per-file copy would keep its tests skipped after the bug is gone.
"""

import pytest

# The gated 1F1B executor's stage-index lowering emits a PartitionId
# instruction that XLA-CPU's SPMD partitioner rejects.  Deterministic
# compile-time error on this backend, so run=False; the real fix (stage
# ids as a sharded operand, or full-manual meshes) is a pipeline-
# executor PR of its own.
#
# Re-probed 2026-08-03 (round 18, while building the HLO-level SPMD
# audit — the cross-check pipeline compiles through the same
# partitioner): all 9 tests still fail at compile with the IDENTICAL
# signature below (jax 0.4.37 / jaxlib 0.4.36); none can be un-xfailed.
# The audit surfaces the same class gracefully: a target whose lowering
# raises gets a warning finding naming the failure instead of crashing
# (analysis/hlo_audit.py, test_compile_failure_is_surfaced_not_fatal).
#
# Precise XLA failure signature (assert against PARTITION_ID_SIGNATURE
# when probing — a DIFFERENT partitioner error means the bug moved, not
# that it is fixed):
PARTITION_ID_SIGNATURE = (
    "UNIMPLEMENTED: PartitionId instruction is not supported for SPMD "
    "partitioning since the meaning is ambiguous -- whether the "
    "instruction is replicated or the data is replicated, and if the "
    "latter which data is replicated.")

PARTITION_ID_XFAIL = pytest.mark.xfail(
    reason="XLA-CPU SPMD partitioner rejects the gated 1F1B executor's "
           "PartitionId lowering (pre-existing seed failure, "
           "docs/COVERAGE.md; signature re-verified round 18: "
           "PARTITION_ID_SIGNATURE)", run=False)
