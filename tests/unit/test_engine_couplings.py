"""Engine couplings the reference wires inside DeepSpeedEngine:
sparse (CSR-style) embedding-grad reduction (engine.py:1729-1792) and the
eigenvalue→MoQ schedule modulation (engine.py:1478-1485)."""

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model

SEQ = 32
GLOBAL_BATCH = 8


def _model(tied=False):
    # untied LM head: row-sparse embedding grads are only valid when the
    # wte grad is the pure embedding scatter (see GPT2Model.sparse_grad_paths)
    cfg = GPT2Config(vocab_size=128, n_positions=SEQ, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0,
                     tie_word_embeddings=tied)
    return GPT2Model(cfg)


def _train(extra_conf, steps=3, tp=1):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1, model=tp)
    model = _model()
    dp = mesh.data_parallel_world_size
    conf = {
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 10 ** 9,
    }
    conf.update(extra_conf)
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                        (GLOBAL_BATCH, SEQ), 0, 128),
                     np.int32)
    losses = []
    for _ in range(steps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    params = jax.tree.map(np.asarray, engine.params)
    ds.reset_mesh_context()
    return losses, params, engine


# ---------------------------------------------------------------------- #
# sparse_gradients
# ---------------------------------------------------------------------- #
def test_sparse_gradients_matches_dense():
    """The row-sparse (indices, values) reduction must be a pure layout
    change: identical trajectory to the dense allreduce."""
    dense_losses, dense_params, _ = _train({})
    losses, params, engine = _train({"sparse_gradients": True})
    np.testing.assert_allclose(losses, dense_losses, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(dense_params)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_sparse_gradients_uses_gathered_rows():
    """The compiled grad program must actually take the sparse path:
    all_gather of (indices, rows) appears in the jaxpr where the dense
    path has none."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)
    model = _model()
    conf = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "sparse_gradients": True,
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    ids = jax.numpy.zeros((8, SEQ), jax.numpy.int32)
    jaxpr = str(jax.make_jaxpr(
        lambda p, s, r: engine._grad_fn.__wrapped__(p, s, r, ids))(
        engine.params, engine.scaler_state, jax.random.PRNGKey(0)))
    assert "all_gather" in jaxpr
    ds.reset_mesh_context()


def test_sparse_gradients_rejects_zero2():
    with pytest.raises(ValueError, match="stage"):
        _train({"sparse_gradients": True,
                "zero_optimization": {"stage": 2}}, steps=1)
    ds.reset_mesh_context()


def test_sparse_gradients_rejects_tensor_parallel():
    with pytest.raises(ValueError, match="tensor"):
        _train({"sparse_gradients": True}, steps=1, tp=2)
    ds.reset_mesh_context()


# ---------------------------------------------------------------------- #
# eigenvalue -> MoQ
# ---------------------------------------------------------------------- #
def test_eigenvalue_drives_moq_schedule():
    conf = {
        "quantize_training": {
            "enabled": True, "quantize_bits": {"start_bits": 16,
                                               "target_bits": 8},
            "quantize_schedule": {"quantize_period": 1,
                                  "schedule_offset": 0},
        },
        "eigenvalue": {"enabled": True, "max_iter": 4, "tol": 0.1,
                       "gas_boundary_resolution": 1},
    }
    losses, params, engine = _train(conf, steps=3)
    # the probe ran and produced per-block curvature
    assert engine._block_eigs is not None and len(engine._block_eigs) >= 3
    assert all(np.isfinite(v) for v in engine._block_eigs.values())
    # the per-block schedule advanced (blocks dropped bits independently)
    blocks = engine.quantizer.state_dict()["block_state"]
    assert blocks and any(st["cur_bits"] < 16 for st in blocks.values())
    # curvature modulation: per-block periods may diverge from the global
    periods = {k: st["period"] for k, st in blocks.items()}
    assert len(periods) == len(engine._block_eigs)


def test_eigenvalue_disabled_keeps_global_schedule():
    conf = {
        "quantize_training": {
            "enabled": True, "quantize_bits": {"start_bits": 16,
                                               "target_bits": 8},
            "quantize_schedule": {"quantize_period": 1,
                                  "schedule_offset": 0},
        },
    }
    losses, params, engine = _train(conf, steps=2)
    assert engine._block_eigs is None
    assert engine.quantizer.cur_bits < 16  # global path advanced
