"""Program Auditor (deepspeed_tpu/analysis/, docs/program_auditor.md).

One deliberately-broken fixture per lint rule — host callback in a scan
body, undonated grad carry, divergent collective order, forced fp32
upcast, wire-budget blowup, retrace storm — asserting rule id, severity,
and provenance; plus clean-program zero-findings runs over the gpt2
modular and fused train steps, the shared jaxpr-walk regression pins
(remat2/shard_map/while-cond gaps, custom_vjp-bwd wire bytes), the
golden lockstep signature, the CLI exit-code contract, and the
checkpoint round-trip of the audit counters.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.analysis import (
    ArgInfo, AuditTarget, ProgramAuditor, ProgramAuditError,
    RecompileGuard, RULE_COMM_BUDGET, RULE_DONATION, RULE_DTYPE_HAZARD,
    RULE_HBM_BUDGET, RULE_HOST_SYNC, RULE_LOCKSTEP, RULE_OVERLAP,
    RULE_RECOMPILE, analyze_overlap, compare_lockstep, estimate_liveness,
    iter_eqns, lockstep_signature, overlap_efficiency, sub_jaxprs)
from deepspeed_tpu.config import AnalysisConfig, DeepSpeedConfigError

REPO = Path(__file__).resolve().parents[2]
GOLDEN = REPO / "tests" / "unit" / "golden" / "gpt2_lockstep_signature.json"
GOLDEN_STREAM = (REPO / "tests" / "unit" / "golden" /
                 "gpt2_zero3_stream_schedule.json")
GOLDEN_STREAM_SERIALIZED = (REPO / "tests" / "unit" / "golden" /
                            "gpt2_zero3_stream_schedule_serialized.json")
GOLDEN_STREAM_FCM = (REPO / "tests" / "unit" / "golden" /
                     "gpt2_zero3_stream_fcm_schedule.json")
GOLDEN_HLO_AUDIT = (REPO / "tests" / "unit" / "golden" /
                    "gpt2_hlo_audit.json")
EXAMPLE_CFG = REPO / "docs" / "examples" / "gpt2_analysis.json"
EXAMPLE_STREAM_CFG = (REPO / "docs" / "examples" /
                      "gpt2_zero3_stream_analysis.json")
EXAMPLE_FCM_CFG = (REPO / "docs" / "examples" /
                   "gpt2_zero3_stream_fcm.json")
EXAMPLE_HLO_CFG = REPO / "docs" / "examples" / "gpt2_hlo_audit.json"


def _cfg(**kw) -> AnalysisConfig:
    return AnalysisConfig.from_dict(dict({"mode": "warn"}, **kw))


def _target(fn, *args, label="fixture", args_info=None,
            **target_kw) -> AuditTarget:
    return AuditTarget(label, jax.make_jaxpr(fn)(*args),
                       args=args_info or [], **target_kw)


def _findings(target, cfg=None):
    return ProgramAuditor(cfg or _cfg()).run([target]).findings


# --------------------------------------------------------------------- #
# shared jaxpr walker (satellite: the unified sub-jaxpr dispatch)
# --------------------------------------------------------------------- #
def test_sub_jaxprs_dispatch_covers_higher_order_prims():
    jx = jax.make_jaxpr(
        jax.grad(lambda x: jax.checkpoint(
            lambda a: jnp.dot(a, a).sum())(x)))(jnp.ones((4, 4)))
    names = {c.eqn.primitive.name for c in iter_eqns(jx)}
    assert "remat2" in names and "dot_general" in names

    def wf(x):
        return lax.while_loop(lambda c: jnp.dot(c, c).sum() < 100,
                              lambda c: c + jnp.dot(c, c), x)
    jw = jax.make_jaxpr(wf)(jnp.ones((4, 4)))
    eqn = next(e for e in jw.jaxpr.eqns if e.primitive.name == "while")
    kinds = [s.kind for s in sub_jaxprs(eqn)]
    assert kinds == ["while_cond", "while_body"]
    # the cond jaxpr's dot is visible to the flat iterator (the old
    # flops walk missed while_cond entirely)
    dots = [c for c in iter_eqns(jw)
            if c.eqn.primitive.name == "dot_general"]
    assert len(dots) == 2


def test_flops_counts_remat_and_shard_map_regions():
    """Unification gap fix: jax.checkpoint emits `remat2` (the old
    dispatch listed only 'remat'/'checkpoint' and counted the region as
    1 flop/element), and shard_map regions were skipped entirely."""
    from deepspeed_tpu.profiling.flops_profiler import count_jaxpr_flops
    n = 32
    dot_flops = 2 * n * n * n

    plain = jax.make_jaxpr(lambda x: jnp.dot(x, x))(jnp.ones((n, n)))
    remat = jax.make_jaxpr(
        jax.checkpoint(lambda x: jnp.dot(x, x)))(jnp.ones((n, n)))
    bd_plain, bd_remat = {}, {}
    count_jaxpr_flops(plain, bd_plain)
    count_jaxpr_flops(remat, bd_remat)
    assert bd_plain["dot_general"] == dot_flops
    assert bd_remat["dot_general"] == dot_flops

    mesh = ds.initialize_mesh(data=-1)

    def region(x):
        return jnp.dot(x, x)

    sm = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=P(), out_specs=P()))(
        jnp.ones((n, n)))
    bd_sm = {}
    count_jaxpr_flops(sm, bd_sm)
    assert bd_sm.get("dot_general", 0) == dot_flops
    ds.reset_mesh_context()


def test_wire_bytes_counts_custom_vjp_bwd_under_shard_map():
    """Satellite regression: a two-collective program — custom_vjp whose
    forward all-gathers and whose backward reduce-scatters, inside
    shard_map, traced under grad — pins both directions' counted bytes.
    The sparse-gradients/low-bandwidth paths have exactly this shape."""
    from deepspeed_tpu.runtime.comm.low_bandwidth import (
        collective_wire_bytes)
    mesh = ds.initialize_mesh(data=-1)  # 8 simulated devices

    @jax.custom_vjp
    def gather(x):
        return lax.all_gather(x, "data", axis=0, tiled=True)

    def fwd(x):
        return gather(x), None

    def bwd(_, g):
        return (lax.psum_scatter(g, "data", scatter_dimension=0,
                                 tiled=True),)

    gather.defvjp(fwd, bwd)

    def region(x):
        y = gather(x)
        return (y * y).sum()

    def loss(x):
        return jax.shard_map(region, mesh=mesh.mesh, in_specs=P("data"),
                             out_specs=P(), check_vma=False)(x).sum()

    jx = jax.make_jaxpr(jax.grad(loss))(jnp.ones((8, 4), jnp.float32))
    wire = collective_wire_bytes(jx)
    # fwd: all_gather output [8, 4] fp32 inside the region = 128 B —
    # nested under custom_vjp fun_jaxpr under shard_map
    assert wire["gather_bytes"] == 8 * 4 * 4
    # bwd: the custom-vjp reduce_scatter operand [8, 4] fp32 = 128 B
    # (+ 16 B from the axes=() psum jax's shard_map transpose inserts on
    # the [1, 4] output — pinned so a walker regression is loud)
    assert wire["reduce_bytes"] == 8 * 4 * 4 + 16, wire
    ds.reset_mesh_context()


# --------------------------------------------------------------------- #
# rule fixtures — one deliberately-broken program per rule
# --------------------------------------------------------------------- #
def test_host_sync_fires_on_callback_in_scan_body():
    def body(c, x):
        with jax.named_scope("hot_region"):
            jax.debug.print("loss={}", x)
            return c + x, None

    def f(xs):
        return lax.scan(body, 0.0, xs)[0]

    target = _target(f, jnp.ones(4), label="grad_step")
    hits = [f for f in _findings(target) if f.rule == RULE_HOST_SYNC]
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "debug_callback" in hits[0].message
    assert hits[0].target == "grad_step"
    # name-stack provenance into the scan body survives
    assert "hot_region" in hits[0].scope


def test_host_sync_warns_at_top_level():
    def f(x):
        jax.debug.print("x={}", x)
        return x * 2

    hits = [f for f in _findings(_target(f, jnp.ones(4)))
            if f.rule == RULE_HOST_SYNC]
    assert len(hits) == 1 and hits[0].severity == "warning"


def test_host_sync_silent_on_clean_scan():
    def f(xs):
        return lax.scan(lambda c, x: (c + x, None), 0.0, xs)[0]

    assert not [f for f in _findings(_target(f, jnp.ones(4)))
                if f.rule == RULE_HOST_SYNC]


def test_donation_audit_flags_undonated_consumed_arg():
    mb = 1024 * 1024

    def f(p, g):
        return jax.tree.map(lambda a, b: a - b, p, g)

    p = {"w": jnp.ones((512, 512))}  # 1 MiB
    target = _target(
        f, p, p, label="apply_step",
        args_info=[ArgInfo("params", mb, donated=False, consumed=True),
                   ArgInfo("grads", mb, donated=True, consumed=True)])
    hits = [f for f in _findings(target) if f.rule == RULE_DONATION]
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "params" in hits[0].message and "1.0 MiB" in hits[0].message
    # donated and sub-floor args stay silent; waste estimate = the miss
    report = ProgramAuditor(_cfg()).run([target])
    assert report.donation_waste_bytes == mb


def test_lockstep_divergent_collective_order_between_configs():
    mesh = ds.initialize_mesh(data=-1)

    def order_a(x):
        g = lax.all_gather(x, "data", axis=0, tiled=True)
        return lax.psum_scatter(g, "data", scatter_dimension=0,
                                tiled=True).sum()

    def order_b(x):  # reduces BEFORE gathering — diverges at position 0
        s = lax.psum(x, "data")
        g = lax.all_gather(s, "data", axis=0, tiled=True)
        return g.sum()

    def shmap(f):
        return jax.make_jaxpr(jax.shard_map(
            f, mesh=mesh.mesh, in_specs=P("data"), out_specs=P(),
            check_vma=False))(jnp.ones((8, 4)))

    jx_a, jx_b = shmap(order_a), shmap(order_b)
    same = compare_lockstep(jx_a, jx_a)
    assert same is None
    finding = compare_lockstep(jx_a, jx_b, "host0", "host1")
    assert finding is not None and finding.rule == RULE_LOCKSTEP
    assert finding.severity == "error"
    assert "position 0" in finding.message  # first divergence named
    # signatures themselves are order-sensitive and stable
    assert lockstep_signature(jx_a)[0] != lockstep_signature(jx_b)[0]
    assert lockstep_signature(jx_a)[0] == lockstep_signature(jx_a)[0]
    ds.reset_mesh_context()


def test_lockstep_expected_signature_mismatch_is_error():
    target = _target(lambda x: x + 1, jnp.ones(4), label="grad_step")
    report = ProgramAuditor(
        _cfg(expected_signature="deadbeef")).run([target])
    hits = [f for f in report.findings if f.rule == RULE_LOCKSTEP]
    assert len(hits) == 1 and hits[0].severity == "error"
    # pinning the real combined signature passes clean
    report2 = ProgramAuditor(
        _cfg(expected_signature=report.signature)).run(
        [_target(lambda x: x + 1, jnp.ones(4), label="grad_step")])
    assert not report2.findings


def test_dtype_hazard_forced_fp32_upcast_feeding_matmul():
    def bad(x):  # bf16 wire upcast then matmul at fp32
        return jnp.dot(x.astype(jnp.float32), x.astype(jnp.float32))

    def good(x):  # matmul stays bf16; scalar loss upcast is intended
        return jnp.dot(x, x).sum().astype(jnp.float32)

    cfg = _cfg(dtype_min_elements=1)
    x = jnp.ones((8, 8), jnp.bfloat16)
    hits = [f for f in _findings(_target(bad, x), cfg)
            if f.rule == RULE_DTYPE_HAZARD]
    assert hits and hits[0].severity == "error"
    assert "bfloat16" in hits[0].message and "fp32" in hits[0].message
    assert not [f for f in _findings(_target(good, x), cfg)
                if f.rule == RULE_DTYPE_HAZARD]


def test_dtype_hazard_upcast_wire_into_collective():
    mesh = ds.initialize_mesh(data=-1)

    def region(x):
        return lax.all_gather(x.astype(jnp.float32), "data", axis=0,
                              tiled=True).sum()

    jx = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False))(jnp.ones((8, 16), jnp.bfloat16))
    hits = [f for f in _findings(
        AuditTarget("grad_step", jx), _cfg(dtype_min_elements=1))
        if f.rule == RULE_DTYPE_HAZARD]
    assert hits and hits[0].severity == "error"
    assert "all_gather" in hits[0].message
    ds.reset_mesh_context()


def test_comm_budget_dense_blowup_flagged():
    mesh = ds.initialize_mesh(data=-1)

    def region(x):
        return lax.all_gather(x, "data", axis=0, tiled=True).sum()

    jx = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False))(jnp.ones((8, 1024), jnp.float32))
    target = AuditTarget("grad_step", jx)
    # gather moves 8*1024*4 B = 32 KiB; budget of 1 KiB trips
    hits = [f for f in _findings(target, _cfg(comm_budget_mb=1 / 1024))
            if f.rule == RULE_COMM_BUDGET]
    assert len(hits) == 1 and hits[0].severity == "error"
    assert "all_gather" in hits[0].message  # top contributor named
    # a budget that fits stays silent; None disables
    assert not [f for f in _findings(target, _cfg(comm_budget_mb=1.0))
                if f.rule == RULE_COMM_BUDGET]
    assert not [f for f in _findings(target, _cfg())
                if f.rule == RULE_COMM_BUDGET]
    ds.reset_mesh_context()


def test_comm_budget_is_gas_weighted_per_optimizer_step():
    """The budget must compare against the same gas-weighted per-step
    total the report (and bench rows) publish: the modular grad program
    dispatches gas times per optimizer step."""
    mesh = ds.initialize_mesh(data=-1)

    def region(x):
        return lax.all_gather(x, "data", axis=0, tiled=True).sum()

    jx = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False))(jnp.ones((8, 1024), jnp.float32))
    target = AuditTarget("grad_step", jx)
    one_dispatch = 8 * 1024 * 4  # 32 KiB
    # budget sits between 1 dispatch and the gas=8 per-step total
    cfg = _cfg(comm_budget_mb=(4 * one_dispatch) / (1024 * 1024))
    report = ProgramAuditor(cfg).run([target], gas=8)
    assert report.wire_bytes_per_step == 8 * one_dispatch
    hits = [f for f in report.findings if f.rule == RULE_COMM_BUDGET]
    assert len(hits) == 1 and hits[0].severity == "error"
    # at gas=1 the same budget fits
    assert not [f for f in ProgramAuditor(cfg).run([target]).findings
                if f.rule == RULE_COMM_BUDGET]
    ds.reset_mesh_context()


def test_step_wire_bytes_counts_max_cond_branch_only():
    """Only one cond branch executes, so wire volume counts the most
    expensive branch (the flops counter's semantics) — and ppermute is
    lockstep-relevant but excluded from wire volume."""
    from deepspeed_tpu.analysis import step_wire_bytes
    mesh = ds.initialize_mesh(data=-1)

    def region(pred, x):
        big = lambda a: lax.all_gather(a, "data", axis=0, tiled=True).sum()
        small = lambda a: a.sum()
        return lax.cond(pred, big, small, x)

    jx = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False))(jnp.array(True), jnp.ones((8, 64), jnp.float32))
    total, contributors = step_wire_bytes(jx)
    assert total == 8 * 64 * 4  # the gather branch, counted once
    assert len(contributors) == 1

    def perm(x):
        return lax.ppermute(x, "data",
                            perm=[(i, (i + 1) % 8) for i in range(8)])

    jp = jax.make_jaxpr(jax.shard_map(
        perm, mesh=mesh.mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False))(jnp.ones((8, 64), jnp.float32))
    assert step_wire_bytes(jp)[0] == 0  # ppermute: lockstep-only
    from deepspeed_tpu.analysis import collective_sequence
    assert any("ppermute" in s for s in collective_sequence(jp))
    ds.reset_mesh_context()


def test_recompile_guard_retrace_storm():
    guard = RecompileGuard(max_retraces=2)
    assert guard.observe((np.zeros((4, 16), np.int32),)) is None
    assert guard.observe((np.zeros((4, 16), np.int32),)) is None  # cached
    assert guard.observe((np.zeros((4, 12), np.int32),)) is None  # 1st
    assert guard.observe((np.zeros((4, 8), np.int32),)) is None   # 2nd
    finding = guard.observe((np.zeros((4, 4), np.int32),))        # 3rd
    assert finding is not None and finding.rule == RULE_RECOMPILE
    assert finding.severity == "error"
    assert "(4, 8)" in finding.message and "(4, 4)" in finding.message
    assert guard.retraces_seen == 3
    # dtype flap is also a retrace
    g2 = RecompileGuard(max_retraces=1)
    g2.observe((np.zeros(4, np.int32),))
    g2.observe((np.zeros(4, np.float32),))
    f2 = g2.observe((np.zeros(4, np.int64),))
    assert f2 is not None and "int64" in f2.message


# --------------------------------------------------------------------- #
# schedule rules (ISSUE 6): overlap + HBM liveness fixtures
# --------------------------------------------------------------------- #
def _serialized_gather_scan_jaxpr(mesh):
    """A layer scan that gathers each layer's weights ON the critical
    path (first consumer is the very next matmul) — the shape of the
    current streamed-ZeRO-3 schedule."""
    def region(x, w):
        def body(c, wi):
            full = lax.all_gather(wi, "data", axis=0, tiled=True)
            return c @ full, None
        c, _ = lax.scan(body, x, w)
        return c

    return jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=(P(), P(None, "data")),
        out_specs=P(), check_vma=False))(
        jnp.ones((16, 64)), jnp.ones((4, 64, 64)))


def test_overlap_serialized_gather_in_scan_flagged():
    mesh = ds.initialize_mesh(data=-1)
    jx = _serialized_gather_scan_jaxpr(mesh)
    target = AuditTarget("grad_step", jx)
    hits = [f for f in _findings(target) if f.rule == RULE_OVERLAP]
    assert len(hits) == 1
    assert hits[0].severity == "warning"  # error once require_overlap
    assert "all_gather" in hits[0].message
    assert "critical path" in hits[0].message
    assert hits[0].target == "grad_step"
    # analysis.require_overlap escalates to error (the prefetch CI gate)
    hits_err = [f for f in _findings(target, _cfg(require_overlap=True))
                if f.rule == RULE_OVERLAP]
    assert hits_err and hits_err[0].severity == "error"
    # the record carries the schedule facts
    recs = analyze_overlap(jx, _cfg(), "grad_step")
    gathers = [r for r in recs if r.prim == "all_gather"]
    assert len(gathers) == 1
    r = gathers[0]
    assert r.serialized and not r.carried
    assert r.loop_depth == 1 and r.mult == 4  # inside the 4-layer scan
    assert r.distance_eqns == 0 and r.slack_flops == 0
    report = ProgramAuditor(_cfg()).run([target])
    assert report.overlap_efficiency < 0.5
    ds.reset_mesh_context()


def test_overlap_carried_gather_verifies_double_buffer():
    """The double-buffered prefetch shape (ROADMAP item 1): layer i+1's
    gather is issued into the scan carry under layer i's compute — the
    overlap rule must verify it statically and stay silent."""
    mesh = ds.initialize_mesh(data=-1)

    def region(x, w):
        def body(carry, wi):
            c, pref = carry
            nxt = lax.all_gather(wi, "data", axis=0, tiled=True)
            return (c @ pref, nxt), None
        first = lax.all_gather(w[0], "data", axis=0, tiled=True)
        (c, _), _ = lax.scan(body, (x, first), w)
        return c

    jx = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=(P(), P(None, "data")),
        out_specs=P(), check_vma=False))(
        jnp.ones((16, 64)), jnp.ones((4, 64, 64)))
    assert not [f for f in _findings(AuditTarget("grad_step", jx))
                if f.rule == RULE_OVERLAP]
    recs = analyze_overlap(jx, _cfg(), "grad_step")
    in_loop = [r for r in recs if r.prim == "all_gather"
               and r.loop_depth == 1]
    assert in_loop and all(r.carried and not r.serialized
                           for r in in_loop)
    ds.reset_mesh_context()


def test_overlap_top_level_collective_not_flagged():
    """A one-shot top-level gather is serialized by the dispatch anyway
    — recorded (it feeds overlap_efficiency and the step-time model) but
    never a finding."""
    mesh = ds.initialize_mesh(data=-1)

    def region(x):
        return lax.all_gather(x, "data", axis=0, tiled=True).sum()

    jx = jax.make_jaxpr(jax.shard_map(
        region, mesh=mesh.mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False))(jnp.ones((8, 64), jnp.float32))
    assert not [f for f in _findings(AuditTarget("grad_step", jx))
                if f.rule == RULE_OVERLAP]
    recs = analyze_overlap(jx, _cfg(), "grad_step")
    assert len(recs) == 1 and recs[0].loop_depth == 0
    assert overlap_efficiency([]) == 1.0
    ds.reset_mesh_context()


def test_hbm_budget_undonated_blowup_over_budget():
    """An undonated param/grad update doubles its HBM; the liveness
    estimator sees it and the hbm_budget rule names the contributors."""
    def f(p, g):
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g)

    p = {"w": jnp.ones((512, 512))}  # 1 MiB
    jx = jax.make_jaxpr(f)(p, p)
    undonated = estimate_liveness(jx, [False, False],
                                  ["params[0]", "grads[0]"])
    donated = estimate_liveness(jx, [True, True],
                                ["params[0]", "grads[0]"])
    mb = 1024 * 1024
    assert undonated.peak_bytes == 3 * mb  # params + grads + new params
    assert donated.peak_bytes == 2 * mb    # output aliases a dying input
    assert any("params[0]" in k for k, _ in undonated.contributors)

    target = AuditTarget("apply_step", jx,
                         donated_invars=[False, False],
                         invar_labels=["params[0]", "grads[0]"])
    hits = [f for f in _findings(target, _cfg(hbm_budget_mb=2.5))
            if f.rule == RULE_HBM_BUDGET]
    assert len(hits) == 1 and hits[0].severity == "error"
    assert "params[0]" in hits[0].message
    # a budget that fits stays silent; None disables the lint
    assert not [f for f in _findings(target, _cfg(hbm_budget_mb=4.0))
                if f.rule == RULE_HBM_BUDGET]
    assert not [f for f in _findings(target, _cfg())
                if f.rule == RULE_HBM_BUDGET]


def test_liveness_counts_scan_body_internals():
    """The streamed gather materializes the full layer INSIDE the scan
    body — the estimator must count the body's transient peak, not just
    the top-level live set."""
    def f(xs):
        def body(c, x):
            big = jnp.tile(x, (64, 1))        # transient [64, 256]
            return c + big.sum(), None
        return lax.scan(body, 0.0, xs)[0]

    jx = jax.make_jaxpr(f)(jnp.ones((4, 256), jnp.float32))
    rep = estimate_liveness(jx)
    assert rep.peak_bytes >= 64 * 256 * 4  # the body transient counts


def test_step_time_model_fields_and_bound():
    mesh = ds.initialize_mesh(data=-1)
    jx = _serialized_gather_scan_jaxpr(mesh)
    report = ProgramAuditor(_cfg()).run([AuditTarget("grad_step", jx)])
    st = report.step_time
    assert st["predicted_step_time_lb_s"] > 0
    assert st["bound"] in ("compute", "memory", "hidden_comm")
    assert st["flops_per_step"] > 0 and st["io_bytes_per_step"] > 0
    # serialized wire is exposed: the lower bound must include it
    assert st["wire_bytes_exposed"] > 0
    assert (st["predicted_step_time_lb_s"]
            >= st["t_comm_exposed_s"] > 0)
    # gas weighting: the modular grad program dispatches gas times
    report4 = ProgramAuditor(_cfg()).run(
        [AuditTarget("grad_step", jx)], gas=4)
    assert (report4.step_time["flops_per_step"]
            == 4 * st["flops_per_step"])
    ds.reset_mesh_context()


# --------------------------------------------------------------------- #
# clean programs: gpt2 modular + fused train steps audit to zero
# --------------------------------------------------------------------- #
def _tiny_engine(extra_config=None, fused=False, bf16=False, gas=1,
                 num_layers=2):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=num_layers, num_heads=4, bf16=bf16,
                     embd_dropout=0.0, attn_dropout=0.0,
                     hidden_dropout=0.0)
    model = GPT2Model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "fused_step": {"enabled": fused},
        "analysis": {"mode": "warn"},
        "steps_per_print": 10 ** 9,
    }
    if bf16:
        config["bf16"] = {"enabled": True}
    config.update(extra_config or {})
    engine, _, _, _ = ds.initialize(
        model=model, config=config,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    return engine


def test_clean_gpt2_modular_step_zero_findings():
    engine = _tiny_engine()
    report = engine.program_audit
    assert report is not None
    assert report.findings == [], [f.format() for f in report.findings]
    assert report.targets == ["grad_step", "apply_step"]
    assert report.signature is not None


def test_clean_gpt2_fused_step_zero_findings():
    engine = _tiny_engine(fused=True, bf16=True, gas=2)
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    report = engine.program_audit
    assert report.findings == [], [f.format() for f in report.findings]
    assert report.targets == ["fused_step"]


def test_zero3_streaming_gather_on_critical_path_pinned():
    """The negative fixture of the overlap gate: with prefetch off (the
    pre-carried schedule, frozen in golden/gpt2_zero3_stream_schedule_
    serialized.json) the streamed stage-3 program gathers each group at
    use, and the overlap rule must flag the serialized hot-loop gathers
    with the plan's provenance.  ISSUE 7's carried mode flips this to
    zero findings — pinned by test_zero3_streaming_carried_flips_
    overlap_gate_green."""
    engine = _tiny_engine(extra_config={"zero_optimization": {
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "stage3_max_live_parameters": 1,
        "stage3_prefetch_bucket_size": 0}})
    assert engine._zero3_stream.last_plan.mode == "off"
    report = engine.program_audit
    assert report.wire_bytes_per_step > 0
    assert any("all_gather" in s for s in report.collective_sequence)
    # every finding is the overlap rule (the other five rules stay
    # clean) and at least one names a hot-loop serialized gather with
    # the streamed plan's provenance
    assert report.findings, "streamed gathers should be flagged"
    assert all(f.rule == RULE_OVERLAP and f.severity == "warning"
               for f in report.findings), [
        f.format() for f in report.findings]
    gather_hits = [f for f in report.findings
                   if "all_gather" in f.message]
    assert gather_hits
    assert any("streamed ZeRO-3 plan" in f.message for f in gather_hits)
    assert any("mode=off" in f.message for f in gather_hits)
    assert report.overlap["n_serialized_hot_loop"] > 0
    assert report.overlap_efficiency < 1.0


def _stream_engine(mode, layers=2, bucket=200_000, max_live=200_000):
    cfg = {"stage": 3, "stage3_param_persistence_threshold": 0,
           "stage3_max_live_parameters": max_live,
           "stage3_prefetch_bucket_size": bucket,
           "stage3_prefetch_mode": mode}
    return _tiny_engine(extra_config={"zero_optimization": cfg},
                        num_layers=layers)


def test_zero3_streaming_carried_flips_overlap_gate_green():
    """ISSUE 7 tentpole pin: with stage3_prefetch_mode=carried (the
    default) the hot-loop weight gathers ride the scan carry — the
    overlap rule verifies the double buffer statically (zero findings
    even under require_overlap), every hot-loop gather record is
    ``carried``, and the bytes-weighted efficiency beats the frozen
    serialized baseline."""
    from deepspeed_tpu.analysis import audit_engine
    engine = _stream_engine("carried")
    plan = engine._zero3_stream.last_plan
    assert plan.mode == "carried" and plan.prefetch
    report = engine.program_audit
    assert report.findings == [], [f.format() for f in report.findings]
    assert report.overlap["n_serialized_hot_loop"] == 0
    hot_gathers = [r for r in report.overlap["records"]
                   if r["prim"] == "all_gather" and r["loop_depth"] > 0]
    assert hot_gathers and all(r["carried"] for r in hot_gathers)
    # the carried records carry real slack: a full group of compute sits
    # between issue and first consume
    assert all(r["slack_flops"] > 0 for r in hot_gathers)
    # the backward re-fetch is carried too: hot-loop reduce_scatters
    # (the re-gather sweep's grad transposes) escape via the carry/ys
    assert report.overlap["n_carried"] > len(hot_gathers)
    serialized = json.loads(GOLDEN_STREAM_SERIALIZED.read_text())
    assert (report.overlap_efficiency
            > serialized["overlap"]["overlap_efficiency"])
    # require_overlap (the CI posture) stays green on the carried
    # schedule: zero findings at error severity
    strict = audit_engine(engine, cfg=AnalysisConfig.from_dict(
        {"mode": "error", "require_overlap": True}), multihost=False)
    assert strict.findings == [], [f.format() for f in strict.findings]


def test_zero3_streaming_carried_liveness_within_plan_bound():
    """The carried buffer must NOT become a stacked scan residual (the
    naive carried structure saves steps x group = the full unsharded
    model).  Pin: the carried program's static peak stays within the
    at-use program's peak plus the plan's 2x-group live-parameter bound
    — a full-model stacking regression would blow past it by
    (num_layers - 2) x group."""
    carried = _stream_engine("carried")
    at_use = _stream_engine("off")
    plan = carried._zero3_stream.last_plan
    assert plan.mode == "carried"
    group_bytes = plan.layers_per_step * plan.params_per_layer * 4
    peak_carried = carried.program_audit.peak_hbm_bytes
    peak_at_use = at_use.program_audit.peak_hbm_bytes
    assert peak_carried <= peak_at_use + 2 * group_bytes, (
        peak_carried, peak_at_use, group_bytes)


def test_zero3_streaming_forfeited_prefetch_surfaced():
    """plan_layer_streaming forfeits a requested prefetch when no legal
    group split exists (e.g. unrolled mode on an odd prime layer count)
    — the auditor must surface the forfeit as a warning finding instead
    of silently falling back to serialized gathers."""
    engine = _stream_engine("unrolled", layers=3)
    plan = engine._zero3_stream.last_plan
    assert not plan.prefetch and plan.forfeited is not None
    report = engine.program_audit
    forfeits = [f for f in report.findings
                if f.rule == RULE_OVERLAP and "FORFEITED" in f.message]
    assert len(forfeits) >= 1
    assert "EVEN" in forfeits[0].message
    # the unrolled forfeit reason names the mode that lifts the
    # constraint (plan_layer_streaming's message rides into the finding)
    assert "carried" in forfeits[0].message
    # the serialized gathers themselves are still flagged alongside
    assert any("critical path" in f.message for f in report.findings)


def test_overlap_chase_flows_through_dequant_epilogue():
    """A quantized gather's dequant (payload * scales) must not count as
    the first consumer: the payload-preserving elementwise op flows the
    chase through, so a dequantized-then-carried gather still verifies
    as carried, while a dequantized-then-matmul'd gather stays
    serialized."""
    mesh = ds.initialize_mesh(data=-1)

    def make(carried):
        def region(x, w, s):
            def body(carry, xs):
                c, pref = carry
                wi, si = xs
                q = lax.all_gather(wi, "data", axis=0, tiled=True)
                deq = q * si          # same-shape dequant epilogue
                if carried:
                    return (c @ pref, deq), None
                return (c @ deq, pref), None
            first = jnp.zeros((64, 64))
            (c, _), _ = lax.scan(body, (x, first), (w, s))
            return c

        return jax.make_jaxpr(jax.shard_map(
            region, mesh=mesh.mesh, in_specs=(P(), P(None, "data"), P()),
            out_specs=P(), check_vma=False))(
            jnp.ones((16, 64)), jnp.ones((4, 64, 64)),
            jnp.ones((4, 64, 64)))

    recs = analyze_overlap(make(carried=True), _cfg(), "grad_step")
    in_loop = [r for r in recs if r.prim == "all_gather"
               and r.loop_depth == 1]
    assert in_loop and all(r.carried for r in in_loop)
    recs = analyze_overlap(make(carried=False), _cfg(), "grad_step")
    in_loop = [r for r in recs if r.prim == "all_gather"
               and r.loop_depth == 1]
    assert in_loop and all(not r.carried and r.serialized
                           for r in in_loop)
    ds.reset_mesh_context()


def test_peak_hbm_default_gpt2_within_sanity_band():
    """The donation-aware static peak for the default gpt2 config must
    sit in a sane band: at least the resident state (params + Adam
    moments live through the grad program), at most a small multiple of
    state + activations (the estimator is pre-fusion, so it may
    overcount transients — but never by orders of magnitude)."""
    import jax as _jax
    engine = _tiny_engine()
    report = engine.program_audit
    param_bytes = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in _jax.tree.leaves(engine.params))
    state_bytes = param_bytes + sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in _jax.tree.leaves(engine.opt_state)
        if hasattr(leaf, "shape"))
    assert report.peak_hbm_bytes >= state_bytes
    assert report.peak_hbm_bytes <= 50 * state_bytes, (
        report.peak_hbm_bytes, state_bytes,
        report.peak_hbm_contributors)
    assert report.peak_hbm_contributors
    # engine exposes the static step-time bound for bench/monitors
    assert engine.predicted_step_time_lb_s == (
        report.step_time["predicted_step_time_lb_s"])
    assert engine.predicted_step_time_lb_s > 0


def test_bench_rows_embed_schedule_provenance():
    """Flagship bench rows must carry overlap_efficiency,
    peak_hbm_bytes, and predicted_step_time_lb next to the lockstep
    signature and wire bytes (acceptance criterion, ISSUE 6)."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    engine = _tiny_engine()
    fields = bench._program_audit_fields(engine)
    assert "lockstep_signature" in fields
    assert fields["overlap_efficiency"] == 1.0  # no explicit collectives
    assert fields["peak_hbm_bytes"] > 0
    assert fields["predicted_step_time_lb"] > 0


def test_engine_error_mode_raises_on_retrace_storm():
    engine = _tiny_engine(extra_config={
        "analysis": {"mode": "error", "max_retraces": 1}})
    ids16 = np.zeros((8, 16), np.int32)
    ids12 = np.zeros((8, 12), np.int32)
    ids8 = np.zeros((8, 8), np.int32)
    engine.forward(ids16)
    engine.backward()
    engine.step()
    engine.forward(ids12)  # 1st retrace: within budget
    engine.backward()
    engine.step()
    with pytest.raises(ProgramAuditError) as ei:
        engine.forward(ids8)  # 2nd retrace: over budget
    assert "retraced" in str(ei.value)


def test_audit_counters_round_trip_through_checkpoint(tmp_path):
    engine = _tiny_engine(extra_config={
        "analysis": {"mode": "warn", "max_retraces": 8}})
    engine.forward(np.zeros((8, 16), np.int32))
    engine.backward()
    engine.step()
    engine.forward(np.zeros((8, 12), np.int32))  # one retrace
    engine.backward()
    engine.step()
    assert engine._recompile_guard.retraces_seen == 1
    engine.save_checkpoint(str(tmp_path), tag="t1")
    meta = json.loads(
        (tmp_path / "t1" / "ds_meta.json").read_text())
    audit = meta["client_state"]["program_audit"]
    assert audit["retraces_seen"] == 1
    assert audit["lockstep_signature"] == engine.program_audit.signature
    assert "findings_by_severity" in audit

    engine2 = _tiny_engine(extra_config={
        "analysis": {"mode": "warn", "max_retraces": 8}})
    engine2.load_checkpoint(str(tmp_path), tag="t1")
    assert engine2._recompile_guard.retraces_seen >= 1


def test_analysis_off_by_default_no_auditor_state():
    engine = _tiny_engine(extra_config={"analysis": None})
    assert engine.program_audit is None
    assert engine._recompile_guard is None


def test_analysis_config_validation():
    assert not AnalysisConfig.from_dict(None).enabled
    with pytest.raises(DeepSpeedConfigError):
        AnalysisConfig.from_dict({"mode": "loud"})
    with pytest.raises(DeepSpeedConfigError):
        AnalysisConfig.from_dict({"mode": "warn", "max_retraces": 0})
    with pytest.raises(DeepSpeedConfigError):
        AnalysisConfig.from_dict({"mode": "warn", "comm_budget_mb": -1})


# --------------------------------------------------------------------- #
# golden lockstep signature + CLI contract (CI satellites)
# --------------------------------------------------------------------- #
def test_golden_lockstep_signature_of_default_gpt2_config():
    """Drift in the default gpt2 config's collective sequence must be an
    explicit diff of the golden file, not a silent change."""
    golden = json.loads(GOLDEN.read_text())
    engine = _tiny_engine()  # stage 2 — the example config's shape
    report = engine.program_audit
    assert report.signature == golden["signature"], (
        "the default gpt2 step program's collective sequence changed — "
        "if intended, update tests/unit/golden/gpt2_lockstep_signature"
        f".json (traced {len(report.collective_sequence)} collectives: "
        f"{report.collective_sequence[:5]}...)")
    assert len(report.collective_sequence) == golden["collective_count"]


def _run_cli(config_path, *extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis",
         "--config", str(config_path), *extra],
        cwd=str(REPO), capture_output=True, text=True, timeout=300,
        env=env)


def test_cli_warn_mode_exits_zero_on_example_config():
    out = _run_cli(EXAMPLE_CFG, "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(
        out.stdout[out.stdout.index("{\n"):])
    golden = json.loads(GOLDEN.read_text())
    assert payload["signature"] == golden["signature"]
    assert payload["findings"] == []


def test_cli_error_mode_exits_nonzero_on_error_findings(tmp_path):
    bad = dict(json.loads(EXAMPLE_CFG.read_text()))
    bad["analysis"] = {"mode": "error", "expected_signature": "deadbeef"}
    cfg_path = tmp_path / "bad.json"
    cfg_path.write_text(json.dumps(bad))
    out = _run_cli(cfg_path)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "lockstep" in out.stdout
    assert "FAILED" in out.stderr


def test_cli_error_mode_hbm_budget_exits_nonzero(tmp_path, capsys):
    """Acceptance criterion (ISSUE 6): an over-budget
    analysis.hbm_budget_mb run exits nonzero via the CLI in error
    mode, naming the live buffers.  Runs cli.main in-process — its
    return value IS the process exit code (__main__ sys.exits it); the
    true subprocess path is pinned by the neighboring CLI tests."""
    from deepspeed_tpu.analysis.cli import main as cli_main
    bad = dict(json.loads(EXAMPLE_CFG.read_text()))
    bad["analysis"] = {"mode": "error", "hbm_budget_mb": 0.001}
    cfg_path = tmp_path / "hbm.json"
    cfg_path.write_text(json.dumps(bad))
    ds.reset_mesh_context()
    rc = cli_main(["--config", str(cfg_path)])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "hbm_budget" in out.out
    assert "FAILED" in out.err


# --------------------------------------------------------------------- #
# CI gate (satellite, ISSUE 6): every docs/examples config must lint
# clean under --mode error — a schedule regression (serialized gather
# escalated via require_overlap, budget breach, signature drift) fails
# the suite here before it can burn a pod.  The same gate runs as a
# workflow step (.github/workflows/tier1.yml) via the real CLI.
# --------------------------------------------------------------------- #
def test_ci_gate_examples_error_mode(capsys, request):
    from deepspeed_tpu.analysis.cli import main as cli_main
    examples = sorted((REPO / "docs" / "examples").glob("*.json"))
    assert EXAMPLE_CFG in examples and EXAMPLE_STREAM_CFG in examples
    assert EXAMPLE_FCM_CFG in examples and EXAMPLE_HLO_CFG in examples
    golden_stream = json.loads(GOLDEN_STREAM.read_text())
    # gpt2_chaos.json installs the process-global chaos plane at engine
    # init; its faults are at_step-triggered (audits never step, so none
    # can fire here) but the plane must not outlive this gate
    from deepspeed_tpu.runtime.resilience import chaos as _chaos
    request.addfinalizer(_chaos.uninstall)
    for cfg_path in examples:
        ds.reset_mesh_context()
        rc = cli_main(["--config", str(cfg_path), "--mode", "error",
                       "--json"])
        stdout = capsys.readouterr().out
        assert rc == 0, (
            f"{cfg_path.name} failed the error-mode analysis gate:\n"
            + stdout)
        payload = json.loads(stdout[stdout.index("{\n"):])
        # a 1-bit-tier config is TWO audited programs: the CLI emits
        # one payload per phase, and each must clear the same gate
        phases = ([payload["phase_warmup"], payload["phase_compressed"]]
                  if "phase_warmup" in payload else [payload])
        for ph in phases:
            errors = [f for f in ph["findings"]
                      if f["severity"] == "error"]
            assert errors == [], f"{cfg_path.name}: {errors}"
        if cfg_path == EXAMPLE_STREAM_CFG:
            # the streamed config's CARRIED schedule is pinned by its
            # golden: signature, collective count, zero serialized
            # hot-loop gathers, carried records present (regenerate with
            # --update-golden).  The config sets require_overlap +
            # mode=error, so a serialized regression fails the rc==0
            # assert above before these pins even run.
            assert payload["signature"] == golden_stream["signature"]
            assert (len(payload["collective_sequence"])
                    == golden_stream["collective_count"])
            ov = golden_stream["overlap"]
            assert payload["overlap"]["n_serialized_hot_loop"] == 0
            assert (payload["overlap"]["n_serialized_hot_loop"]
                    == ov["n_serialized_hot_loop"])
            assert payload["overlap"]["n_carried"] == ov["n_carried"] > 0
            assert abs(payload["overlap_efficiency"]
                       - ov["overlap_efficiency"]) < 0.1
            # the carried schedule must beat the frozen pre-carried
            # serialized baseline on bytes-weighted efficiency — the
            # ISSUE 7 acceptance bar
            serialized = json.loads(GOLDEN_STREAM_SERIALIZED.read_text())
            assert (payload["overlap_efficiency"]
                    > serialized["overlap"]["overlap_efficiency"])
            assert payload["findings"] == []
        if cfg_path == EXAMPLE_FCM_CFG:
            # the fused-collective-matmul schedule is pinned by its
            # golden: every hot-loop qwZ/qgZ wire-mover classifies
            # fused/hidden, ZERO exposed hot-loop bytes — the ISSUE 13
            # acceptance bar (exposed-comm lane ~ 0), enforced here
            # under the config's own require_overlap + mode=error
            golden_fcm = json.loads(GOLDEN_STREAM_FCM.read_text())
            assert payload["signature"] == golden_fcm["signature"]
            assert (len(payload["collective_sequence"])
                    == golden_fcm["collective_count"])
            ovf = golden_fcm["overlap"]
            assert payload["overlap"]["n_serialized_hot_loop"] == 0
            assert (payload["overlap"]["n_fused"]
                    == ovf["n_fused"] > 0)
            exposed_hot = sum(
                int(r["wire_bytes"] * r["mult"]
                    * (1.0 - r["hidden_fraction"]))
                for r in payload["overlap"]["records"]
                if r["loop_depth"] > 0)
            assert exposed_hot == 0
            assert golden_fcm["wire_bytes_exposed_hot_loop"] == 0
            assert (payload["step_time"]["wire_bytes_fused"]
                    == golden_fcm["wire_bytes_fused"] > 0)
            assert payload["findings"] == []
        if cfg_path == EXAMPLE_HLO_CFG:
            # the HLO-level SPMD cross-check config runs the compiled-
            # view audit via its own analysis.hlo_audit knob (no CLI
            # flag needed) under require_spmd_match + mode=error; its
            # golden pins the clean compiled wire story — zero silent
            # reshards, jaxpr/HLO accountings in agreement (ISSUE 14
            # acceptance bar).  Regenerate with --update-golden.
            golden_hlo = json.loads(GOLDEN_HLO_AUDIT.read_text())
            assert payload["signature"] == golden_hlo["signature"]
            hlo = payload["hlo"]
            assert (hlo["n_silent_reshards"]
                    == golden_hlo["n_silent_reshards"] == 0)
            assert hlo["reshard_bytes_per_step"] == 0
            assert (hlo["hlo_wire_bytes_per_step"]
                    == golden_hlo["hlo_wire_bytes_per_step"] > 0)
            assert (hlo["hlo_collective_count"]
                    == golden_hlo["hlo_collective_count"] > 0)
            assert (round(hlo["divergence_ratio"], 4)
                    == golden_hlo["divergence_ratio"] == 1.0)
            # the compiled-view-only wire is priced in the exposed lane
            assert (payload["step_time"]["wire_bytes_hlo_only"]
                    == hlo["hlo_only_wire_bytes_per_step"] > 0)
            assert payload["findings"] == []


@pytest.mark.slow
def test_cli_update_golden_regenerates_checked_in_files(tmp_path):
    """--update-golden must reproduce the checked-in goldens exactly —
    the files are CLI output, never hand-edited.  One loop covers all
    four golden files (lockstep, streamed schedule, FCM schedule, HLO
    cross-check) so stale-golden drift fails in one place."""
    env_dir = str(tmp_path / "golden")
    for cfg_path, golden_path, extra in (
            (EXAMPLE_CFG, GOLDEN, ()),
            (EXAMPLE_STREAM_CFG, GOLDEN_STREAM, ("--devices", "8")),
            (EXAMPLE_FCM_CFG, GOLDEN_STREAM_FCM, ("--devices", "8")),
            (EXAMPLE_HLO_CFG, GOLDEN_HLO_AUDIT, ("--devices", "8"))):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["DS_ANALYSIS_GOLDEN_DIR"] = env_dir
        out = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.analysis",
             "--config", str(cfg_path), "--update-golden", *extra],
            cwd=str(REPO), capture_output=True, text=True, timeout=300,
            env=env)
        assert out.returncode == 0, out.stdout + out.stderr
        regenerated = json.loads(
            (Path(env_dir) / golden_path.name).read_text())
        assert regenerated == json.loads(golden_path.read_text()), (
            f"{golden_path.name} drifted from CLI output — regenerate "
            "with --update-golden")


def test_cli_update_golden_unknown_config_errors(tmp_path):
    from deepspeed_tpu.analysis.cli import GOLDEN_MAP, _golden_payload
    assert "gpt2_analysis.json" in GOLDEN_MAP
    assert "gpt2_zero3_stream_analysis.json" in GOLDEN_MAP
    # payload shape for the lockstep golden matches the checked-in file
    from deepspeed_tpu.analysis import AuditReport
    rep = AuditReport(signature="ab" * 32)
    payload = _golden_payload("gpt2_lockstep_signature.json", rep)
    assert set(payload) == {"_comment", "signature", "collective_count"}
    payload2 = _golden_payload("gpt2_zero3_stream_schedule.json", rep)
    assert set(payload2) == {"_comment", "signature", "collective_count",
                             "overlap"}
    payload3 = _golden_payload("gpt2_zero3_stream_fcm_schedule.json",
                               rep)
    assert set(payload3) == {"_comment", "signature", "collective_count",
                             "overlap", "wire_bytes_exposed_hot_loop",
                             "wire_bytes_fused"}
    assert "n_fused" in payload3["overlap"]
    # the HLO cross-check golden (ISSUE 14): its config must be in the
    # regen map and its payload must pin the clean compiled wire story
    assert "gpt2_hlo_audit.json" in GOLDEN_MAP
    payload4 = _golden_payload("gpt2_hlo_audit.json", rep)
    assert {"signature", "hlo_wire_bytes_per_step",
            "hlo_collective_count", "divergence_ratio",
            "n_silent_reshards", "waivers"} <= set(payload4)
