"""Chaos plane tests: deterministic fault injection across every
registered failure surface (runtime/resilience/chaos.py), the bounded
retry policy (resilience/retry.py), the fleet-exchange watchdog
(monitor/fleet.py), and the degradation registry
(resilience/degradation.py).  All fast-lane: faults are seeded and
call/step-triggered — no wall clock anywhere in the assertions."""

import errno
import json
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.resilience import chaos, degradation
from deepspeed_tpu.runtime.resilience.chaos import (ChaosFault, ChaosPlane,
                                                    InjectedCrash,
                                                    InjectedFault)
from deepspeed_tpu.runtime.resilience.retry import (CorruptionError,
                                                    RetryPolicy,
                                                    is_transient)
from tests.unit.simple_model import (base_engine_config, random_dataloader,
                                     simple_model_apply, simple_model_params)

HIDDEN = 16


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Every test leaves the process-global plane and the degradation
    registry clean — a leaked plane would fire into unrelated tests."""
    yield
    chaos.uninstall()
    degradation.get_registry().clear()


def make_engine(**overrides):
    cfg = base_engine_config(micro_batch=8, gas=1, **(overrides or {}))
    params = simple_model_params(HIDDEN)
    engine, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                                    model_parameters=params)
    return engine


def run_steps(engine, n, seed=3):
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    it = iter(RepeatingLoader(random_dataloader(HIDDEN, 32, 8, seed=seed)))
    for _ in range(n):
        x, y = next(it)
        engine.backward(engine.forward(x, y))
        engine.step()
    return it


# --------------------------------------------------------------------- #
# schedule validation (parse-time, not silently-never-fires)
# --------------------------------------------------------------------- #
def test_fault_rejects_unknown_point():
    with pytest.raises(ValueError, match="unknown injection point"):
        ChaosFault(point="aio.prad", kind="eio", at_call=1)


def test_fault_rejects_kind_invalid_at_point():
    with pytest.raises(ValueError, match="not valid at point"):
        ChaosFault(point="heartbeat.beat", kind="eio", at_call=1)


def test_fault_requires_exactly_one_trigger():
    with pytest.raises(ValueError, match="exactly one trigger"):
        ChaosFault(point="aio.pread", kind="eio")
    with pytest.raises(ValueError, match="exactly one trigger"):
        ChaosFault(point="aio.pread", kind="eio", at_call=1, at_step=2)


def test_fault_spec_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown keys"):
        ChaosFault.from_dict({"point": "aio.pread", "kind": "eio",
                              "at_cal": 1})


def test_chaos_config_block_validates_specs():
    base = {"train_micro_batch_size_per_gpu": 8}
    ok = DeepSpeedConfig({**base, "resilience": {"chaos": {
        "enabled": True, "seed": 7,
        "faults": [{"point": "batch.next", "kind": "poison",
                    "at_step": 3}]}}})
    cc = ok.resilience_config.chaos
    assert cc.enabled and cc.seed == 7 and len(cc.faults) == 1
    with pytest.raises(DeepSpeedConfigError, match="not valid at point"):
        DeepSpeedConfig({**base, "resilience": {"chaos": {
            "faults": [{"point": "batch.next", "kind": "eio",
                        "at_step": 3}]}}})


def test_chaos_off_by_default():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 8})
    assert not cfg.resilience_config.chaos.enabled
    assert chaos.active() is None
    assert chaos.maybe_fire(chaos.POINT_AIO_PREAD) is None


# --------------------------------------------------------------------- #
# determinism: same seed + schedule => bitwise-identical fired log
# --------------------------------------------------------------------- #
def _drive(plane):
    with chaos.installed(plane):
        for step in range(1, 6):
            for point in (chaos.POINT_AIO_PREAD, chaos.POINT_HEARTBEAT,
                          chaos.POINT_BATCH):
                try:
                    chaos.maybe_fire(point, step=step)
                except OSError:
                    pass
    return plane.fired


def _schedule():
    return [ChaosFault(point="aio.pread", kind="eio", at_call=4, repeat=2),
            ChaosFault(point="heartbeat.beat", kind="stale", at_call=2),
            ChaosFault(point="batch.next", kind="poison", at_step=3)]


def test_same_seed_same_schedule_identical_fired_log():
    log_a = _drive(ChaosPlane(_schedule(), seed=11))
    log_b = _drive(ChaosPlane(_schedule(), seed=11))
    assert log_a == log_b
    assert [e["kind"] for e in log_a] == ["stale", "poison", "eio", "eio"]
    # the log is timestamp-free by contract (what makes it comparable)
    assert all(set(e) == {"seq", "point", "kind", "call", "step", "detail"}
               for e in log_a)
    assert json.dumps(log_a, sort_keys=True) == \
        json.dumps(log_b, sort_keys=True)


def test_repeat_budget_bounds_firings():
    plane = ChaosPlane([ChaosFault(point="heartbeat.beat", kind="stale",
                                   at_call=1, repeat=3)])
    with chaos.installed(plane):
        fired = [chaos.maybe_fire(chaos.POINT_HEARTBEAT) is not None
                 for _ in range(6)]
    assert fired == [True, True, True, False, False, False]


def test_fired_faults_become_chaos_monitor_records():
    from deepspeed_tpu.monitor import record as R
    plane = ChaosPlane([ChaosFault(point="heartbeat.beat", kind="stale",
                                   at_call=1)])
    with chaos.installed(plane):
        chaos.maybe_fire(chaos.POINT_HEARTBEAT)
    recs = plane.drain_records()
    assert len(recs) == 1
    assert recs[0][R.F_KIND] == R.KIND_CHAOS
    assert recs[0]["fault_kind"] == "stale"
    assert recs[0]["point"] == "heartbeat.beat"
    assert plane.drain_records() == []  # drained means drained


# --------------------------------------------------------------------- #
# retry policy unit cells
# --------------------------------------------------------------------- #
def _policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    return RetryPolicy(**kw)


def test_retry_transient_eio_then_success():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError(errno.EIO, "transient")
        return "ok"

    p = _policy(retries=3)
    assert p.run(flaky, what="cell") == "ok"
    assert calls["n"] == 3
    assert p.counters["retries"] == 2
    assert p.counters["recovered"] == 1
    assert p.counters["gave_up"] == 0


def test_retry_budget_exhaustion_raises_original_with_attempt_count():
    boom = OSError(errno.EIO, "persistent EIO")

    def always():
        raise boom

    p = _policy(retries=2)
    with pytest.raises(OSError) as ei:
        p.run(always, what="cell")
    assert ei.value is boom            # the ORIGINAL error, not a wrapper
    assert ei.value.retry_attempts == 3  # 1 initial + 2 retries
    assert p.counters["gave_up"] == 1


def test_retry_never_retries_corruption():
    calls = {"n": 0}

    def corrupt():
        calls["n"] += 1
        raise CorruptionError("crc mismatch / torn manifest")

    p = _policy(retries=5)
    with pytest.raises(CorruptionError):
        p.run(corrupt)
    assert calls["n"] == 1             # exactly one attempt, no retry
    assert p.counters["retries"] == 0


def test_retry_never_retries_injected_crash():
    calls = {"n": 0}

    def crash():
        calls["n"] += 1
        raise InjectedCrash("simulated kill")

    p = _policy(retries=5)
    with pytest.raises(InjectedCrash):
        p.run(crash)
    assert calls["n"] == 1


def test_is_transient_classification():
    assert is_transient(OSError(errno.EIO, "x"))
    assert is_transient(OSError(errno.ENOSPC, "x"))
    assert is_transient(OSError("errno-less"))
    assert not is_transient(CorruptionError("crc"))
    assert not is_transient(ValueError("x"))
    assert not is_transient(OSError(errno.ENOENT, "missing"))


def test_backoff_deterministic_under_fixed_seed():
    def delays(seed):
        slept = []
        p = RetryPolicy(retries=4, backoff_s=0.5, max_backoff_s=2.0,
                        jitter=0.25, seed=seed, sleep=slept.append)
        with pytest.raises(OSError):
            p.run(lambda: (_ for _ in ()).throw(OSError(errno.EIO, "x")))
        return slept

    a, b = delays(9), delays(9)
    assert a == b and len(a) == 4
    # exponential base under the cap, jitter bounded
    for k, d in enumerate(a, start=1):
        base = min(0.5 * 2 ** (k - 1), 2.0)
        assert base <= d <= base * 1.25
    assert delays(10) != a  # the jitter stream really is seed-keyed


def test_retry_counters_snapshot_restore_roundtrip():
    p = _policy(retries=1)
    p.run(lambda: "ok", what="a")
    with pytest.raises(OSError):
        p.run(lambda: (_ for _ in ()).throw(OSError(errno.EIO, "x")),
              what="b")
    snap = p.snapshot()
    q = _policy(retries=1)
    q.restore(snap)
    assert q.snapshot() == snap
    q.restore(None)  # tolerated (old checkpoints)


def test_build_retry_policy_from_config():
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 8,
        "resilience": {"enabled": True, "io_retries": 4,
                       "retry_jitter": 0.5, "retry_seed": 3,
                       "retry_max_backoff_seconds": 7.0}})
    p = cfg.resilience_config.build_retry_policy(sleep=lambda s: None)
    assert p.retries == 4 and p.jitter == 0.5 and p.max_backoff_s == 7.0
    off = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 8})
    assert off.resilience_config.build_retry_policy() is None


# --------------------------------------------------------------------- #
# satellite bugfix: grace_s forced saves are single-process only
# --------------------------------------------------------------------- #
def test_grace_s_rejected_on_multihost_with_actionable_message(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    with pytest.raises(DeepSpeedConfigError) as ei:
        DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 8,
            "resilience": {"preemption": {"enabled": True,
                                          "grace_s": 30}}})
    msg = str(ei.value)
    assert "single-process only" in msg          # names the limitation
    assert "step-boundary emergency save" in msg  # names the alternative
    assert "4 processes" in msg


def test_grace_s_accepted_single_process():
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 8,
        "resilience": {"preemption": {"enabled": True, "grace_s": 30}}})
    assert cfg.resilience_config.preemption.grace_s == 30


# --------------------------------------------------------------------- #
# degradation registry
# --------------------------------------------------------------------- #
def test_degradation_dedups_and_drains_once():
    from deepspeed_tpu.monitor import record as R
    reg = degradation.get_registry()
    degradation.record("aio", "io_uring", "python", "probe failed")
    degradation.record("aio", "io_uring", "python", "probe failed again")
    degradation.record("tensorboard", "torch", "jsonl", "torch absent")
    evs = reg.events()
    assert len(evs) == 2
    assert evs[0]["count"] == 2        # repeats counted, not re-warned
    assert "aio:io_uring->python" in reg.summary()
    recs = reg.drain_records()
    assert {r[R.F_KIND] for r in recs} == {R.KIND_DEGRADATION}
    assert len(recs) == 2 and reg.drain_records() == []


# --------------------------------------------------------------------- #
# exchange watchdog: a rigged hang becomes an attributed eviction
# --------------------------------------------------------------------- #
def _hung_aggregator(arrival_ages):
    from deepspeed_tpu.monitor.fleet import FleetAggregator

    def gather(arr):
        return np.stack([arr, arr])    # 2-host fake fleet

    return FleetAggregator(process_index=0, process_count=2,
                           host="host-a", gather_fn=gather,
                           deadline_s=0.2,
                           arrival_fn=lambda: arrival_ages)


def test_watchdog_converts_hang_into_timeout_naming_missing_host():
    from deepspeed_tpu.monitor.fleet import ExchangeTimeout
    agg = _hung_aggregator({0: 0.0, 1: 500.0})  # peer 1 went dark
    plane = ChaosPlane([ChaosFault(point="fleet.exchange", kind="hang",
                                   at_call=1, args={"seconds": 30.0})])
    summary = {"step": 1, "steps": 1, "loss_mean": 0.0}
    with chaos.installed(plane):
        with pytest.raises(ExchangeTimeout) as ei:
            agg.exchange(summary)
    t = ei.value
    assert t.missing == [(1, "host-a")]
    assert "p1:host-a" in str(t) and "deadline" in str(t)
    assert agg.timeouts == 1
    # fault-free exchanges proceed normally under the same deadline
    assert agg.exchange(summary).shape[0] == 2


def test_watchdog_timeout_feeds_supervisor_eviction():
    from deepspeed_tpu.monitor.fleet import ExchangeTimeout
    from deepspeed_tpu.runtime.resilience.supervisor import SupervisorPolicy
    timeout = ExchangeTimeout("exchange missed 5.0s deadline",
                              missing=[(1, "host-b")], deadline_s=5.0)
    pol = SupervisorPolicy(min_world_size=1)
    pol.observe_exchange_timeout(timeout)
    decision = pol.decide(world_size=4)
    assert decision.action == "reshape"
    assert 1 in decision.drop
    assert "dead worker 1" in decision.reason


def test_watchdog_exception_fault_propagates_not_times_out():
    agg = _hung_aggregator({0: 0.0, 1: 0.0})
    plane = ChaosPlane([ChaosFault(point="fleet.exchange",
                                   kind="exception", at_call=1)])
    with chaos.installed(plane):
        with pytest.raises(InjectedFault):
            agg.exchange({"step": 1, "steps": 1})


# --------------------------------------------------------------------- #
# chaos matrix: every (fault kind x subsystem) cell either recovers
# with parity or fails loudly naming the injected fault
# --------------------------------------------------------------------- #
def _swapper(tmp_path, retry_policy=None):
    from deepspeed_tpu.runtime.swap_tensor.partitioned_param_swapper \
        import PartitionedParamSwapper
    tree = {"w": np.arange(64, dtype=np.float32)}
    sw = PartitionedParamSwapper(str(tmp_path / "swap"), {"g0": tree},
                                 buffer_count=2,
                                 retry_policy=retry_policy)
    return sw, tree


def test_matrix_aio_pread_eio_recovers_with_retry_and_parity(tmp_path):
    sw, tree = _swapper(tmp_path, _policy(retries=3))
    sw.write("g0", tree)
    sw.release("g0")
    plane = ChaosPlane([ChaosFault(point="aio.pread", kind="eio",
                                   at_call=1, repeat=2)])
    with chaos.installed(plane):
        got = sw.get("g0")             # 2 injected EIOs, then success
    np.testing.assert_array_equal(got["w"], tree["w"])  # parity
    assert sw.retry_policy.counters["recovered"] == 1
    assert [e["kind"] for e in plane.fired] == ["eio", "eio"]


def test_matrix_aio_pwrite_enospc_exhausts_budget_names_fault(tmp_path):
    sw, tree = _swapper(tmp_path, _policy(retries=1))
    plane = ChaosPlane([ChaosFault(point="aio.pwrite", kind="enospc",
                                   at_call=1, repeat=5)])
    with chaos.installed(plane):
        with pytest.raises(OSError) as ei:
            sw.write("g0", tree)
    assert ei.value.errno == errno.ENOSPC
    assert "chaos-injected enospc" in str(ei.value)   # names the fault
    assert ei.value.retry_attempts == 2
    assert sw.retry_policy.counters["gave_up"] == 1


def test_matrix_aio_without_retry_fails_on_first_injected_eio(tmp_path):
    sw, tree = _swapper(tmp_path, retry_policy=None)
    sw.write("g0", tree)
    sw.release("g0")
    plane = ChaosPlane([ChaosFault(point="aio.pread", kind="eio",
                                   at_call=1)])
    with chaos.installed(plane):
        with pytest.raises(OSError) as ei:
            sw.get("g0")
    assert "chaos-injected eio at aio.pread" in str(ei.value)


def test_matrix_manifest_torn_detected_never_retried(tmp_path):
    from deepspeed_tpu.runtime.resilience import atomic
    good = tmp_path / "good"
    good.mkdir()
    (good / "data.bin").write_bytes(b"payload")
    plane = ChaosPlane([ChaosFault(point="checkpoint.manifest",
                                   kind="torn_manifest", at_call=1)])
    with chaos.installed(plane):
        atomic.write_manifest(str(good))
    # the torn manifest is not valid JSON: verification must fail
    # loudly (CorruptionError family), and the retry policy must not
    # absorb it
    with pytest.raises(Exception) as ei:
        problems = atomic.verify_manifest(str(good))
        assert problems  # either raises or reports problems
        raise CorruptionError("; ".join(problems))
    assert not is_transient(ei.value)
    assert [e["kind"] for e in plane.fired] == ["torn_manifest"]


def test_matrix_commit_crash_leaves_no_final_dir_then_recovers(tmp_path):
    from deepspeed_tpu.runtime.resilience import atomic
    tmp_dir = atomic.tmp_tag_dir(str(tmp_path), "tag1")
    with open(os.path.join(tmp_dir, "data.bin"), "wb") as f:
        f.write(b"payload")
    plane = ChaosPlane([ChaosFault(point="checkpoint.commit",
                                   kind="crash", at_call=1)])
    with chaos.installed(plane):
        with pytest.raises(InjectedCrash):
            atomic.commit_tag_dir(str(tmp_path), "tag1", tmp_dir)
        # crash landed between stage and rename: no torn final dir
        assert not os.path.isdir(tmp_path / "tag1")
        # the "restarted process" re-commits; budget spent, so it lands
        final = atomic.commit_tag_dir(str(tmp_path), "tag1", tmp_dir)
    assert os.path.isdir(final)
    assert (tmp_path / "tag1" / "data.bin").read_bytes() == b"payload"


def test_matrix_heartbeat_stale_and_corrupt_surfaced(tmp_path):
    from deepspeed_tpu.monitor.heartbeat import (HeartbeatWriter,
                                                 read_heartbeats)
    hb_dir = str(tmp_path / "hb")
    w = HeartbeatWriter(hb_dir, process_index=0, world_size=1)
    w.beat(step=1)
    first = read_heartbeats(hb_dir)[0]
    plane = ChaosPlane([
        ChaosFault(point="heartbeat.beat", kind="stale", at_call=1),
        ChaosFault(point="heartbeat.beat", kind="corrupt", at_call=2)])
    with chaos.installed(plane):
        w.beat(step=2)                 # stale: write silently skipped
        assert read_heartbeats(hb_dir)[0]["step"] == first["step"]
        w.beat(step=3)                 # corrupt: torn garbage on disk
    rows = read_heartbeats(hb_dir)
    assert rows[0]["status"] == "corrupt"
    assert rows[0]["process_index"] == 0


def test_matrix_batch_poison_sentinel_skips_and_records(tmp_path):
    cfg = {"resilience": {"enabled": True,
                          "sentinel": {"enabled": True,
                                       "policy": "skip_step",
                                       "warmup_steps": 3}},
           "monitor": {"enabled": False}}
    e = make_engine(**cfg)
    plane = ChaosPlane([ChaosFault(point="batch.next", kind="poison",
                                   at_step=4)])
    with chaos.installed(plane):
        run_steps(e, 5)
        # the chaos record names the injected fault for post-mortem
        recs = e._drain_resilience_records()
    # the poisoned step was skipped (the recovery), training continued
    assert e.sentinel.counters()["steps_skipped"] == 1
    assert e.global_steps == 5
    kinds = [(r["fault_kind"], r["point"]) for r in recs
             if r.get("fault_kind")]
    assert ("poison", "batch.next") in kinds


def test_matrix_ckpt_stage_eio_retried_save_load_parity(tmp_path):
    cfg = {"resilience": {"enabled": True, "io_retries": 3,
                          "io_backoff_seconds": 0.0}}
    e = make_engine(**cfg)
    e._retry_policy._sleep = lambda s: None
    run_steps(e, 2)
    plane = ChaosPlane([ChaosFault(point="checkpoint.stage", kind="eio",
                                   at_call=1, repeat=2)])
    with chaos.installed(plane):
        e.save_checkpoint(str(tmp_path), tag="chaosed")
    assert [f["kind"] for f in plane.fired] == ["eio", "eio"]
    assert e._retry_policy.counters["recovered"] >= 1
    # the tally is snapshotted into client state at the NEXT save (the
    # current save's own I/O happens after its client dict is sealed) —
    # same boundary semantics as the sentinel counters
    e.save_checkpoint(str(tmp_path), tag="final")

    e2 = make_engine(**cfg)
    _, client = e2.load_checkpoint(str(tmp_path), tag="final")
    jax.tree.map(np.testing.assert_array_equal,
                 jax.tree.map(np.asarray, e.params),
                 jax.tree.map(np.asarray, e2.params))
    # the retry tally rode client state (sentinel-counter pattern)
    assert e2._retry_policy.counters["recovered"] >= 1
    assert client["retry_counters"]["recovered"] >= 1


def test_matrix_step_boundary_sigterm_emergency_save_and_resume(tmp_path):
    from deepspeed_tpu.runtime.resilience.preemption import \
        TrainingInterrupted
    cfg = {"resilience": {
        "enabled": True,
        "preemption": {"enabled": True, "reraise": False,
                       "save_dir": str(tmp_path)},
        "chaos": {"enabled": True,
                  "faults": [{"point": "step.boundary", "kind": "sigterm",
                              "at_step": 2}]}}}
    e = make_engine(**cfg)
    try:
        assert chaos.active() is not None  # engine installed the plane
        it = run_steps(e, 1)
        x, y = next(it)
        e.backward(e.forward(x, y))
        with pytest.raises(TrainingInterrupted) as ei:
            e.step()               # chaos delivers SIGTERM at step 2
        tag = ei.value.emergency_tag
        assert tag == "emergency_step2"
        assert os.path.isdir(tmp_path / tag)
        chaos.uninstall()

        e2 = make_engine()
        e2.load_checkpoint(str(tmp_path), tag=tag)
        assert e2.global_steps == 2
        jax.tree.map(np.testing.assert_array_equal,
                     jax.tree.map(np.asarray, e.params),
                     jax.tree.map(np.asarray, e2.params))
    finally:
        if e._preemption is not None:
            e._preemption.uninstall()


def test_matrix_step_boundary_crash_raises_injected_crash():
    cfg = {"resilience": {"chaos": {
        "enabled": True, "seed": 5,
        "faults": [{"point": "step.boundary", "kind": "crash",
                    "at_step": 2}]}}}
    e = make_engine(**cfg)
    run_steps(e, 1)
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    it = iter(RepeatingLoader(random_dataloader(HIDDEN, 32, 8, seed=3)))
    x, y = next(it)
    e.backward(e.forward(x, y))
    with pytest.raises(InjectedCrash, match="step.boundary"):
        e.step()
    # the "killed" process's plane still knows exactly what it did
    assert chaos.active().fired[0]["step"] == 2


def test_legacy_fault_injection_shim_still_works(tmp_path):
    # deprecated import path (test_resilience/test_infinity_prefetch
    # call sites): same objects, no behavior change
    from deepspeed_tpu.runtime.resilience import fault_injection as fi
    assert fi.InjectedCrash is InjectedCrash
    assert fi.poison_batch is chaos.poison_batch
    with fi.crash_after_bytes(4, path_prefix=str(tmp_path)):
        with pytest.raises(InjectedCrash):
            with open(tmp_path / "f.bin", "wb") as f:
                f.write(b"12345")


def test_legacy_fault_injection_shim_names_its_replacement():
    # the deprecation must point movers at the chaos plane by module
    # path — a bare "deprecated" is not actionable
    import importlib
    import warnings as _warnings

    from deepspeed_tpu.runtime.resilience import fault_injection as fi
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        importlib.reload(fi)  # the warning fires at import time
    dep = [w for w in caught
           if issubclass(w.category, DeprecationWarning)]
    assert len(dep) == 1
    msg = str(dep[0].message)
    assert "deepspeed_tpu.runtime.resilience.fault_injection is " \
        "deprecated" in msg
    assert "deepspeed_tpu.runtime.resilience.chaos" in msg


def test_engine_drains_degradation_records():
    from deepspeed_tpu.monitor import record as R
    e = make_engine()
    degradation.record("aio", "io_uring", "python", "probe failed")
    recs = e._drain_resilience_records()
    deg = [r for r in recs if r[R.F_KIND] == R.KIND_DEGRADATION]
    assert len(deg) == 1 and deg[0]["subsystem"] == "aio"
