"""Collect-only smoke check: the tier-1 command runs with
``--continue-on-collection-errors``, so an ImportError in one test module
silently shrinks the suite instead of failing it.  This test makes any
collection error loud: it re-collects the unit suite WITHOUT that flag
(collection errors -> nonzero rc) and sanity-checks the collected count
so a mass-deselection regression can't hide either.  ~5 s, fast lane."""

import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

# floor well under the current count (458 at introduction) but high
# enough that losing a whole module to an import error trips it
MIN_COLLECTED = 400


def test_resilience_package_imports_cleanly():
    """Lazily-imported engine modules (resilience: only when the config
    block is on; fused_step: only when fused_step.enabled) would not
    surface a syntax/import error in most tests — and an ImportError in
    their test modules would just shrink the suite under
    --continue-on-collection-errors.  Import each explicitly, in a
    subprocess, so it fails loudly."""
    mods = ("deepspeed_tpu.runtime.resilience",
            "deepspeed_tpu.runtime.resilience.atomic",
            "deepspeed_tpu.runtime.resilience.recovery",
            "deepspeed_tpu.runtime.resilience.preemption",
            "deepspeed_tpu.runtime.resilience.sentinel",
            "deepspeed_tpu.runtime.resilience.fault_injection",
            # chaos plane + retry/degradation (round 21): fired lazily
            # from guarded imports at every injection surface — a broken
            # standalone import would silently disable fault injection
            "deepspeed_tpu.runtime.resilience.chaos",
            "deepspeed_tpu.runtime.resilience.retry",
            "deepspeed_tpu.runtime.resilience.degradation",
            # elastic self-healing layer: reshard validation is lazily
            # imported inside save/load_checkpoint; the supervisor is
            # jax-free and imported by controller-side scripts only
            "deepspeed_tpu.runtime.resilience.reshard",
            "deepspeed_tpu.runtime.resilience.supervisor",
            "deepspeed_tpu.runtime.fused_step",
            # program auditor: lazily imported by the engine (only when
            # the analysis block is on) and by the CLI entry point
            "deepspeed_tpu.analysis",
            "deepspeed_tpu.analysis.cli",
            "deepspeed_tpu.analysis.__main__",
            # HLO-level SPMD cross-check: lazily reachable through the
            # auditor's hlo path and the CLI's --hlo-audit
            "deepspeed_tpu.analysis.hlo_audit",
            # config autotuner: lazily imported by the tune/calibrate
            # subcommands and bench.py's autotune ladder row
            "deepspeed_tpu.analysis.search_space",
            "deepspeed_tpu.analysis.autotuner",
            # source-invariant lint (round 22): lazily imported by the
            # lint-source subcommand; jax-free by design, so nothing
            # else in the suite would catch a break in it
            "deepspeed_tpu.analysis.source_lint",
            "deepspeed_tpu.analysis.source_lint.core",
            "deepspeed_tpu.analysis.source_lint.manifest",
            "deepspeed_tpu.analysis.source_lint.runner",
            "deepspeed_tpu.analysis.source_lint.rules_thread",
            "deepspeed_tpu.analysis.source_lint.rules_determinism",
            "deepspeed_tpu.analysis.source_lint.rules_degradation",
            "deepspeed_tpu.analysis.source_lint.rules_knobs",
            "deepspeed_tpu.analysis.source_lint.rules_checkpoint",
            # fused collective-matmul kernels: lazily reachable through
            # the streaming context's fcm routing and the bench fcm row
            "deepspeed_tpu.ops.collective_matmul",
            # 1-bit optimizer wire tier: the compressed transport and
            # wire accounting are lazily imported by the engine (only
            # when low_bandwidth.onebit is on) and by bench.py's
            # gpt2_onebit row
            "deepspeed_tpu.runtime.comm.onebit",
            "deepspeed_tpu.runtime.comm.compressed",
            "deepspeed_tpu.runtime.comm.low_bandwidth",
            # telemetry monitor: lazily imported by the engines (only
            # when the monitor block is on)
            "deepspeed_tpu.monitor",
            "deepspeed_tpu.monitor.record",
            "deepspeed_tpu.monitor.writers",
            "deepspeed_tpu.monitor.trace",
            "deepspeed_tpu.monitor.reconcile",
            "deepspeed_tpu.monitor.monitor",
            # fleet observability layer (monitor.fleet is lazily
            # reachable through the launcher's --watch too)
            "deepspeed_tpu.monitor.fleet",
            "deepspeed_tpu.monitor.health",
            "deepspeed_tpu.monitor.heartbeat",
            "deepspeed_tpu.monitor.capture",
            # MoE routing observability (monitor.moe is lazily reachable
            # through TrainingMonitor and the bench moe rows)
            "deepspeed_tpu.monitor.moe")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c",
         "import importlib\n"
         + "\n".join(f"importlib.import_module({m!r})" for m in mods)],
        cwd=str(REPO), capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, (
        f"resilience package import failed:\n{out.stderr[-2000:]}")


def test_unit_suite_collects_cleanly():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/unit", "--collect-only",
         "-q", "-p", "no:cacheprovider"],
        cwd=str(REPO), capture_output=True, text=True, timeout=300,
        env=env)
    tail = "\n".join(out.stdout.splitlines()[-25:])
    assert out.returncode == 0, (
        f"unit-suite collection failed (rc={out.returncode}) — a test "
        f"module no longer imports:\n{tail}\n{out.stderr[-2000:]}")
    m = re.search(r"(\d+) tests? collected", out.stdout)
    assert m, f"no collection summary in output:\n{tail}"
    count = int(m.group(1))
    assert count >= MIN_COLLECTED, (
        f"only {count} tests collected (expected >= {MIN_COLLECTED}) — "
        "did a module or parametrization silently vanish?")


def test_fused_step_tests_run_in_fast_lane():
    """Fast-lane marker audit: the fused-step regression surface (parity,
    dispatch count, fallback matrix) must run in tier-1, i.e. survive the
    `-m "not slow"` deselection — a conftest _SLOW_PREFIXES entry or a
    stray marker would silently drop the whole module from the gate."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/unit/test_fused_step.py",
         "--collect-only", "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        cwd=str(REPO), capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, (
        f"fused-step collection failed:\n{out.stdout[-1500:]}"
        f"\n{out.stderr[-1500:]}")
    m = re.search(r"(\d+) tests? collected", out.stdout)
    assert m, f"no collection summary:\n{out.stdout[-1500:]}"
    selected = int(m.group(1))
    dm = re.search(r"(\d+) deselected", out.stdout)
    deselected = int(dm.group(1)) if dm else 0
    assert selected >= 15 and deselected == 0, (
        f"fused-step fast lane shrank: {selected} selected, "
        f"{deselected} deselected — the tier-1 gate no longer covers the "
        "fused path")
