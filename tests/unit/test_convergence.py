"""Convergence-to-target tests — the role the reference's tests/model/
end-to-end runs play (run_func_test.py / BingBertSquad F1 checks): the full
engine must LEARN a learnable task to a target loss, not just execute.

Task: deterministic successor sequences (x_{t+1} = (x_t + step) % V).  A
2-layer causal LM solves it from the previous token alone, so the loss
must approach zero; failure modes this catches that per-module tests do
not: broken loss scaling, optimizer wiring, dropout/rng misuse, label
shift off-by-one, LR schedule misapplication.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model

VOCAB, SEQ, BATCH = 32, 32, 8


def _batches(n_steps, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n_steps):
        start = rng.randint(0, VOCAB, size=(BATCH, 1))
        step = rng.randint(1, 4, size=(BATCH, 1))
        pos = np.arange(SEQ)[None, :]
        yield ((start + step * pos) % VOCAB).astype(np.int32)


@pytest.mark.parametrize("zero_stage", [0, 2])
def test_gpt2_engine_converges_on_successor_task(zero_stage):
    cfg = GPT2Config(vocab_size=VOCAB, n_positions=SEQ, hidden_size=64,
                     num_layers=2, num_heads=2, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_num_steps": 20,
                                     "warmup_max_lr": 3e-3}},
            "zero_optimization": {"stage": zero_stage},
            "steps_per_print": 10 ** 9,
        })
    first = last = None
    for ids in _batches(150):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        if first is None:
            first = float(loss)
        last = float(loss)
    # random-chance CE is ln(32) ~ 3.47; the task is exactly learnable
    assert first > 2.0, f"suspicious start loss {first}"
    assert last < 0.35, (f"engine failed to learn the successor task: "
                         f"start {first:.3f} -> end {last:.3f}")


def test_gpt2_engine_converges_bf16_with_dropout():
    """bf16 compute + dropout + GAS=2 — the production configuration must
    also learn (catches bf16 cast bugs and dropout-rng reuse)."""
    cfg = GPT2Config(vocab_size=VOCAB, n_positions=SEQ, hidden_size=64,
                     num_layers=2, num_heads=2, bf16=True,
                     embd_dropout=0.05, attn_dropout=0.05,
                     hidden_dropout=0.05)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9,
        })
    last = None
    for ids in _batches(300, seed=1):  # 2 micro-batches per step
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        last = float(loss)
    assert last < 0.6, f"bf16+dropout config failed to learn: end {last:.3f}"


# --------------------------------------------------------------------- #
# Chip-scale tier (reference: tests/model/run_func_test.py:606 — real
# runs diffed against stored baselines).  benchmarks/convergence_run.py
# trains the flagship GPT-2 124M on the chip and stores its curve under
# tests/baselines/; these tests gate regressions against that artifact.
# --------------------------------------------------------------------- #
import json
import os
import sys

_BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "baselines",
    "convergence_gpt2_124m.json")


def _conv_mod():
    bench_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "benchmarks")
    sys.path.insert(0, bench_dir)
    try:
        import convergence_run
    finally:
        sys.path.remove(bench_dir)
    return convergence_run


def test_markov_floor_matches_brute_force():
    """The analytic floor (mean true -log p(next|prev)) must equal a
    brute-force per-transition lookup — the threshold the chip run is
    judged against has to be trustworthy."""
    cr = _conv_mod()
    lang = cr.MarkovLanguage(vocab=64, n_succ=8, seed=7)
    ids = lang.sample(4, 32, np.random.RandomState(3))
    expect = []
    for b in range(ids.shape[0]):
        for t in range(1, ids.shape[1]):
            prev, nxt = int(ids[b, t - 1]), int(ids[b, t])
            p = sum(w for s, w in zip(lang.succ[prev], lang.row_probs)
                    if s == nxt)
            expect.append(-np.log(max(p, 1e-12)))
    assert abs(lang.floor_nats(ids) - float(np.mean(expect))) < 1e-9
    # and sampling really follows the table: every transition possible
    assert np.isfinite(lang.floor_nats(ids))
    assert lang.floor_nats(ids) < np.log(64)  # structured, not uniform


def test_chip_convergence_baseline():
    """Gate on the stored chip run: it must exist (after the first
    measured round), be from real hardware, and show convergence to the
    analytic-floor threshold."""
    if not os.path.exists(_BASELINE):
        import pytest as _pytest
        _pytest.skip("no stored chip convergence baseline yet "
                     "(benchmarks/convergence_run.py writes it)")
    with open(_BASELINE) as f:
        base = json.load(f)
    assert base["platform"] == "tpu", "baseline must come from the chip"
    assert base["converged"] is True
    assert base["final_val_loss"] <= base["threshold_nats"]
    # the curve must actually descend (no flat/NaN runs sneaking in)
    first_val = base["val_curve"][0][1]
    last_val = base["val_curve"][-1][1]
    assert last_val < first_val - 1.0, (first_val, last_val)
    # floor math is reproducible from the seed: re-derive and compare
    cr = _conv_mod()
    lang = cr.MarkovLanguage()
    val_rng = np.random.RandomState(9999)
    floors = [lang.floor_nats(lang.sample(cr.BATCH, cr.SEQ, val_rng))
              for _ in range(cr.VAL_BATCHES)]
    assert abs(float(np.mean(floors)) - base["analytic_floor_nats"]) < 2e-3
