"""Convergence-to-target tests — the role the reference's tests/model/
end-to-end runs play (run_func_test.py / BingBertSquad F1 checks): the full
engine must LEARN a learnable task to a target loss, not just execute.

Task: deterministic successor sequences (x_{t+1} = (x_t + step) % V).  A
2-layer causal LM solves it from the previous token alone, so the loss
must approach zero; failure modes this catches that per-module tests do
not: broken loss scaling, optimizer wiring, dropout/rng misuse, label
shift off-by-one, LR schedule misapplication.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model

VOCAB, SEQ, BATCH = 32, 32, 8


def _batches(n_steps, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n_steps):
        start = rng.randint(0, VOCAB, size=(BATCH, 1))
        step = rng.randint(1, 4, size=(BATCH, 1))
        pos = np.arange(SEQ)[None, :]
        yield ((start + step * pos) % VOCAB).astype(np.int32)


@pytest.mark.parametrize("zero_stage", [0, 2])
def test_gpt2_engine_converges_on_successor_task(zero_stage):
    cfg = GPT2Config(vocab_size=VOCAB, n_positions=SEQ, hidden_size=64,
                     num_layers=2, num_heads=2, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_num_steps": 20,
                                     "warmup_max_lr": 3e-3}},
            "zero_optimization": {"stage": zero_stage},
            "steps_per_print": 10 ** 9,
        })
    first = last = None
    for ids in _batches(150):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        if first is None:
            first = float(loss)
        last = float(loss)
    # random-chance CE is ln(32) ~ 3.47; the task is exactly learnable
    assert first > 2.0, f"suspicious start loss {first}"
    assert last < 0.35, (f"engine failed to learn the successor task: "
                         f"start {first:.3f} -> end {last:.3f}")


def test_gpt2_engine_converges_bf16_with_dropout():
    """bf16 compute + dropout + GAS=2 — the production configuration must
    also learn (catches bf16 cast bugs and dropout-rng reuse)."""
    cfg = GPT2Config(vocab_size=VOCAB, n_positions=SEQ, hidden_size=64,
                     num_layers=2, num_heads=2, bf16=True,
                     embd_dropout=0.05, attn_dropout=0.05,
                     hidden_dropout=0.05)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9,
        })
    last = None
    for ids in _batches(300, seed=1):  # 2 micro-batches per step
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        last = float(loss)
    assert last < 0.6, f"bf16+dropout config failed to learn: end {last:.3f}"
