"""Vocab-parallel embedding + fused vocab-parallel cross-entropy
(ops/vocab_parallel.py) — the manual-TP aux chains of the gated 1F1B
executor (Megatron VocabParallelEmbedding / parallel-CE role).

Parity bar: exact agreement with the replicated lookup and with
optax.softmax_cross_entropy_with_integer_labels on full fp32 logits —
forward AND all grads, with no post-hoc correction (the custom VJPs
place the f/g collectives internally)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.ops.vocab_parallel import (
    vocab_parallel_embedding, vocab_parallel_linear_cross_entropy)

V, H, N = 64, 16, 24


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    return {
        "wte": jnp.asarray(rng.standard_normal((V, H)).astype(np.float32))
        * 0.1,
        "head": jnp.asarray(rng.standard_normal((H, V)).astype(np.float32))
        * 0.1,
        "ids": jnp.asarray(rng.randint(0, V, N).astype(np.int32)),
        "h": jnp.asarray(rng.standard_normal((N, H)).astype(np.float32)),
    }


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_embedding_and_ce_match_replicated(tp, data):
    wte, head, ids, h = (data["wte"], data["head"], data["ids"], data["h"])

    def ref_emb_loss(w):
        return (w[ids].astype(jnp.float32) ** 2).sum()

    def ref_ce(h_, w_):
        logits = (h_ @ w_).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, ids).mean()

    ref_emb = wte[ids]
    ref_gw = jax.grad(ref_emb_loss)(wte)
    ref_loss = ref_ce(h, head)
    ref_gh, ref_ghead = jax.grad(ref_ce, argnums=(0, 1))(h, head)

    mesh = Mesh(np.array(jax.devices()[:tp]).reshape(tp), ("model",))

    def region(wte_l, head_l, h_, ids_):
        emb = vocab_parallel_embedding(wte_l, ids_, "model")
        gw = jax.grad(
            lambda w: (vocab_parallel_embedding(w, ids_, "model")
                       .astype(jnp.float32) ** 2).sum())(wte_l)
        loss = vocab_parallel_linear_cross_entropy(h_, head_l, ids_,
                                                   "model")
        gh, ghead = jax.grad(
            lambda a, b: vocab_parallel_linear_cross_entropy(
                a, b, ids_, "model"), argnums=(0, 1))(h_, head_l)
        return emb, gw, loss, gh, ghead

    f = jax.jit(jax.shard_map(
        region, mesh=mesh,
        in_specs=(P("model", None), P(None, "model"), P(), P()),
        out_specs=(P(), P("model", None), P(), P(), P(None, "model")),
        axis_names=frozenset({"model"}), check_vma=False))
    emb, gw, loss, gh, ghead = f(wte, head, h, ids)

    np.testing.assert_allclose(np.asarray(emb), np.asarray(ref_emb),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ref_gw),
                               atol=1e-5)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-6)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(ref_gh),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(ghead), np.asarray(ref_ghead),
                               atol=1e-5)


from tests.unit.seed_xfails import (  # noqa: E402 — marker for the triaged seed failures
    PARTITION_ID_XFAIL as _PARTITION_ID_XFAIL)


@_PARTITION_ID_XFAIL
def test_indivisible_vocab_declines_aux_manual():
    """A vocab the model axis can't divide must fall back to replicated
    aux chains (tp_manual_aux_supports False) while the BLOCKS still
    gate with manual TP — not crash, not silently shard wrong."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    cfg = GPT2Config(vocab_size=65, n_positions=16, hidden_size=32,
                     num_layers=4, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0,
                     hidden_dropout=0.0)
    engine = PipelineEngine(
        model=gpt2_pipeline_module(cfg, num_stages=2),
        config={"train_batch_size": 8,
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9},
        example_input=jnp.zeros((4, 16), jnp.int32),
        rng=jax.random.PRNGKey(0))
    assert engine.schedule_gated is True
    assert engine._tp_manual is True
    assert engine._tp_aux_manual is False
    ids = np.random.RandomState(0).randint(0, 65, size=(4, 16)).astype(
        np.int32)
    loss = engine.train_batch(iter([(ids, ids), (ids, ids)]))
    assert np.isfinite(loss)
    deepspeed_tpu.reset_mesh_context()


@_PARTITION_ID_XFAIL
def test_gated_tp_bf16_smoke():
    """bf16 gated-TP with vocab-parallel aux: the manual branches cast
    params/activations at several boundaries (qkv einsum, psum merges,
    CE's fp32 logits accumulation) — all trajectory tests run fp32, so
    this is the only exercise of those casts.  One step, finite loss."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=4, num_heads=4, bf16=True,
                     embd_dropout=0.1, attn_dropout=0.1,
                     hidden_dropout=0.1)
    engine = PipelineEngine(
        model=gpt2_pipeline_module(cfg, num_stages=2),
        config={"train_batch_size": 8,
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9},
        example_input=jnp.zeros((4, 16), jnp.int32),
        rng=jax.random.PRNGKey(0))
    assert engine.schedule_gated and engine._tp_manual
    assert engine._tp_aux_manual
    ids = np.random.RandomState(0).randint(0, 64, size=(4, 16)).astype(
        np.int32)
    loss = engine.train_batch(iter([(ids, ids), (ids, ids)]))
    assert np.isfinite(loss)
    deepspeed_tpu.reset_mesh_context()


@_PARTITION_ID_XFAIL
def test_untied_head_vocab_parallel_trajectory():
    """Untied-head GPT-2 (independent lm_head, vocab-sharded over the
    model axis through pre_s/post_s specs) under pipe=2 x tp=2 matches
    the pipe=1/tp=1 trajectory — the untied branch of
    _attach_vocab_parallel_aux (the 3D matrix covers the tied branch)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    def train(pipe, tp, steps=3):
        deepspeed_tpu.reset_mesh_context()
        mesh = deepspeed_tpu.initialize_mesh(pipe=pipe, model=tp, data=-1)
        dp = mesh.data_parallel_world_size
        cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                         num_layers=4, num_heads=4, bf16=False,
                         tie_word_embeddings=False,
                         embd_dropout=0.0, attn_dropout=0.0,
                         hidden_dropout=0.0)
        engine = PipelineEngine(
            model=gpt2_pipeline_module(cfg, num_stages=pipe),
            config={"train_batch_size": 16,
                    "train_micro_batch_size_per_gpu": 8 // dp,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 10 ** 9},
            example_input=jnp.zeros((8, 16), jnp.int32),
            rng=jax.random.PRNGKey(5))
        rs = np.random.RandomState(0)
        losses = []
        for _ in range(steps):
            micro = [(ids, ids) for ids in
                     (rs.randint(0, 64, size=(8, 16)).astype(np.int32)
                      for _ in range(2))]
            losses.append(float(engine.train_batch(iter(micro))))
        aux = engine._tp_aux_manual if tp > 1 else None
        deepspeed_tpu.reset_mesh_context()
        return losses, aux

    base, _ = train(1, 1)
    got, aux = train(2, 2)
    assert aux is True
    np.testing.assert_allclose(got, base, rtol=2e-5)
