"""GPT-MoE model family: expert FFNs on alternating layers (reference
pattern: Megatron-MoE / GShard put the MoE layer in the FFN position —
deepspeed/moe/layer.py:18; interleaved dense/expert layers in the
0.5.2-era examples)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPTMoEConfig, GPTMoEModel

V, S, H = 128, 32, 32


def _cfg(**kw):
    defaults = dict(vocab_size=V, n_positions=S, hidden_size=H,
                    num_layers=4, num_heads=4, num_experts=4, top_k=2,
                    bf16=False, embd_dropout=0.0, attn_dropout=0.0,
                    hidden_dropout=0.0, capacity_factor=4.0,
                    min_capacity=64)
    defaults.update(kw)
    return GPTMoEConfig(**defaults)


@pytest.fixture
def ep_mesh():
    ds.reset_mesh_context()
    yield ds.initialize_mesh(expert=4, data=-1)
    ds.reset_mesh_context()


def test_param_count_exact(ep_mesh):
    cfg = _cfg()
    model = GPTMoEModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    actual = sum(int(np.prod(np.shape(leaf))) for leaf in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_layer_interleave(ep_mesh):
    cfg = _cfg(num_layers=6, moe_every=2)
    model = GPTMoEModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    for i, lp in enumerate(params["h"]):
        assert ("moe" in lp) == cfg.is_moe_layer(i)
    # every other layer is MoE: 1, 3, 5
    assert sum("moe" in lp for lp in params["h"]) == 3


def test_logits_shape_and_aux_loss(ep_mesh):
    cfg = _cfg()
    model = GPTMoEModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = np.random.RandomState(0).randint(0, V, (2, S)).astype(np.int32)
    logits = model.logits(params, jnp.asarray(ids), deterministic=True)
    assert logits.shape == (2, S, V) and logits.dtype == jnp.float32
    _, l_aux = model.hidden_states(params, jnp.asarray(ids),
                                   deterministic=True)
    assert float(l_aux) > 0.0  # load-balance loss is live


def test_engine_training_converges(ep_mesh):
    cfg = _cfg()
    model = GPTMoEModel(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10 ** 9})
    ids = np.random.RandomState(0).randint(0, V, (8, S)).astype(np.int32)
    losses = []
    for _ in range(8):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_expert_params_sharded_over_expert_axis(ep_mesh):
    cfg = _cfg()
    model = GPTMoEModel(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10 ** 9})
    moe_layer = engine.params["h"][1]
    wi = moe_layer["moe"]["experts"]["wi"]
    assert "expert" in str(wi.sharding.spec), wi.sharding.spec
    # dense layers keep the Megatron TP spec shape (no expert axis)
    dense = engine.params["h"][0]
    assert "expert" not in str(dense["attn_qkvw"].sharding.spec)


def test_moe_every_zero_is_all_dense(ep_mesh):
    """moe_every=0 degenerates to a plain dense GPT (no MoE layers)."""
    cfg = _cfg(moe_every=0)
    model = GPTMoEModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    assert all("moe" not in lp for lp in params["h"])
    ids = np.random.RandomState(0).randint(0, V, (2, S)).astype(np.int32)
    _, l_aux = model.hidden_states(params, jnp.asarray(ids),
                                   deterministic=True)
    assert float(l_aux) == 0.0


def test_moe_every_one_is_all_moe(ep_mesh):
    cfg = _cfg(moe_every=1)
    model = GPTMoEModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    assert all("moe" in lp for lp in params["h"])


def test_checkpoint_roundtrip(ep_mesh, tmp_path):
    """Save/load with expert-sharded params and optimizer state over the
    heterogeneous per-layer tuple tree."""
    cfg = _cfg(num_layers=2)
    model = GPTMoEModel(cfg)
    conf = {"train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9}
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config=conf)
    ids = np.random.RandomState(0).randint(0, V, (8, S)).astype(np.int32)
    for _ in range(2):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path), tag="moe")

    engine2, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(1)),
        config=conf)
    engine2.load_checkpoint(str(tmp_path), tag="moe")
    assert engine2.global_steps == 2
    for a, b in zip(jax.tree.leaves(jax.device_get(engine.params)),
                    jax.tree.leaves(jax.device_get(engine2.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # trajectories continue identically
    l1 = float(engine.forward(ids))
    l2 = float(engine2.forward(ids))
    assert l1 == l2


@pytest.mark.parametrize("zero", [3])
def test_engine_training_zero3(ep_mesh, zero):
    """GPT-MoE under GSPMD ZeRO-3 (heterogeneous per-layer tuples shard
    declaratively; the explicit streaming executor only engages for
    homogeneous stacked models and stays off here)."""
    cfg = _cfg(num_layers=2)
    model = GPTMoEModel(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": zero},
                "steps_per_print": 10 ** 9})
    ids = np.random.RandomState(0).randint(0, V, (8, S)).astype(np.int32)
    losses = []
    for _ in range(6):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_engine_training_tp_times_ep():
    """TP x EP x DP on one mesh: dense layers Megatron-split over 'model',
    experts over 'expert', batch over 'data' (2x2x2 on the 8-device sim
    mesh)."""
    ds.reset_mesh_context()
    ds.initialize_mesh(expert=2, model=2, data=-1)
    try:
        cfg = _cfg(num_layers=2, num_experts=2, hidden_size=64)
        model = GPTMoEModel(cfg)
        engine, _, _, _ = ds.initialize(
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)),
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1},
                    "steps_per_print": 10 ** 9})
        dense_qkv = engine.params["h"][0]["attn_qkvw"]
        assert "model" in str(dense_qkv.sharding.spec)
        wi = engine.params["h"][1]["moe"]["experts"]["wi"]
        assert "expert" in str(wi.sharding.spec)
        ids = np.random.RandomState(0).randint(0, V, (8, S)).astype(np.int32)
        losses = []
        for _ in range(6):
            loss = engine.forward(ids)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
    finally:
        ds.reset_mesh_context()


def test_fp16_consolidated_export(ep_mesh, tmp_path):
    """save_fp16_model flattens the heterogeneous per-layer tuple tree
    (expert-sharded leaves gathered) into one serving .npz."""
    cfg = _cfg(num_layers=2)
    model = GPTMoEModel(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10 ** 9})
    path = engine.save_fp16_model(str(tmp_path))
    data = np.load(path)
    n = sum(int(np.prod(v.shape)) for v in data.values())
    assert n == cfg.num_params()
    # an expert leaf made it out whole (unsharded) in fp16
    expert_keys = [k for k in data.files if "moe" in k and "wi" in k]
    assert expert_keys and data[expert_keys[0]].dtype == np.float16
    assert data[expert_keys[0]].shape[0] == cfg.num_experts
