"""Tests for the round-2 performance paths: layer-stack unroll vs scan,
attention impl dispatch, kernel-backend override, windowed ThroughputTimer,
and the fused-CE auto chunk policy."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.models.layer_stack import (SCAN_LAYERS_AUTO_THRESHOLD,
                                              resolve_use_scan,
                                              run_layer_stack)
from deepspeed_tpu.ops import dispatch
from deepspeed_tpu.ops.flash_attention import (DEFAULT_BLOCK_K,
                                               DEFAULT_BLOCK_Q,
                                               flash_attention, mha_reference)
from deepspeed_tpu.utils.timer import ThroughputTimer


def test_resolve_use_scan_policy():
    assert resolve_use_scan(None, SCAN_LAYERS_AUTO_THRESHOLD) is False
    assert resolve_use_scan(None, SCAN_LAYERS_AUTO_THRESHOLD + 1) is True
    assert resolve_use_scan(True, 2) is True
    assert resolve_use_scan(False, 100) is False


def test_run_layer_stack_scan_unrolled_equivalent():
    def body(carry, xs):
        w, b = xs
        return jnp.tanh(carry @ w + b), None

    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(3, 8, 8) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(3, 8) * 0.1, jnp.float32)
    x = jnp.asarray(rng.randn(4, 8), jnp.float32)
    out_scan = run_layer_stack(body, x, (ws, bs), use_scan=True)
    out_unroll = run_layer_stack(body, x, (ws, bs), use_scan=False)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_unroll),
                               rtol=1e-6)


def test_gpt2_scan_vs_unrolled_same_loss():
    """The scan_layers flag changes execution strategy only — identical
    math (deterministic path; dropout rng folding differs by design)."""
    kw = dict(vocab_size=128, n_positions=32, hidden_size=32, num_layers=2,
              num_heads=2, bf16=False, embd_dropout=0.0, attn_dropout=0.0,
              hidden_dropout=0.0)
    m_scan = GPT2Model(GPT2Config(scan_layers=True, **kw))
    m_unroll = GPT2Model(GPT2Config(scan_layers=False, **kw))
    params = m_scan.init_params(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 32)),
                      jnp.int32)
    l1 = float(m_scan.loss(params, None, ids))
    l2 = float(m_unroll.loss(params, None, ids))
    assert abs(l1 - l2) < 1e-5, (l1, l2)


def test_flash_attention_impl_dispatch():
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 2, 64, 16),
                                 jnp.float32) for i in range(3))
    ref = mha_reference(q, k, v, causal=True)
    for impl in ("auto", "xla"):
        out = flash_attention(q, k, v, causal=True, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    # impl="pallas" is STRICT (advisor r2): no silent XLA fallback — on CPU
    # (pallas unavailable) it must raise, never quietly measure XLA
    with pytest.raises(ValueError, match="pallas"):
        flash_attention(q, k, v, causal=True, impl="pallas")
    # tuned defaults: large blocks (grid overhead dominates small ones)
    assert DEFAULT_BLOCK_Q >= 512 and DEFAULT_BLOCK_K >= 512


def test_resolve_blocks_policy():
    """The block-fitting policy behind impl='auto': every 128-multiple
    length stays on the Pallas path with aligned tiles; only pathological
    lengths fall back."""
    from deepspeed_tpu.ops.flash_attention import _resolve_blocks
    # flagship and long-seq shapes get the full tuned blocks
    assert _resolve_blocks(1024, 1024, 512, 1024) == (True, 512, 1024)
    assert _resolve_blocks(4096, 4096, 512, 1024) == (True, 512, 1024)
    # non-power-of-two 128-multiples fit with smaller ALIGNED divisors
    assert _resolve_blocks(1536, 1536, 512, 1024) == (True, 512, 768)
    usable, bq, bk = _resolve_blocks(1152, 1152, 512, 1024)
    assert usable and bq % 8 == 0 and bk % 128 == 0
    assert 1152 % bq == 0 and 1152 % bk == 0
    # unaligned whole lengths are NOT usable (advisor r2: masked lane
    # reductions on partial tiles are untestable off-TPU) -> XLA path
    usable, bq, bk = _resolve_blocks(33, 33, 512, 1024)
    assert usable is False and (bq, bk) == (33, 33)
    usable, _, _ = _resolve_blocks(1000, 1000, 512, 1024)
    assert usable is False
    # primes have no aligned tiling -> XLA path
    assert _resolve_blocks(1021, 1021, 512, 1024)[0] is False
    # explicit small blocks remain honored (kernel-parity tests rely on it)
    _, bq, bk = _resolve_blocks(128, 128, 64, 64)
    assert (bq, bk) == (64, 64)


def test_force_xla_kernels_override():
    orig = dispatch._force_xla
    try:
        dispatch.force_xla_kernels(True)
        assert not dispatch.pallas_available()
        dispatch.force_xla_kernels(False)
        # on CPU still false (backend gate), but the flag itself is off
        assert not dispatch._force_xla
    finally:
        dispatch._force_xla = orig


def test_throughput_timer_windows_and_short_runs():
    t = ThroughputTimer(batch_size=4, num_workers=2, start_step=0,
                        steps_per_output=3, logging_fn=lambda *a, **k: None)
    for _ in range(7):  # two full windows + one partial
        t.start()
        time.sleep(0.002)
        t.stop(global_step=True)
    # partial window folded in on read; all 7 steps counted
    rate = t.avg_samples_per_sec()
    assert rate > 0 and rate != float("-inf")
    assert t.total_timed_steps == 7
    # units: global samples/sec includes num_workers
    assert rate == pytest.approx(
        4 * 2 * t.total_timed_steps / t.total_elapsed_time, rel=1e-6)


def test_ce_auto_chunk_policy():
    from deepspeed_tpu.ops.fused_cross_entropy import (_CE_CHUNK_ELEM_BUDGET,
                                                       _plan)
    # few tokens -> whole vocab in one chunk
    c, n_chunks, padded = _plan(50304, None, 8184)
    assert n_chunks == 1 and c == 50304
    # moderate token count -> chunk bounded by the transient budget
    n_tok = 10 ** 5
    c, n_chunks, _ = _plan(50304, None, n_tok)
    assert c == _CE_CHUNK_ELEM_BUDGET // n_tok and n_chunks > 1
    # enormous token count -> the 4096 floor wins (matmul width floor)
    c, _, _ = _plan(50304, None, 10 ** 9)
    assert c == 4096
