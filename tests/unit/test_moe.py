"""MoE gating + expert-parallel layer tests (reference: tests/unit/test_moe.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.moe import MoE, MOELayer, TopKGate, top1gating, top2gating
from deepspeed_tpu.moe.experts import ExpertMLP

D = 8
E = 4


class TestTop1Gating:
    def test_shapes(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (16, E))
        l_aux, combine, dispatch, counts, stats = top1gating(
            logits, capacity_factor=2.0, min_capacity=1)
        cap = max(1, int(np.ceil(16 / E * 2.0)))
        assert combine.shape == (16, E, cap)
        assert dispatch.shape == (16, E, cap)
        assert counts.shape == (E,)
        assert np.isfinite(float(l_aux))

    def test_all_tokens_dispatched_when_capacity_ample(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (16, E))
        _, combine, dispatch, _, _ = top1gating(
            logits, capacity_factor=float(E), min_capacity=16)
        # each token occupies exactly one (expert, slot)
        per_token = dispatch.sum(axis=(1, 2))
        np.testing.assert_array_equal(np.asarray(per_token), np.ones(16))

    def test_capacity_drops_tokens(self):
        # all tokens prefer expert 0; capacity 2 keeps only 2
        logits = jnp.stack([jnp.full((16,), 5.0)] + [jnp.zeros(16)] * (E - 1),
                           axis=1)
        _, _, dispatch, _, _ = top1gating(logits, capacity_factor=0.5,
                                          min_capacity=2)
        kept = float(dispatch.sum())
        assert kept == 2.0

    def test_l_aux_uniform_is_one(self):
        # perfectly uniform router → l_aux == 1 (E * E * (1/E²))
        logits = jnp.zeros((E * 8, E))
        l_aux, _, _, _, _ = top1gating(logits, capacity_factor=2.0,
                                       min_capacity=64)
        # argmax breaks ties to expert 0 → ce is one-hot; me uniform
        # so l_aux = E * sum(me*ce) = E * 1/E = 1
        assert float(l_aux) == pytest.approx(1.0, rel=1e-5)

    def test_combine_weights_are_gate_probs(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (8, E))
        gates = jax.nn.softmax(logits, axis=-1)
        _, combine, dispatch, _, _ = top1gating(
            logits, capacity_factor=float(E), min_capacity=8)
        sel = np.asarray(jnp.argmax(logits, axis=-1))
        w = np.asarray(combine.sum(axis=2))  # [S, E]
        for s in range(8):
            assert w[s, sel[s]] == pytest.approx(
                float(gates[s, sel[s]]), rel=1e-5)


class TestTop2Gating:
    def test_shapes_and_two_experts(self):
        logits = jax.random.normal(jax.random.PRNGKey(3), (16, E))
        l_aux, combine, dispatch, counts, stats = top2gating(
            logits, capacity_factor=float(E), min_capacity=32)
        per_token_experts = (dispatch.sum(axis=2) > 0).sum(axis=1)
        np.testing.assert_array_equal(np.asarray(per_token_experts),
                                      np.full(16, 2))
        # combine weights normalized over the two experts
        np.testing.assert_allclose(np.asarray(combine.sum(axis=(1, 2))),
                                   np.ones(16), rtol=1e-5)

    def test_second_differs_from_first(self):
        logits = jax.random.normal(jax.random.PRNGKey(4), (16, E))
        _, _, dispatch, _, _ = top2gating(logits, capacity_factor=float(E),
                                          min_capacity=32)
        experts_hit = np.asarray(dispatch.sum(axis=2))  # [S, E] 0/1
        assert (experts_hit.max(axis=1) <= 1).all()


class TestMOELayer:
    def test_parity_with_per_token_expert(self):
        """k=1, ample capacity: y[token] == gate_prob * expert(token)."""
        gate = TopKGate(D, E, k=1, capacity_factor=float(E), min_capacity=64)
        expert = ExpertMLP(D, 2 * D)
        layer = MOELayer(gate, expert, E)
        rng = jax.random.PRNGKey(5)
        x = jax.random.normal(jax.random.PRNGKey(6), (16, D))
        params = layer.init_params(rng, x)
        y, l_aux, counts = layer.apply(params, x, train=False)
        assert y.shape == x.shape
        assert float(counts.sum()) == 16

        logits = np.asarray(x.astype(jnp.float32) @ params["gate"]["wg"])
        gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        sel = logits.argmax(axis=-1)
        for s in range(16):
            p_e = jax.tree.map(lambda a: a[sel[s]], params["experts"])
            expected = gates[s, sel[s]] * np.asarray(
                expert.apply(p_e, x[s:s + 1]))[0]
            np.testing.assert_allclose(np.asarray(y[s]), expected, rtol=1e-4)

    def test_batched_input_shape(self):
        gate = TopKGate(D, E, k=2, capacity_factor=2.0)
        layer = MOELayer(gate, ExpertMLP(D), E)
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 8, D))
        params = layer.init_params(jax.random.PRNGKey(8), x)
        y, l_aux, _ = layer.apply(params, x, train=False)
        assert y.shape == x.shape


class TestMoEWrapper:
    def test_requires_divisible_experts(self):
        deepspeed_tpu.initialize_mesh(expert=4, data=-1)
        with pytest.raises(ValueError, match="divide"):
            MoE(hidden_size=D, num_experts=6)

    def test_expert_params_sharded(self):
        deepspeed_tpu.initialize_mesh(expert=4, data=-1)
        moe = MoE(hidden_size=D, num_experts=4, k=1)
        assert moe.num_local_experts == 1
        x = jnp.zeros((8, D))
        params = moe.init_params(jax.random.PRNGKey(0), x)
        specs = moe.param_partition_specs(params)
        from jax.sharding import PartitionSpec
        for leaf in jax.tree.leaves(
                specs["experts"],
                is_leaf=lambda s: isinstance(s, PartitionSpec)):
            assert leaf == PartitionSpec("expert")

    def test_training_decreases_loss(self):
        """MoE regression model trained through the engine on an expert=4
        mesh (the reference's SimpleMoEModel scenario, simple_model.py:234)."""
        deepspeed_tpu.initialize_mesh(expert=4, data=-1)
        moe = MoE(hidden_size=D, num_experts=4, k=1, capacity_factor=4.0,
                  min_capacity=64)
        rng = jax.random.PRNGKey(0)
        x0 = jnp.zeros((16, D))
        moe_params = moe.init_params(rng, x0)
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        head = jax.random.normal(k1, (D, D)) * 0.3
        params = {"moe": moe_params, "head": head}

        def model(p, rng, x, y):
            h, l_aux, _ = moe.apply(p["moe"], x, rng=rng)
            pred = h @ p["head"]
            return jnp.mean((pred - y) ** 2) + 0.01 * l_aux

        config = {
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 100,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=config, model_parameters=params)
        rs = np.random.RandomState(0)
        w = rs.randn(D, D).astype(np.float32)
        xb = rs.randn(16, D).astype(np.float32)
        yb = xb @ w
        losses = []
        for i in range(50):
            loss = engine.forward(xb, yb)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.3, losses

    def test_moe_zero_specs_no_duplicate_axis(self):
        """ZeRO partitioning must not reuse the expert axis already claimed
        by stacked expert params."""
        deepspeed_tpu.initialize_mesh(expert=4, data=-1)
        from deepspeed_tpu.parallel.mesh import get_mesh_context
        from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
        moe = MoE(hidden_size=D, num_experts=4, k=1)
        params = moe.init_params(jax.random.PRNGKey(0), jnp.zeros((8, D)))
        specs = moe.param_partition_specs(params)
        zp = ZeroPartitioner(get_mesh_context(), stage=2)
        shardings = zp.grad_shardings(params, specs)
        for s in jax.tree.leaves(shardings):
            axes = []
            for part in s.spec:
                if part is None:
                    continue
                axes.extend(part if isinstance(part, tuple) else (part,))
            assert len(axes) == len(set(axes)), s.spec


class TestScatterDispatch:
    """The scatter dispatcher must route identically to the GShard einsum
    reference (same gating, O(S*k*d) memory instead of O(S*E*C))."""

    @pytest.mark.parametrize("k", [1, 2])
    def test_scatter_matches_einsum(self, k):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate
        from deepspeed_tpu.moe.experts import ExpertMLP

        d, e = 16, 4
        gate = TopKGate(d, e, k=k, capacity_factor=1.0)
        expert = ExpertMLP(d, 32)
        scatter = MOELayer(gate, expert, e, dispatch_impl="scatter")
        einsum = MOELayer(gate, expert, e, dispatch_impl="einsum")
        x = jax.random.normal(jax.random.PRNGKey(0), (64, d), jnp.float32)
        params = scatter.init_params(jax.random.PRNGKey(1), x)
        y_s, aux_s, cnt_s = scatter.apply(params, x)
        y_e, aux_e, cnt_e = einsum.apply(params, x)
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(cnt_s), np.asarray(cnt_e))

    def test_scatter_gradients_match_einsum(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate
        from deepspeed_tpu.moe.experts import ExpertMLP

        d, e = 16, 4
        gate = TopKGate(d, e, k=2, capacity_factor=1.25)
        expert = ExpertMLP(d, 32)
        scatter = MOELayer(gate, expert, e, dispatch_impl="scatter")
        einsum = MOELayer(gate, expert, e, dispatch_impl="einsum")
        x = jax.random.normal(jax.random.PRNGKey(2), (64, d), jnp.float32)
        params = scatter.init_params(jax.random.PRNGKey(3), x)

        def loss(layer):
            def f(p):
                y, aux, _ = layer.apply(p, x)
                return (y.astype(jnp.float32) ** 2).mean() + 0.01 * aux
            return f

        g_s = jax.grad(loss(scatter))(params)
        g_e = jax.grad(loss(einsum))(params)
        for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_e)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6)


class TestManualTP:
    """MoE expert FFNs under MANUAL tensor parallelism (round 5): the
    group pipe body's apply_manual(tp_axis=) must match the replicated
    apply_with_aux exactly — forward AND per-leaf grads — at tp in
    {2, 4}.  Reference slot: the expert FFN position of
    moe/sharded_moe.py:312 under Megatron mp."""

    def _parity(self, tp):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        import deepspeed_tpu
        from deepspeed_tpu.models import GPTMoEConfig
        from deepspeed_tpu.models.gpt_moe_pipe import GPTMoEGroupPipe

        deepspeed_tpu.reset_mesh_context()
        ctx = deepspeed_tpu.initialize_mesh(model=tp, data=-1)
        cfg = GPTMoEConfig(
            vocab_size=64, n_positions=32, hidden_size=32, num_layers=4,
            num_heads=4, bf16=False, num_experts=4, top_k=2,
            capacity_factor=2.0, min_capacity=4, moe_every=2,
            embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
        grp = GPTMoEGroupPipe(cfg)
        assert grp.supports_manual_tp(tp)
        params = grp.init_params(jax.random.PRNGKey(0), None)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32),
                              jnp.float32)

        def loss_ref(p):
            y, aux = grp.apply_with_aux(p, x, rng=None)
            return (y.astype(jnp.float32) ** 2).sum() * 1e-3 + aux

        g_ref = jax.grad(loss_ref)(params)

        pv = grp.tp_manual_views(params)
        specs = grp.tp_manual_view_specs()

        def region(pl, xl):
            def f(pp):
                y, aux = grp.apply_manual(pp, xl, rng=None,
                                          tp_axis="model")
                return (y.astype(jnp.float32) ** 2).sum() * 1e-3 + aux
            return jax.value_and_grad(f)(pl)

        fn = jax.shard_map(region, mesh=ctx.mesh, in_specs=(specs, P()),
                           out_specs=(P(), specs), check_vma=False)
        l_tp, g_tp_v = fn(pv, x)
        g_tp = grp.tp_manual_unview(g_tp_v)
        np.testing.assert_allclose(float(l_tp), float(loss_ref(params)),
                                   rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_tp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=2e-5)
        deepspeed_tpu.reset_mesh_context()

    def test_group_layer_parity_tp2(self):
        self._parity(2)

    def test_group_layer_parity_tp4(self):
        self._parity(4)

    def test_einsum_dispatch_tp_parity(self):
        """The einsum dispatch path's tp_axis branch (fcast on the
        dispatch input only, apply_tp experts) must match the replicated
        einsum layer — fwd and grads."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        import deepspeed_tpu
        from deepspeed_tpu.moe.experts import ExpertMLP
        from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate

        deepspeed_tpu.reset_mesh_context()
        ctx = deepspeed_tpu.initialize_mesh(model=2, data=-1)
        d, e = 16, 4
        gate = TopKGate(d, e, k=2, capacity_factor=2.0)
        layer = MOELayer(gate, ExpertMLP(d, 32), e, dispatch_impl="einsum")
        x = jax.random.normal(jax.random.PRNGKey(2), (64, d), jnp.float32)
        params = layer.init_params(jax.random.PRNGKey(3), x)

        def loss_ref(p):
            y, aux, _ = layer.apply(p, x)
            return (y.astype(jnp.float32) ** 2).mean() + 0.01 * aux

        g_ref = jax.grad(loss_ref)(params)

        specs = {"gate": {"wg": P()},
                 "experts": jax.tree.map(
                     lambda sp: P(None, *sp),
                     ExpertMLP.tp_partition_specs("model"),
                     is_leaf=lambda v: isinstance(v, P))}

        def region(pl, xl):
            def f(pp):
                y, aux, _ = layer.apply(pp, xl, tp_axis="model")
                return (y.astype(jnp.float32) ** 2).mean() + 0.01 * aux
            return jax.value_and_grad(f)(pl)

        fn = jax.shard_map(region, mesh=ctx.mesh, in_specs=(specs, P()),
                           out_specs=(P(), specs), check_vma=False)
        l_tp, g_tp = fn(params, x)
        np.testing.assert_allclose(float(l_tp), float(loss_ref(params)),
                                   rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_tp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=2e-5)
        deepspeed_tpu.reset_mesh_context()


class TestRoutingStats:
    """ISSUE-15 satellite: gating drop accounting — exp_counts and
    RoutingStats reflect POST-capacity-mask reality (a token dropped by
    `locations < capacity` never counts as routed; its demand survives
    in overflow_counts)."""

    def _hot_logits(self, s=16, hot=0):
        # every token prefers expert `hot` decisively
        cols = [jnp.full((s,), 5.0) if e == hot else jnp.zeros(s)
                for e in range(E)]
        return jnp.stack(cols, axis=1)

    def test_top1_post_capacity_counts_and_overflow(self):
        logits = self._hot_logits()
        _, _, dispatch, counts, st = top1gating(
            logits, capacity_factor=0.5, min_capacity=2)  # capacity 2
        # routed == what the dispatch mask actually dispatched
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(dispatch.sum(axis=(0, 2))))
        assert float(counts[0]) == 2.0          # post-capacity, not 16
        assert float(st.expert_counts[0]) == 2.0
        assert float(st.overflow_counts[0]) == 14.0
        assert float(st.tokens) == 16.0
        assert float(st.dropped) == 14.0
        assert float(st.layers) == 1.0

    def test_top1_ample_capacity_zero_drops(self):
        logits = jax.random.normal(jax.random.PRNGKey(11), (16, E))
        _, _, dispatch, counts, st = top1gating(
            logits, capacity_factor=float(E), min_capacity=16)
        assert float(st.dropped) == 0.0
        assert float(st.tokens) == 16.0
        np.testing.assert_array_equal(np.asarray(st.overflow_counts),
                                      np.zeros(E))
        np.testing.assert_array_equal(np.asarray(st.expert_counts),
                                      np.asarray(dispatch.sum(axis=(0, 2))))

    def test_top1_used_token_masks_everything(self):
        logits = self._hot_logits()
        used = jnp.asarray([1.0] * 8 + [0.0] * 8)
        _, _, dispatch, counts, st = top1gating(
            logits, capacity_factor=float(E), min_capacity=16,
            used_token=used)
        # padding tokens neither want nor route nor contribute entropy
        assert float(st.tokens) == 8.0
        assert float(st.dropped) == 0.0
        assert float(st.gate_tokens) == 8.0
        assert float(counts.sum()) == 8.0
        # and with a tight capacity the drop accounting still holds
        _, _, _, counts2, st2 = top1gating(
            logits, capacity_factor=0.5, min_capacity=2, used_token=used)
        assert float(counts2[0]) == 2.0
        assert float(st2.dropped) == 6.0
        assert float(st2.overflow_counts[0]) == 6.0

    def test_top2_doubled_capacity_in_overflow(self):
        s = 16
        logits = self._hot_logits(s)
        (_, cap, _, _, _, counts, st) = (
            __import__("deepspeed_tpu.moe.sharded_moe",
                       fromlist=["top2gating_compact"]).top2gating_compact(
                logits, capacity_factor=1.0, min_capacity=1))
        # top-2 doubles the slot budget: ceil(16/4 * 2 * 1.0) = 8
        assert cap == 8
        # expert 0 wanted by all 16 first choices, keeps the DOUBLED
        # capacity's 8; the second choice (argmax ties -> expert 1)
        # absorbs 16 wants against the same budget
        assert float(st.expert_counts[0]) == 8.0
        assert float(st.overflow_counts[0]) == 8.0
        assert float(st.tokens) == 2.0 * s      # k=2 slots per token
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(st.expert_counts))

    def test_top2_post_capacity_matches_dispatch(self):
        logits = jax.random.normal(jax.random.PRNGKey(12), (32, E))
        _, _, dispatch, counts, st = top2gating(
            logits, capacity_factor=0.25, min_capacity=1)
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(dispatch.sum(axis=(0, 2))))
        np.testing.assert_array_equal(np.asarray(st.expert_counts),
                                      np.asarray(counts))
        assert float(st.tokens) == float(
            st.expert_counts.sum() + st.overflow_counts.sum())

    def test_entropy_normalization_bounds(self):
        # uniform router -> per-token entropy == ln(E); peaked -> ~0
        from deepspeed_tpu.moe.sharded_moe import top1gating_compact
        s = 32
        uniform = top1gating_compact(jnp.zeros((s, E)),
                                     capacity_factor=float(E),
                                     min_capacity=s)[-1]
        assert float(uniform.entropy) == pytest.approx(s * np.log(E),
                                                       rel=1e-5)
        peaked = top1gating_compact(self._hot_logits(s) * 10.0,
                                    capacity_factor=float(E),
                                    min_capacity=s)[-1]
        assert float(peaked.entropy) < 0.05 * s * np.log(E)
        # confidence: uniform top-1 mass is 1/E per token, peaked ~ 1
        assert float(uniform.confidence) == pytest.approx(s / E, rel=1e-5)
        assert float(peaked.confidence) > 0.95 * s

    def test_tap_collects_and_sums_across_layers(self):
        from deepspeed_tpu.moe import (collect_routing_stats,
                                       sum_routing_stats)
        gate = TopKGate(D, E, k=1, capacity_factor=float(E),
                        min_capacity=64)
        layer = MOELayer(gate, ExpertMLP(D), E)
        x = jax.random.normal(jax.random.PRNGKey(13), (16, D))
        params = layer.init_params(jax.random.PRNGKey(14), x)
        with collect_routing_stats() as tap:
            layer.apply(params, x, train=False)
            layer.apply(params, x, train=False)
        assert len(tap) == 2
        total = sum_routing_stats(tap)
        assert float(total.layers) == 2.0
        assert float(total.tokens) == 32.0
        # outside the context, emissions go nowhere
        layer.apply(params, x, train=False)
        assert len(tap) == 2
        assert sum_routing_stats([]) is None


class TestMeshValidationMessage:
    def test_error_names_axis_sizes_and_nearest_valid_counts(self):
        """ISSUE-15 satellite: the num_experts-vs-expert-axis failure
        names both values and the nearest valid expert counts."""
        deepspeed_tpu.initialize_mesh(expert=4, data=-1)
        with pytest.raises(ValueError) as ei:
            MoE(hidden_size=D, num_experts=6)
        msg = str(ei.value)
        assert "num_experts=6" in msg
        assert "expert=4" in msg
        assert "4 or 8" in msg           # nearest multiples of ep_size
        assert "divisor of 6" in msg
        deepspeed_tpu.reset_mesh_context()

    def test_error_below_ep_size_suggests_ep_size(self):
        deepspeed_tpu.initialize_mesh(expert=4, data=-1)
        with pytest.raises(ValueError) as ei:
            MoE(hidden_size=D, num_experts=2)
        # below=0 is not a valid expert count; only ep_size survives
        assert "Nearest valid num_experts: 4;" in str(ei.value)
        deepspeed_tpu.reset_mesh_context()
