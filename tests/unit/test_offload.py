"""ZeRO-Offload tier tests: native host Adam numerics, fused bf16 copy-out,
and the engine's offload_optimizer=cpu path (reference test shapes:
tests/unit/test_zero.py:233 correctness-vs-baseline, test_checkpointing.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.adam.cpu_adam import get_native_lib
from deepspeed_tpu.ops.op_builder import CPUAdamBuilder, op_report


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": rng.randn(33, 17).astype(np.float32),
        "b": rng.randn(17).astype(np.float32),
        "step_id": np.array(3, np.int32),  # non-float pass-through leaf
    }


def _grads(seed=1):
    rng = np.random.RandomState(seed)
    return {
        "w": rng.randn(33, 17).astype(np.float32),
        "b": rng.randn(17).astype(np.float32),
        "step_id": np.zeros((), np.int32),
    }


def test_native_builds():
    assert CPUAdamBuilder().is_compatible()
    lib = get_native_lib()
    assert lib is not None, "host_adam.cpp must compile in this image"
    assert lib.ds_adam_num_threads() >= 1


def test_native_matches_numpy_fallback():
    opt_native = DeepSpeedCPUAdam(_params(), lr=1e-2, weight_decay=0.01)
    opt_np = DeepSpeedCPUAdam(_params(), lr=1e-2, weight_decay=0.01)
    assert opt_native.using_native
    opt_np._lib = None  # force the NumPy path
    for i in range(4):
        opt_native.step(_grads(i))
        opt_np.step(_grads(i))
    for a, b in zip(jax.tree.leaves(opt_native.params),
                    jax.tree.leaves(opt_np.params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("adamw", [True, False])
def test_matches_optax(adamw):
    import optax
    params = {k: v for k, v in _params().items() if k != "step_id"}
    opt = DeepSpeedCPUAdam(params, lr=1e-2, weight_decay=0.01,
                           adamw_mode=adamw)
    if adamw:
        tx = optax.adamw(1e-2, weight_decay=0.01)
    else:
        tx = optax.chain(optax.add_decayed_weights(0.01),
                         optax.adam(1e-2))
    ref = jax.tree.map(jnp.asarray, params)
    state = tx.init(ref)
    for i in range(3):
        g = {k: v for k, v in _grads(i).items() if k != "step_id"}
        opt.step(g)
        updates, state = tx.update(jax.tree.map(jnp.asarray, g), state, ref)
        ref = optax.apply_updates(ref, updates)
    for a, b in zip(jax.tree.leaves(opt.params), jax.tree.leaves(ref)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=2e-5, atol=1e-6)


def test_bf16_emit():
    import ml_dtypes
    opt = DeepSpeedCPUAdam(_params(), lr=1e-2)
    out = opt.step(_grads(), emit_bf16=True)
    assert out["w"].dtype == ml_dtypes.bfloat16
    assert out["step_id"].dtype == np.int32  # pass-through
    np.testing.assert_allclose(
        np.asarray(out["w"], np.float32),
        opt.params["w"].astype(ml_dtypes.bfloat16).astype(np.float32))


def test_op_report():
    rep = op_report()
    assert rep["cpu_adam"]["compatible"]


def _mk_engine(offload: bool, seed=0):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        h = jnp.tanh(x @ params["w1"])
        pred = h @ params["w2"]
        return jnp.mean((pred - y) ** 2)

    rs = np.random.RandomState(seed)
    params = {"w1": rs.randn(8, 16).astype(np.float32) * 0.3,
              "w2": rs.randn(16, 4).astype(np.float32) * 0.3}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 10 ** 9,
    }
    if offload:
        cfg["zero_optimization"] = {
            "stage": 2, "offload_optimizer": {"device": "cpu"}}
    engine, _, _, _ = ds.initialize(model=model, config=cfg,
                                    model_parameters=params, mesh=mesh)
    return engine


def _batch(seed=0):
    rs = np.random.RandomState(seed + 100)
    x = rs.randn(16, 8).astype(np.float32)
    y = rs.randn(16, 4).astype(np.float32)
    return x, y


def test_engine_offload_matches_device_adam():
    e_dev = _mk_engine(offload=False)
    e_off = _mk_engine(offload=True)
    assert e_off._offload_enabled and not e_dev._offload_enabled
    for i in range(4):
        x, y = _batch(i)
        l1 = e_dev.forward(x, y); e_dev.backward(l1); e_dev.step()
        l2 = e_off.forward(x, y); e_off.backward(l2); e_off.step()
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(e_dev.params),
                    jax.tree.leaves(e_off.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_engine_offload_loss_decreases():
    engine = _mk_engine(offload=True)
    losses = []
    for i in range(6):
        x, y = _batch(0)  # same batch -> must strictly improve
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert engine.global_steps == 6


def test_engine_offload_checkpoint_roundtrip(tmp_path):
    engine = _mk_engine(offload=True)
    for i in range(2):
        x, y = _batch(i)
        loss = engine.forward(x, y); engine.backward(loss); engine.step()
    engine.save_checkpoint(str(tmp_path), tag="t1")

    engine2 = _mk_engine(offload=True, seed=7)
    engine2.load_checkpoint(str(tmp_path), tag="t1")
    assert engine2._offload_opt.step_count() == engine._offload_opt.step_count()
    for a, b in zip(jax.tree.leaves(engine.params),
                    jax.tree.leaves(engine2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # training continues from identical state -> identical next step
    x, y = _batch(9)
    l1 = engine.forward(x, y); engine.backward(l1); engine.step()
    l2 = engine2.forward(x, y); engine2.backward(l2); engine2.step()
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


def test_engine_offload_load_module_only(tmp_path):
    """Module-only restore must also refresh the host fp32 master — a step
    after load must start from the restored weights, not the constructor's."""
    engine = _mk_engine(offload=True)
    for i in range(3):
        x, y = _batch(i)
        loss = engine.forward(x, y); engine.backward(loss); engine.step()
    engine.save_checkpoint(str(tmp_path), tag="m1")
    trained = jax.tree.map(np.asarray, engine.params)

    fresh = _mk_engine(offload=True, seed=9)
    fresh.load_checkpoint(str(tmp_path), tag="m1", load_module_only=True)
    master = fresh._offload_opt.master_params
    for a, b in zip(jax.tree.leaves(trained), jax.tree.leaves(master)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)
    # one step must not clobber the restored weights with stale master math
    x, y = _batch(5)
    loss = fresh.forward(x, y); fresh.backward(loss); fresh.step()
    drift = max(float(np.abs(np.asarray(a) - np.asarray(b)).max())
                for a, b in zip(jax.tree.leaves(fresh.params),
                                jax.tree.leaves(trained)))
    assert drift < 0.1, "post-load step diverged from restored weights"


def test_engine_offload_bf16_store():
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    params = {"w": np.random.RandomState(0).randn(8, 4).astype(np.float32)}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg,
                                    model_parameters=params, mesh=mesh)
    assert engine.params["w"].dtype == jnp.bfloat16
    x, y = _batch(0)
    x = x[:, :8]
    losses = []
    for _ in range(5):
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # master stays fp32 on host
    assert engine._offload_opt.master_params["w"].dtype == np.float32
