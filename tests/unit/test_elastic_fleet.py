"""Self-healing elastic fleet (ISSUE 11): mesh-shape-portable
checkpoints, the kill→shrink→resume→regrow supervisor cycle, the
lockstep-signature re-verify on resume, the preemption grace deadline,
and the launcher liveness gate — all CPU-only via the fault-injection
harness (request_stop, rigged slow steps, poisoned heartbeat files).

Acceptance (ISSUE 11): a checkpoint saved at W loads and trains at W-ish
and back at W with loss parity vs uninterrupted training; reshard
round-trips ZeRO-1/2/3 + hpZ bitwise across two (W, W') pairs; a
topology-ambiguous or signature-mismatched load fails loudly.  Fast
lane.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.resilience import supervisor as sup
from deepspeed_tpu.runtime.resilience.preemption import TrainingInterrupted
from deepspeed_tpu.runtime.resilience.reshard import (LockstepResumeError,
                                                      ReshardError,
                                                      SIGNATURE_KEY,
                                                      TOPOLOGY_KEY)
from tests.unit.simple_model import (base_engine_config, random_dataset,
                                     simple_model_apply, simple_model_params)

HIDDEN = 16
GLOBAL_BATCH = 8
TOTAL_STEPS = 9


def _mesh(n=None, **axes):
    ds.reset_mesh_context()
    devices = jax.devices() if n is None else jax.devices()[:n]
    return ds.initialize_mesh(**(axes or {"data": -1}), devices=devices)


def _batches(nsteps, seed=12):
    """One fixed global batch per step — every world size consumes the
    IDENTICAL sample sequence, so loss parity across reshapes is exact
    up to reduction order."""
    data = random_dataset(nsteps * GLOBAL_BATCH, HIDDEN, seed=seed)
    out = []
    for i in range(nsteps):
        chunk = data[i * GLOBAL_BATCH:(i + 1) * GLOBAL_BATCH]
        out.append((np.stack([x for x, _ in chunk]),
                    np.stack([y for _, y in chunk])))
    return out


def make_engine(n_devices, micro_batch, gas=1, stage=2, res_extra=None,
                **overrides):
    mesh = _mesh(n_devices)
    cfg = base_engine_config(
        micro_batch=micro_batch, gas=gas,
        **{"zero_optimization": {"stage": stage},
           "checkpoint": {"sharded": True},
           "resilience": dict({"enabled": True}, **(res_extra or {})),
           **overrides})
    engine, _, _, _ = ds.initialize(
        model=simple_model_apply, config=cfg,
        model_parameters=simple_model_params(HIDDEN), mesh=mesh)
    return engine


def np_tree(tree):
    return jax.tree.map(np.asarray, tree)


def assert_tree_equal(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


# --------------------------------------------------------------------- #
# reshard-on-load round-trips: ZeRO 1/2/3 across two (W, W') pairs
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("stage", [1, 2, 3])
@pytest.mark.parametrize("w_pair", [(8, 4), (4, 2)])
def test_reshard_roundtrip_bitwise(stage, w_pair, tmp_path):
    """Save at W, load at W' (bitwise), save from W', load back at W
    (bitwise) — params AND optimizer state, per zero stage."""
    w, w_prime = w_pair
    batches = _batches(2)
    e = make_engine(w, GLOBAL_BATCH // w, stage=stage)
    for x, y in batches:
        e.backward(e.forward(x, y))
        e.step()
    e.save_checkpoint(str(tmp_path), tag="t0")
    ref_p, ref_o = np_tree(e.params), np_tree(e.opt_state)

    e2 = make_engine(w_prime, GLOBAL_BATCH // w_prime, stage=stage)
    e2.load_checkpoint(str(tmp_path), tag="t0")
    assert e2.global_steps == 2
    assert_tree_equal(ref_p, np_tree(e2.params))
    assert_tree_equal(ref_o, np_tree(e2.opt_state))
    e2.save_checkpoint(str(tmp_path), tag="t1")

    e3 = make_engine(w, GLOBAL_BATCH // w, stage=stage)
    e3.load_checkpoint(str(tmp_path), tag="t1")
    assert_tree_equal(ref_p, np_tree(e3.params))
    assert_tree_equal(ref_o, np_tree(e3.opt_state))
    ds.reset_mesh_context()


def _gpt2_hpz_engine(data, expert, tmp_path=None):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=data, expert=expert,
                              devices=jax.devices()[:data * expert])
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0,
                     hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0,
                    "stage3_max_live_parameters": 1,
                    "stage3_prefetch_bucket_size": 0,
                    "low_bandwidth": {"hpz_group_size": 2}},
                "checkpoint": {"sharded": True},
                "resilience": {"enabled": True},
                "steps_per_print": 10 ** 9},
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    return engine


@pytest.mark.parametrize("shrink", [(2, 1), (1, 2)],
                         ids=["data2x2_to_1x2", "back_1x2_to_2x2"])
def test_reshard_roundtrip_hpz(shrink, tmp_path):
    """hpZ (secondary partition on the inner expert axis) survives a
    data-axis resize bitwise in BOTH directions: (data=2,expert=2) <->
    (data=1,expert=2) — the hpz group stays a valid inner suffix on
    both meshes, which is exactly the Frontier low-bandwidth scenario's
    surviving-worker constraint."""
    d_save, d_load = shrink
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (4, 16),
                                        0, 64), np.int32)
    e = _gpt2_hpz_engine(d_save, 2)
    e.backward(e.forward(ids))
    e.step()
    e.save_checkpoint(str(tmp_path), tag="h0")
    ref_p, ref_o = np_tree(e.params), np_tree(e.opt_state)

    e2 = _gpt2_hpz_engine(d_load, 2)
    e2.load_checkpoint(str(tmp_path), tag="h0")
    assert_tree_equal(ref_p, np_tree(e2.params))
    assert_tree_equal(ref_o, np_tree(e2.opt_state))
    ds.reset_mesh_context()


# --------------------------------------------------------------------- #
# fail-loudly: topology ambiguity + non-ZeRO-axis resize + lockstep drift
# --------------------------------------------------------------------- #
def test_topology_ambiguous_load_fails_loudly(tmp_path):
    """A tag with NO recorded topology (pre-portability checkpoint)
    loading across a world-size change must refuse, naming the tag —
    the saved partition layout is ambiguous."""
    e = make_engine(4, 2)
    x, y = _batches(1)[0]
    e.backward(e.forward(x, y))
    e.step()
    e.save_checkpoint(str(tmp_path), tag="legacy")
    # simulate a pre-PR checkpoint: strip the topology record (and
    # re-manifest so the CRC verify still passes)
    meta_path = tmp_path / "legacy" / "ds_meta.json"
    meta = json.loads(meta_path.read_text())
    del meta["client_state"][TOPOLOGY_KEY]
    meta["client_state"].pop(SIGNATURE_KEY, None)
    meta_path.write_text(json.dumps(meta))
    from deepspeed_tpu.runtime.resilience.atomic import write_manifest
    write_manifest(str(tmp_path / "legacy"))

    e2 = make_engine(2, 4)
    with pytest.raises(ReshardError) as ei:
        e2.load_checkpoint(str(tmp_path), tag="legacy")
    msg = str(ei.value)
    assert "'legacy'" in msg and "no partition_topology" in msg
    assert "saved topology" in msg and "requested topology" in msg
    # same world size stays loadable (nothing ambiguous to resolve)
    e3 = make_engine(4, 2)
    e3.load_checkpoint(str(tmp_path), tag="legacy")
    ds.reset_mesh_context()


def test_non_zero_axis_resize_rejected(tmp_path):
    """model-parallel resize is NOT a ZeRO reshard: the topology check
    names the offending axis and both topologies."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=2, model=2, devices=jax.devices()[:4])
    cfg = base_engine_config(
        micro_batch=4, gas=1,
        **{"checkpoint": {"sharded": True}, "resilience": {"enabled": True}})
    e, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                               model_parameters=simple_model_params(HIDDEN),
                               mesh=mesh)
    x, y = _batches(1)[0]
    e.backward(e.forward(x, y))
    e.step()
    e.save_checkpoint(str(tmp_path), tag="mp2")

    e2 = make_engine(4, 2)  # model=1 now
    with pytest.raises(ReshardError) as ei:
        e2.load_checkpoint(str(tmp_path), tag="mp2")
    msg = str(ei.value)
    assert "'model'" in msg and "2 -> 1" in msg and "'mp2'" in msg
    ds.reset_mesh_context()


def test_consolidated_layout_portable_across_model_resize(tmp_path):
    """The consolidated (.npz) layout stores full unsharded leaves —
    mesh-independent, so even a model-parallel resize loads (the
    non-ZeRO-axis rejection applies to the SHARDED layout only)."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=2, model=2, devices=jax.devices()[:4])
    cfg = base_engine_config(
        micro_batch=4, gas=1,
        **{"checkpoint": {"sharded": False},
           "resilience": {"enabled": True}})
    e, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                               model_parameters=simple_model_params(HIDDEN),
                               mesh=mesh)
    x, y = _batches(1)[0]
    e.backward(e.forward(x, y))
    e.step()
    e.save_checkpoint(str(tmp_path), tag="mp2c")
    ref = np_tree(e.params)

    ds.reset_mesh_context()
    mesh2 = ds.initialize_mesh(data=4, devices=jax.devices()[:4])
    cfg2 = base_engine_config(
        micro_batch=2, gas=1,
        **{"checkpoint": {"sharded": False},
           "resilience": {"enabled": True}})
    e2, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg2,
                                model_parameters=simple_model_params(HIDDEN),
                                mesh=mesh2)
    e2.load_checkpoint(str(tmp_path), tag="mp2c")  # model 2 -> 1: OK
    assert_tree_equal(ref, np_tree(e2.params))
    ds.reset_mesh_context()


_Z3_STREAM = {"stage": 3, "stage3_param_persistence_threshold": 0,
              "stage3_max_live_parameters": 1,
              "stage3_prefetch_bucket_size": 0}


def _gpt2_stream_engine(zero_cfg, n=4):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1, devices=jax.devices()[:n])
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0,
                     hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": zero_cfg,
                "checkpoint": {"sharded": True},
                "resilience": {"enabled": True},
                "steps_per_print": 10 ** 9},
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(7))
    return engine


def test_lockstep_reverify_rejects_config_drift_on_resume(tmp_path):
    """Same topology, drifted config (qwZ flipped on): the resumed
    program traces a DIFFERENT collective schedule — the re-verify
    aborts before the first post-resume step, naming tag + signatures.
    The identical config resumes cleanly."""
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (4, 16),
                                        0, 64), np.int32)
    e = _gpt2_stream_engine(dict(_Z3_STREAM))
    e.backward(e.forward(ids))
    e.step()
    e.save_checkpoint(str(tmp_path), tag="s0")

    drifted = _gpt2_stream_engine(
        dict(_Z3_STREAM, low_bandwidth={"qwz_bits": 8}))
    with pytest.raises(LockstepResumeError) as ei:
        drifted.load_checkpoint(str(tmp_path), tag="s0")
    msg = str(ei.value)
    assert "'s0'" in msg and "saved signature" in msg
    assert "unchanged topology" in msg

    same = _gpt2_stream_engine(dict(_Z3_STREAM))
    same.load_checkpoint(str(tmp_path), tag="s0")
    assert same.global_steps == 1
    ds.reset_mesh_context()


# --------------------------------------------------------------------- #
# preemption grace deadline (satellite): rigged slow step
# --------------------------------------------------------------------- #
def _grace_engine(tmp_path, grace_s):
    return make_engine(
        4, 2, res_extra={
            "atomic_checkpoints": True,
            "preemption": {"enabled": True, "reraise": False,
                           "grace_s": grace_s,
                           "save_dir": str(tmp_path)}})


def test_grace_deadline_forces_last_completed_step_save(tmp_path):
    """Signal lands, the step wedges (rigged: the loop simply never
    reaches another boundary): after grace_s the timer thread saves the
    LAST COMPLETED step under the _forced tag; the eventual boundary
    finalizes with that tag instead of double-saving."""
    e = _grace_engine(tmp_path, grace_s=0.15)
    batches = _batches(3)
    for x, y in batches[:2]:
        e.backward(e.forward(x, y))
        e.step()
    e._preemption.request_stop()
    deadline = time.monotonic() + 5.0
    while (e._preemption.forced_tag is None
           and time.monotonic() < deadline):
        time.sleep(0.05)  # the rigged slow step: no boundary reached
    assert e._preemption.deadline_fired
    forced = e._preemption.forced_tag
    assert forced == "emergency_step2_forced"
    assert os.path.isdir(tmp_path / forced)
    # manifest is intact — the forced save used the atomic protocol
    from deepspeed_tpu.runtime.resilience.atomic import verify_manifest
    assert verify_manifest(str(tmp_path / forced)) == []

    # the loop limps to one more boundary: finalize carries the forced
    # tag; the normal per-boundary tag is NOT saved again
    x, y = batches[2]
    with pytest.raises(TrainingInterrupted) as ei:
        e.backward(e.forward(x, y))
        e.step()
    assert ei.value.emergency_tag == forced
    assert not os.path.isdir(tmp_path / "emergency_step3")

    # the forced tag resumes: last completed step was 2
    e2 = make_engine(4, 2)
    e2.load_checkpoint(str(tmp_path), tag=forced)
    assert e2.global_steps == 2
    ds.reset_mesh_context()


def test_grace_deadline_cancelled_at_boundary(tmp_path):
    """A healthy loop (boundary inside the grace window) never sees the
    forced path: normal emergency tag, timer disarmed."""
    e = _grace_engine(tmp_path, grace_s=30.0)
    batches = _batches(2)
    x, y = batches[0]
    e.backward(e.forward(x, y))
    e.step()
    e._preemption.request_stop()
    x, y = batches[1]
    with pytest.raises(TrainingInterrupted) as ei:
        e.backward(e.forward(x, y))
        e.step()
    assert not e._preemption.deadline_fired
    assert e._preemption._deadline_timer is None  # disarmed, not leaked
    assert ei.value.emergency_tag == "emergency_step2"
    assert os.path.isdir(tmp_path / "emergency_step2")
    ds.reset_mesh_context()


def test_boundary_waits_for_inflight_forced_save():
    """Race regression: the boundary reached WHILE the deadline callback
    is still saving must wait for its forced_tag instead of reading None
    and double-saving the same step."""
    import threading

    from deepspeed_tpu.runtime.resilience.preemption import PreemptionHandler
    started, release = threading.Event(), threading.Event()

    def on_deadline():
        started.set()
        release.wait(5)
        return "tag_forced"

    h = PreemptionHandler(grace_s=0.01, on_deadline=on_deadline)
    h.request_stop()
    assert started.wait(5)           # timer fired, callback mid-save
    boundary = threading.Thread(target=h.boundary_reached)
    boundary.start()
    time.sleep(0.1)
    assert boundary.is_alive()       # boundary waits out the callback
    assert h.forced_tag is None
    release.set()
    boundary.join(5)
    assert not boundary.is_alive()
    assert h.forced_tag == "tag_forced"


# --------------------------------------------------------------------- #
# supervisor policy + planning units
# --------------------------------------------------------------------- #
def test_policy_straggler_needs_consecutive_strikes():
    pol = sup.SupervisorPolicy(min_world_size=1, straggler_strikes=3)
    ev = {"event": "straggler", "process_index": 2, "lane": "compute"}
    pol.observe_window([ev])
    pol.observe_window([ev])
    assert pol.decide(4).action == "continue"
    # a clean window resets the streak — one-off slowness never evicts
    pol.observe_window([])
    pol.observe_window([ev])
    pol.observe_window([ev])
    assert pol.decide(4).action == "continue"
    pol.observe_window([ev])
    d = pol.decide(4)
    assert d.action == "reshape" and d.drop == (2,)
    assert "straggler" in d.reason and 2 in pol.evicted


def test_policy_stale_heartbeat_and_floor():
    pol = sup.SupervisorPolicy(min_world_size=2)
    pol.observe_stale_heartbeats([
        {"process_index": 0, "stale": False},
        {"process_index": 3, "stale": True}])
    d = pol.decide(4)
    assert d.action == "reshape" and d.drop == (3,)
    # dropping below the floor aborts instead of thrashing
    pol2 = sup.SupervisorPolicy(min_world_size=2)
    pol2.observe_dead(0)
    assert pol2.decide(2).action == "abort"


def test_policy_divergence_restarts_same_workers():
    pol = sup.SupervisorPolicy()
    pol.observe_window([{"event": "divergence", "detail": "loss spread"}])
    d = pol.decide(4)
    assert d.action == "reshape" and d.drop == ()
    assert "divergence" in d.reason


def test_plan_resume_fixed_global_batch():
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1}
    plan = sup.plan_resume(cfg, capacity=3, train_batch_size=8)
    assert (plan.world_size, plan.micro_batch,
            plan.gradient_accumulation_steps) == (2, 4, 1)
    # gas preserved when it still divides
    plan = sup.plan_resume({"gradient_accumulation_steps": 2}, capacity=4,
                           train_batch_size=16)
    assert (plan.world_size, plan.micro_batch,
            plan.gradient_accumulation_steps) == (4, 2, 2)
    with pytest.raises(sup.FleetAbort):
        sup.plan_resume(cfg, capacity=0, train_batch_size=8)


def test_plan_resume_elastic_block():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 32,
                          "micro_batch_sizes": [1, 2, 4],
                          "min_gpus": 1, "max_gpus": 8, "version": 0.1}}
    plan = sup.plan_resume(cfg, capacity=5)
    assert plan.world_size == 4
    assert (plan.micro_batch * plan.gradient_accumulation_steps
            * plan.world_size == plan.train_batch_size)
    # apply_to_config leaves elastic configs to the engine's own solve
    assert "train_batch_size" not in plan.apply_to_config(cfg)
    non_elastic = sup.plan_resume({}, capacity=4, train_batch_size=8)
    assert non_elastic.apply_to_config({})["train_batch_size"] == 8


# --------------------------------------------------------------------- #
# THE acceptance sweep: kill → shrink(W→W') → resume → regrow(→W),
# loss parity vs an uninterrupted run
# --------------------------------------------------------------------- #
def test_kill_shrink_resume_regrow_loss_parity(tmp_path):
    batches = _batches(TOTAL_STEPS)

    # ---- uninterrupted baseline at W=4 --------------------------- #
    base = make_engine(4, 2)
    base_losses = []
    for x, y in batches:
        loss = base.forward(x, y)
        base.backward(loss)
        base.step()
        base_losses.append(float(loss))
    base_params = np_tree(base.params)

    # ---- elastic run: cycle 0 killed at step 5, shrink to W=2,
    #      capacity returns, regrow to W=4 ------------------------- #
    save_dir = str(tmp_path / "elastic")
    # worker 3 dies in cycle 0; a REPLACEMENT (id 4) joins by cycle 2 —
    # regrow is new capacity appearing in discovery, not the dead worker
    # un-dying (its eviction is permanent for this supervisor)
    schedule = [[0, 1, 2, 3], [0, 1, 2], [0, 1, 2, 4]]
    calls = {"n": 0}

    def discover():
        i = min(calls["n"], len(schedule) - 1)
        calls["n"] += 1
        return schedule[i]

    elastic_losses = {}

    def launch(plan):
        cfg = base_engine_config(
            micro_batch=plan.micro_batch,
            gas=plan.gradient_accumulation_steps,
            **{"zero_optimization": {"stage": 2},
               "checkpoint": {"sharded": True},
               "resilience": {
                   "enabled": True,
                   "preemption": {"enabled": True, "reraise": False,
                                  "save_dir": save_dir}}})
        mesh = _mesh(plan.world_size)
        engine, _, _, _ = ds.initialize(
            model=simple_model_apply, config=cfg,
            model_parameters=simple_model_params(HIDDEN), mesh=mesh)
        try:
            if plan.load_dir is not None:
                engine.load_checkpoint(plan.load_dir, tag=plan.tag)
            start = engine.global_steps
            while engine.global_steps < TOTAL_STEPS:
                i = engine.global_steps
                x, y = batches[i]
                loss = engine.forward(x, y)
                engine.backward(loss)
                # recorded pre-step: the kill's TrainingInterrupted
                # fires INSIDE step()'s boundary check, after the
                # update applied — the step is completed, not lost
                elastic_losses[i] = float(loss)
                engine.step()
                if plan.cycle == 0 and engine.global_steps == 4:
                    # the kill: worker 3 preempted mid-run — emergency
                    # save fires at the NEXT step boundary
                    engine._preemption.request_stop()
                if (plan.cycle == 1
                        and engine.global_steps - start >= 2):
                    # replacement capacity arrived: checkpoint and hand
                    # control back so the supervisor can regrow
                    engine.save_checkpoint(save_dir)
                    return sup.CycleResult(
                        "interrupted",
                        steps_done=engine.global_steps - start)
            return sup.CycleResult(
                "completed", steps_done=engine.global_steps - start)
        except TrainingInterrupted as ti:
            return sup.CycleResult(
                "interrupted", emergency_tag=ti.emergency_tag,
                dead_workers=(3,),
                steps_done=engine.global_steps)
        finally:
            if engine._preemption is not None:
                engine._preemption.uninstall()

    fleet = sup.FleetSupervisor(
        {"train_micro_batch_size_per_gpu": 2}, save_dir,
        discover_fn=discover, launch_fn=launch,
        policy=sup.SupervisorPolicy(min_world_size=1),
        max_cycles=5, train_batch_size=GLOBAL_BATCH)
    summary = fleet.run()

    assert summary["status"] == "completed"
    assert summary["world_sizes"] == [4, 2, 4]  # kill→shrink→regrow
    assert 3 in summary["evicted"]
    # the shrink cycle resumed from the emergency tag the kill produced
    assert fleet.history[1][0].tag == "emergency_step5"
    # every step of the elastic run matches the uninterrupted baseline
    assert sorted(elastic_losses) == list(range(TOTAL_STEPS))
    for i, ref in enumerate(base_losses):
        assert elastic_losses[i] == pytest.approx(ref, rel=1e-4), (
            i, elastic_losses[i], ref)
    # final params parity: reload the last checkpointed state at W=4
    ds.reset_mesh_context()
    verify = make_engine(4, 2)
    # the completed cycle never saved after its last step — compare the
    # baseline against a fresh W=4 resume of `latest` plus a replay of
    # the remaining steps
    verify.load_checkpoint(save_dir)
    for i in range(verify.global_steps, TOTAL_STEPS):
        x, y = batches[i]
        verify.backward(verify.forward(x, y))
        verify.step()
    for a, b in zip(jax.tree.leaves(base_params),
                    jax.tree.leaves(np_tree(verify.params))):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
    ds.reset_mesh_context()


def test_supervisor_abort_on_capacity_floor():
    def discover():
        return [0]

    def launch(plan):
        return sup.CycleResult("failed", dead_workers=(0,))

    fleet = sup.FleetSupervisor(
        {}, "/tmp/nowhere", discover_fn=discover, launch_fn=launch,
        policy=sup.SupervisorPolicy(min_world_size=1),
        max_cycles=3, train_batch_size=8)
    with pytest.raises(sup.FleetAbort):
        fleet.run()


# --------------------------------------------------------------------- #
# launcher liveness gate (satellite): --watch_fail_after
# --------------------------------------------------------------------- #
def test_watch_fail_after_exits_nonzero_naming_worker(tmp_path, caplog):
    from deepspeed_tpu.launcher.runner import (WATCH_FAIL_RC,
                                               launch_and_collect)
    from deepspeed_tpu.monitor.heartbeat import (HEARTBEAT_DIR,
                                                 HeartbeatWriter,
                                                 heartbeat_path)
    from deepspeed_tpu.utils.logging import logger as ds_logger
    hb_dir = os.path.join(str(tmp_path), HEARTBEAT_DIR)
    HeartbeatWriter(hb_dir, 0, 2, host="h0").beat(step=5)
    HeartbeatWriter(hb_dir, 1, 2, host="h1").beat(step=5)
    # worker 1 went dark long ago (poisoned heartbeat)
    path = heartbeat_path(hb_dir, 1)
    hb = json.loads(open(path).read())
    hb["time"] -= 9999.0
    hb["interval_s"] = 1.0
    with open(path, "w") as f:
        json.dump(hb, f)

    ds_logger.addHandler(caplog.handler)
    try:
        outcome = launch_and_collect(
            [[sys.executable, "-c", "import time; time.sleep(60)"],
             [sys.executable, "-c", "import time; time.sleep(60)"]],
            ["hostA", "hostB"], watch_dir=str(tmp_path),
            watch_interval=0.2, watch_stale_s=5.0, watch_fail_after=2)
    finally:
        ds_logger.removeHandler(caplog.handler)
    assert outcome.rc == WATCH_FAIL_RC
    assert outcome.stale == [(1, "hostB")]
    assert "hostB" in outcome.bad_hosts
    # the gate's own SIGTERM killed the HEALTHY worker too — it must not
    # count as failed, or --elastic would drop the whole fleet instead
    # of only the stale host
    assert "hostA" not in outcome.bad_hosts
    messages = " ".join(r.getMessage() for r in caplog.records)
    assert "'hostB'" in messages and "stale" in messages
