"""Worker script for the 2-process jax.distributed checkpoint test.

Each process owns 4 virtual CPU devices (global mesh = 8); the training
batch is fed per-process (make_array_from_process_local_data), the engine
saves the sharded per-process checkpoint layout, and process 0's shard
files must NOT contain the other process's slices.

Usage: python distributed_ckpt_worker.py <coord> <num_procs> <proc_id> <dir>
"""

import json
import os
import sys


def main():
    coord, nprocs, pid, workdir = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == nprocs * 4

    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    mesh = ds.initialize_mesh(data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    conf = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(1))

    # global batch 8, each process feeds ITS half (rows 4p..4p+4)
    full = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (8, 16),
                                         0, 64), np.int32)
    local = full[pid * 4:(pid + 1) * 4]
    losses = []
    for _ in range(2):
        loss = engine.forward(local)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))

    # tag agreement check runs across both processes
    engine.save_checkpoint(workdir, tag="tag0")
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("ckpt_saved")

    # every process restores; trajectory continues
    engine.load_checkpoint(workdir, tag="tag0")
    loss = engine.forward(local)
    engine.backward(loss)
    engine.step()

    out = {"pid": pid, "losses": losses, "final_loss": float(loss),
           "shard_file": f"model_shards_p{pid:05d}.npz"}
    with open(os.path.join(workdir, f"result_p{pid}.json"), "w") as f:
        json.dump(out, f)
    multihost_utils.sync_global_devices("done")


if __name__ == "__main__":
    main()
