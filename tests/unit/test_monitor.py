"""Runtime telemetry subsystem (deepspeed_tpu/monitor/, docs/telemetry.md).

Covers the ISSUE-9 acceptance surface: writer backends round-trip
(JSONL/CSV; trace-event JSON validates against the Chrome schema),
reconciliation math on rigged predicted/measured pairs, the host-sync
audit regression (monitor-on adds zero hot-loop host callbacks and does
not change the program shape), a telemetry-overhead bound, the swap-tier
integration (ZeRO-Infinity records + swap-I/O trace spans), and the
satellite fixes (tensorboard fallback chain, timer exception narrowing,
fused wall_clock_breakdown window timer).
"""

import csv
import json
import os
import sys
import time

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.config import DeepSpeedConfigError, MonitorConfig
from deepspeed_tpu.monitor import (
    Bands, FLAG_HBM_ABOVE_BAND, FLAG_HBM_BELOW_BAND, FLAG_MODEL_VIOLATION,
    FLAG_STEP_TIME_ABOVE_BAND, FLAG_SWAP_BELOW_CEILING, KIND_RECONCILE,
    KIND_STEP, MetricsStream, STEP_RECORD_FIELDS, ScalarJsonlWriter,
    TraceEventBuffer, attribute_gap, reconcile_window,
    validate_trace_events)
from deepspeed_tpu.monitor import record as R
from deepspeed_tpu.monitor.reconcile import (ATTR_COMM_EXPOSED,
                                             ATTR_COMPUTE, ATTR_IO,
                                             ATTR_SWAP)


# --------------------------------------------------------------------- #
# engine fixture (CPU gpt2 — the acceptance config)
# --------------------------------------------------------------------- #
def _engine(tmp_path, monitor=None, num_layers=2, gas=1, fused=False,
            extra=None):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=num_layers, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     hidden_dropout=0.0)
    model = GPT2Model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "fused_step": {"enabled": fused},
        "steps_per_print": 10 ** 9,
    }
    if monitor is not None:
        monitor = dict(monitor)
        monitor.setdefault("enabled", True)
        monitor.setdefault("output_path", str(tmp_path))
        config["monitor"] = monitor
    config.update(extra or {})
    engine, _, _, _ = ds.initialize(
        model=model, config=config,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    return engine


def _run_steps(engine, n, seq=16, batch=2, gas=1):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(batch, seq)).astype(np.int32)
    for _ in range(n):
        for _ in range(gas):
            loss = engine.forward(ids)
            engine.backward(loss)
            engine.step()
    return loss


# --------------------------------------------------------------------- #
# writer backends round-trip
# --------------------------------------------------------------------- #
def test_jsonl_records_roundtrip(tmp_path):
    """Acceptance: per-step JSONL records carry measured wall time,
    memory high-water, and counters on the CPU gpt2 config."""
    engine = _engine(tmp_path, monitor={"writers": ["jsonl"],
                                        "write_interval": 2})
    _run_steps(engine, 5)
    engine.monitor.close()
    path = engine.monitor.jsonl_path
    recs = [json.loads(line) for line in open(path)]
    steps = [r for r in recs if r.get(R.F_KIND) == KIND_STEP]
    assert [r[R.F_STEP] for r in steps] == [1, 2, 3, 4, 5]
    for rec in steps:
        assert rec[R.F_LOSS] is not None and np.isfinite(rec[R.F_LOSS])
        assert rec[R.F_MEM_PEAK_BYTES] and rec[R.F_MEM_PEAK_BYTES] > 0
        assert rec[R.F_MEM_SOURCE] in ("device", "host_rss")
        assert rec[R.F_SKIPPED_STEPS] == 0
        assert rec[R.F_DISPATCHES_PER_STEP] == 2
        assert rec[R.F_LR] == pytest.approx(1e-3)
    # wall time exists from step 2 on (step 1's clock armed at forward)
    assert all(r[R.F_WALL_TIME_S] is not None and r[R.F_WALL_TIME_S] > 0
               for r in steps)
    assert all(r[R.F_TOKENS_PER_SEC] > 0 for r in steps)
    # reconciliation records ride the same stream, one per flush window
    recons = [r for r in recs if r.get(R.F_KIND) == KIND_RECONCILE]
    assert len(recons) == 3  # windows [1-2], [3-4], [5]
    assert recons[0][R.R_WINDOW_START] == 1
    assert recons[-1][R.R_WINDOW_END] == 5


def test_csv_roundtrip_matches_schema(tmp_path):
    engine = _engine(tmp_path, monitor={"writers": ["jsonl", "csv"],
                                        "write_interval": 3})
    _run_steps(engine, 4)
    engine.monitor.close()
    with open(engine.monitor.csv_path, newline="") as f:
        rows = list(csv.reader(f))
    assert tuple(rows[0]) == STEP_RECORD_FIELDS
    body = rows[1:]
    assert len(body) == 4  # step records only; reconcile stays in JSONL
    step_col = STEP_RECORD_FIELDS.index(R.F_STEP)
    assert [int(r[step_col]) for r in body] == [1, 2, 3, 4]
    loss_col = STEP_RECORD_FIELDS.index(R.F_LOSS)
    assert all(np.isfinite(float(r[loss_col])) for r in body)


def test_monitor_unknown_writer_rejected():
    with pytest.raises(DeepSpeedConfigError, match="unknown backend"):
        MonitorConfig.from_dict({"enabled": True, "writers": ["sqlite"]})
    with pytest.raises(DeepSpeedConfigError, match="list of backend"):
        MonitorConfig.from_dict({"enabled": True, "writers": None})


def test_monitor_band_validation():
    with pytest.raises(DeepSpeedConfigError, match="step_time_ratio_max"):
        MonitorConfig.from_dict({"step_time_ratio_max": 0.5})
    with pytest.raises(DeepSpeedConfigError, match="write_interval"):
        MonitorConfig.from_dict({"write_interval": 0})


# --------------------------------------------------------------------- #
# trace export: Chrome/Perfetto trace-event schema
# --------------------------------------------------------------------- #
def test_trace_export_validates_and_has_step_phases(tmp_path):
    engine = _engine(tmp_path, monitor={"writers": ["jsonl"],
                                        "trace": True})
    _run_steps(engine, 3)
    engine.monitor.close()
    payload = json.load(open(engine.monitor.trace_path))
    assert validate_trace_events(payload) == []
    events = payload["traceEvents"]
    names = {e["name"] for e in events}
    # modular path: grad/accumulate/apply dispatch spans per step
    assert "grad_dispatch" in names
    assert "apply_dispatch" in names
    x_events = [e for e in events if e["ph"] == "X"]
    assert all(e["dur"] >= 0 for e in x_events)
    steps = {e.get("args", {}).get("step") for e in x_events}
    assert {1, 2, 3} <= steps
    # flush boundaries appear as instants on the monitor lane
    assert any(e["ph"] == "i" and e["name"] == "flush" for e in events)


def test_trace_step_bound_saturates(tmp_path):
    engine = _engine(tmp_path, monitor={"writers": ["jsonl"],
                                        "trace": True, "trace_steps": 2})
    _run_steps(engine, 4)
    engine.monitor.close()
    payload = json.load(open(engine.monitor.trace_path))
    assert payload["otherData"]["steps_traced"] == 2
    assert payload["otherData"]["truncated_at_max_steps"] is True


def test_trace_buffer_schema_validator_catches_garbage():
    assert validate_trace_events({"traceEvents": "nope"})
    assert validate_trace_events(
        {"traceEvents": [{"name": "x", "ph": "X", "pid": 1, "tid": 1,
                          "ts": 0.0}]})  # X without dur
    buf = TraceEventBuffer()
    buf.add_span("ok", 1.0, 2.0)
    assert validate_trace_events(buf.to_json()) == []


# --------------------------------------------------------------------- #
# reconciliation math on rigged predicted/measured pairs
# --------------------------------------------------------------------- #
def _pred(lb=0.010, compute=0.010, memory=0.002, hidden=0.001,
          exposed=0.0, hbm=None):
    return {"predicted_step_time_lb_s": lb,
            "lanes": {"compute": compute, "memory": memory,
                      "hidden_comm": hidden, "exposed_comm": exposed},
            "peak_hbm_bytes": hbm}


def test_reconcile_within_band_no_flags():
    rec = reconcile_window({"step_time_s": 0.03}, _pred(), Bands())
    assert rec[R.R_STEP_RATIO] == pytest.approx(3.0)
    assert rec[R.R_ATTRIBUTION] == ATTR_COMPUTE
    assert rec[R.R_FLAGS] == []


def test_reconcile_step_time_above_band_flags_with_attribution():
    rec = reconcile_window(
        {"step_time_s": 0.5},
        _pred(lb=0.01, compute=0.002, memory=0.010, hidden=0.0),
        Bands(step_time_ratio_max=10.0))
    assert FLAG_STEP_TIME_ABOVE_BAND in rec[R.R_FLAGS]
    assert rec[R.R_ATTRIBUTION] == ATTR_IO  # memory lane binds


def test_reconcile_measured_below_lower_bound_is_model_violation():
    rec = reconcile_window({"step_time_s": 0.005}, _pred(lb=0.010),
                           Bands())
    assert rec[R.R_FLAGS] == [FLAG_MODEL_VIOLATION]


def test_reconcile_exposed_comm_attribution():
    lanes = {"compute": 0.002, "memory": 0.001, "hidden_comm": 0.0,
             "exposed_comm": 0.008}
    assert attribute_gap(lanes) == ATTR_COMM_EXPOSED


def test_reconcile_swap_exposure_wins_attribution():
    lanes = {"compute": 0.010, "memory": 0.001, "hidden_comm": 0.0,
             "exposed_comm": 0.0}
    swap = {"read_exposed_s": 0.08, "write_exposed_s": 0.0}
    assert attribute_gap(lanes, swap, measured_step_s=0.1) == ATTR_SWAP
    # below the 25% share the roofline lane keeps the attribution
    swap = {"read_exposed_s": 0.01}
    assert attribute_gap(lanes, swap, measured_step_s=0.1) == ATTR_COMPUTE


def test_reconcile_hbm_bands_device_only():
    bands = Bands(hbm_ratio_max=2.0)
    over = reconcile_window(
        {"step_time_s": None, "hbm_peak_bytes": 300, "mem_source":
         "device"}, _pred(hbm=100), bands)
    assert FLAG_HBM_ABOVE_BAND in over[R.R_FLAGS]
    assert over[R.R_HBM_RATIO] == pytest.approx(3.0)
    under = reconcile_window(
        {"step_time_s": None, "hbm_peak_bytes": 40, "mem_source":
         "device"}, _pred(hbm=100), bands)
    assert FLAG_HBM_BELOW_BAND in under[R.R_FLAGS]
    # host-RSS readings are NOT comparable to the HBM estimate: no
    # ratio, no flag (a CPU run must not cry HBM regression)
    rss = reconcile_window(
        {"step_time_s": None, "hbm_peak_bytes": 300, "mem_source":
         "host_rss"}, _pred(hbm=100), bands)
    assert rss[R.R_HBM_RATIO] is None
    assert rss[R.R_FLAGS] == []


def test_reconcile_swap_ceiling_band():
    swap = {"read_gbps": 1.0, "sweep_read_gbps": 20.0,
            "read_vs_ceiling": 0.05, "overlap_fraction": 0.8}
    rec = reconcile_window({"step_time_s": None, "swap": swap}, None,
                           Bands(swap_min_vs_ceiling=0.25))
    assert rec[R.R_FLAGS] == [FLAG_SWAP_BELOW_CEILING]
    assert rec[R.R_SWAP_VS_CEILING] == pytest.approx(0.05)
    assert rec[R.R_OVERLAP_FRACTION] == pytest.approx(0.8)
    ok = dict(swap, read_vs_ceiling=0.6)
    rec = reconcile_window({"step_time_s": None, "swap": ok}, None,
                           Bands(swap_min_vs_ceiling=0.25))
    assert rec[R.R_FLAGS] == []


def test_reconcile_no_predictions_still_self_describing():
    rec = reconcile_window({"step_time_s": 0.1}, None, Bands())
    assert rec[R.R_MEASURED_STEP_S] == pytest.approx(0.1)
    assert rec[R.R_STEP_RATIO] is None
    assert rec[R.R_FLAGS] == []


# --------------------------------------------------------------------- #
# host-sync audit regression: monitor-on adds ZERO hot-loop callbacks
# --------------------------------------------------------------------- #
def test_monitor_on_adds_zero_host_sync_findings(tmp_path):
    """Acceptance: the host_sync audit of the monitored program reports
    zero new hot-loop findings — the monitor lives entirely on the host
    side of the dispatch boundary, so the traced step programs are
    IDENTICAL with it on (same lockstep signature, no callbacks)."""
    from deepspeed_tpu.analysis import RULE_HOST_SYNC, audit_engine
    plain = _engine(tmp_path, monitor=None)
    plain_report = audit_engine(plain, multihost=False)
    monitored = _engine(tmp_path, monitor={"writers": ["jsonl"],
                                           "trace": True})
    _run_steps(monitored, 2)
    report = audit_engine(monitored, multihost=False)
    monitored.monitor.close()
    host_sync = [f for f in report.findings if f.rule == RULE_HOST_SYNC]
    assert host_sync == [], [f.format() for f in host_sync]
    assert report.signature == plain_report.signature
    assert report.wire_bytes_per_step == plain_report.wire_bytes_per_step


def test_monitor_on_fused_step_audit_clean(tmp_path):
    from deepspeed_tpu.analysis import RULE_HOST_SYNC, audit_engine
    engine = _engine(tmp_path, gas=2, fused=True,
                     monitor={"writers": ["jsonl"], "trace": True},
                     extra={"bf16": {"enabled": True}})
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(2, 16)).astype(np.int32)

    def it():
        while True:
            yield (ids,)

    for _ in range(3):
        engine.train_batch(it())
    report = audit_engine(engine, multihost=False)
    engine.monitor.close()
    assert [f for f in report.findings
            if f.rule == RULE_HOST_SYNC] == []
    recs = [json.loads(line) for line in open(engine.monitor.jsonl_path)]
    steps = [r for r in recs if r.get(R.F_KIND) == KIND_STEP]
    assert len(steps) == 3
    assert all(r[R.F_DISPATCHES_PER_STEP] == 1 for r in steps)


# --------------------------------------------------------------------- #
# telemetry overhead bound
# --------------------------------------------------------------------- #
def test_discard_step_resets_arrival_clock():
    """A step that produced no record (sentinel rewind path) must not
    fold its wall time into the next record."""
    sunk = []
    stream = MetricsStream(window=10 ** 9, sink=sunk.extend)
    stream.mark_step_start()
    time.sleep(0.06)                      # the rewound step's wall time
    stream.discard_step()
    stream.end_step(1, loss=1.0)
    stream.flush()
    assert sunk[0][R.F_WALL_TIME_S] < 0.05, sunk[0][R.F_WALL_TIME_S]


def test_per_step_monitor_path_is_cheap():
    """The hot-path call (end_step) is O(1) host work — 1000 calls in
    well under a second even on a loaded CI machine."""
    sunk = []
    stream = MetricsStream(window=10 ** 9, sink=sunk.extend)
    stream.mark_step_start()
    t0 = time.perf_counter()
    for i in range(1000):
        stream.end_step(i, loss=1.0, tokens=1024,
                        counters={R.F_SKIPPED_STEPS: 0})
    dt = time.perf_counter() - t0
    assert dt < 0.5, f"1000 end_step calls took {dt:.3f}s"
    stream.flush()
    assert len(sunk) == 1000


def test_monitor_overhead_within_tolerance(tmp_path):
    """Monitor-on vs monitor-off step loop on CPU: the monitored loop
    must stay within a generous constant factor (the budget absorbs CI
    noise; a per-step device sync regression would blow it by far
    more)."""
    steps = 30

    def timed(monitor):
        engine = _engine(tmp_path, monitor=monitor)
        loss = _run_steps(engine, 3)          # warmup + compile
        float(np.asarray(loss))
        t0 = time.perf_counter()
        loss = _run_steps(engine, steps)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        if engine.monitor is not None:
            engine.monitor.close()
        return dt

    t_off = timed(None)
    t_on = timed({"writers": ["jsonl", "csv"], "write_interval": 10})
    assert t_on < t_off * 2.0 + 0.75, (
        f"monitored loop {t_on:.3f}s vs bare {t_off:.3f}s — telemetry "
        "is not boundary-only anymore?")


# --------------------------------------------------------------------- #
# ZeRO-Infinity: swap stats flow into records + swap-I/O trace spans
# --------------------------------------------------------------------- #
def test_infinity_monitor_records_and_swap_trace(tmp_path):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     hidden_dropout=0.0)
    model = GPT2Model(cfg)
    nvme = tmp_path / "nvme"
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "nvme", "nvme_path": str(nvme),
                              "buffer_count": 2, "prefetch_depth": 2},
            "offload_optimizer": {"device": "cpu"}},
        "monitor": {"enabled": True, "output_path": str(tmp_path),
                    "writers": ["jsonl"], "write_interval": 2,
                    "trace": True},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=config,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(2, 16)).astype(np.int32)
    for _ in range(2):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
    engine.monitor.close()
    recs = [json.loads(line) for line in open(engine.monitor.jsonl_path)]
    steps = [r for r in recs if r.get(R.F_KIND) == KIND_STEP]
    assert len(steps) == 2
    # acceptance: swap-tier achieved GB/s + overlap flow into records
    assert all(r[R.F_SWAP_READ_GBPS] is not None and
               r[R.F_SWAP_READ_GBPS] > 0 for r in steps)
    assert all(r[R.F_SWAP_OVERLAP_FRACTION] is not None for r in steps)
    payload = json.load(open(engine.monitor.trace_path))
    assert validate_trace_events(payload) == []
    cats = {e.get("cat") for e in payload["traceEvents"]}
    assert "swap_in" in cats, sorted(cats)
    assert "swap_out" in cats, sorted(cats)
    recons = [r for r in recs if r.get(R.F_KIND) == KIND_RECONCILE]
    assert recons and recons[-1][R.R_SWAP_GBPS] is not None


# --------------------------------------------------------------------- #
# satellites
# --------------------------------------------------------------------- #
def test_tensorboard_fallback_chain_without_torch(tmp_path, monkeypatch):
    """engine._configure_tensorboard: torch -> tensorboardX -> JSONL
    scalar fallback.  With both blocked, a torch-free host still gets a
    working add_scalar sink (one loud warning, not a silent None)."""
    engine = _engine(tmp_path)
    monkeypatch.setitem(sys.modules, "torch", None)
    monkeypatch.setitem(sys.modules, "torch.utils", None)
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
    monkeypatch.setitem(sys.modules, "tensorboardX", None)
    engine.config.tensorboard_config.enabled = True
    engine.config.tensorboard_config.output_path = str(tmp_path / "tb")
    # a null job_name (present-but-null config key) must degrade, not
    # TypeError out of engine init
    engine.config.tensorboard_config.job_name = None
    writer = engine._configure_tensorboard()
    assert isinstance(writer, ScalarJsonlWriter)
    writer.add_scalar("Train/loss", 1.25, 7)
    writer.close()
    lines = [json.loads(line) for line in open(writer.path)]
    assert lines == [{"tag": "Train/loss", "value": 1.25, "step": 7}]


def test_device_sync_narrowed_exceptions(monkeypatch):
    """_device_sync swallows only ImportError/RuntimeError (logged at
    debug, once); anything else propagates — a real sync failure can no
    longer be silently timed as ~0."""
    from deepspeed_tpu.utils import timer as timer_mod
    timer_mod._device_sync()  # healthy path

    class _Boom:
        def __call__(self, *a, **k):
            raise ValueError("not a sync failure")

    import jax.numpy as jnp
    monkeypatch.setattr(jnp, "zeros", _Boom())
    with pytest.raises(ValueError):
        timer_mod._device_sync()

    def _runtime_err(*a, **k):
        raise RuntimeError("backend torn down")

    monkeypatch.setattr(jnp, "zeros", _runtime_err)
    timer_mod._device_sync()  # swallowed (logged once at debug)


def test_fused_wall_clock_breakdown_window_timer(tmp_path):
    """Satellite: under fused_step the gas window is one dispatch, so the
    forward/backward micro timers never run — the window-level
    'fused_train_batch' timer must report instead of an empty
    breakdown."""
    from deepspeed_tpu.runtime.engine import (FORWARD_MICRO_TIMER,
                                              FUSED_STEP_TIMER)
    engine = _engine(tmp_path, gas=2, fused=True,
                     extra={"wall_clock_breakdown": True,
                            "bf16": {"enabled": True}})
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(2, 16)).astype(np.int32)

    def it():
        while True:
            yield (ids,)

    engine.train_batch(it())
    assert FUSED_STEP_TIMER in engine.timers.timers
    assert engine.timers.timers[FUSED_STEP_TIMER].elapsed(reset=False) > 0
    assert FORWARD_MICRO_TIMER not in engine.timers.timers


def test_inflight_tensor_write_timestamps_feed_trace(tmp_path):
    """InflightTensorWrite carries the same issue/wait timestamp split
    as InflightGroupRead, and AsyncTensorSwapper's drained events become
    valid swap_out trace spans — the write-side handle contract for any
    tier built on the async swapper (the streaming engine's production
    write-back spans come from the param swapper's write→flush
    windows)."""
    from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
    from deepspeed_tpu.runtime.swap_tensor.aio_handle import AsyncIOHandle
    h = AsyncIOHandle(block_size=4096, queue_depth=4, thread_count=1,
                      backend="batched")
    sw = AsyncTensorSwapper(h, buffer_bytes=64 * 1024, buffer_count=2)
    arr = np.arange(1000, dtype=np.float32)
    op = sw.swap_out(arr, str(tmp_path / "w.bin"))
    assert op.t_issue > 0 and op.nbytes == arr.nbytes
    op.wait()
    assert op.hidden_s is not None and op.exposed_s is not None
    events = sw.drain_write_events()
    assert len(events) == 1
    ev = events[0]
    assert ev["bytes"] == arr.nbytes
    assert ev["t_done"] >= ev["t_issue"]
    assert sw.drain_write_events() == []  # return-and-reset
    buf = TraceEventBuffer()
    buf.add_swap_write_events(events, step=1)
    payload = buf.to_json()
    assert validate_trace_events(payload) == []
    assert any(e.get("cat") == "swap_out" for e in payload["traceEvents"])


def test_writer_thread_close_drains(tmp_path):
    from deepspeed_tpu.monitor import JsonlWriter, WriterThread
    path = str(tmp_path / "wt.jsonl")
    wt = WriterThread([JsonlWriter(path)])
    for i in range(50):
        wt.submit([{R.F_KIND: KIND_STEP, R.F_STEP: i}])
    wt.close()
    assert len(open(path).read().splitlines()) == 50
    wt.close()  # idempotent
