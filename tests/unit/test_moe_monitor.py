"""MoE routing observability (ISSUE 15, docs/telemetry.md).

Covers the acceptance surface: the device-resident RoutingStats
accumulator reaches the JSONL stream as ``moe`` records with the
ExpertPopularitySnapshot embedded (round-trip pinned on a rigged skewed
router — the consumable contract ROADMAP item 6's NVMe expert streamer
keys on), the host-sync audit regression (monitor.moe adds ZERO
findings and leaves the lockstep signature + wire bytes bit-identical,
modular and fused), the fused gas scan's in-program accumulation, the
boundary-only fetch cadence, the monitor-on-vs-off wall tolerance on
the MoE row, and the config/schema validation satellites.
"""

import json
import time

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.config import DeepSpeedConfigError, MonitorConfig
from deepspeed_tpu.monitor import (
    KIND_MOE, KIND_STEP, MetricsStream, MoeRoutingAggregator,
    SNAPSHOT_SCHEMA, TrainingMonitor, snapshot_from_record,
    summarize_window, validate_snapshot, validate_trace_events)
from deepspeed_tpu.monitor import record as R

V, S, H = 128, 16, 32


# --------------------------------------------------------------------- #
# engine fixtures (tiny GPT-MoE on an expert=4 mesh)
# --------------------------------------------------------------------- #
def _moe_engine(tmp_path, monitor_moe=True, fused=False, gas=1,
                num_layers=2, monitor=True):
    from deepspeed_tpu.models import GPTMoEConfig, GPTMoEModel
    ds.reset_mesh_context()
    ds.initialize_mesh(expert=4, data=-1)
    cfg = GPTMoEConfig(vocab_size=V, n_positions=S, hidden_size=H,
                       num_layers=num_layers, num_heads=4, num_experts=4,
                       top_k=2, bf16=False, embd_dropout=0.0,
                       attn_dropout=0.0, hidden_dropout=0.0,
                       capacity_factor=1.0, min_capacity=2)
    model = GPTMoEModel(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "fused_step": {"enabled": fused},
        "steps_per_print": 10 ** 9,
    }
    if monitor:
        config["monitor"] = {
            "enabled": True, "output_path": str(tmp_path),
            "writers": ["jsonl"], "write_interval": 2,
            "moe": {"enabled": monitor_moe}}
    engine, _, _, _ = ds.initialize(
        model=model, config=config,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    return engine, cfg


def _run(engine, n, batch=8):
    ids = np.random.RandomState(0).randint(
        0, V, size=(batch, S)).astype(np.int32)
    for _ in range(n):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
    return loss


# --------------------------------------------------------------------- #
# moe records + the popularity snapshot contract
# --------------------------------------------------------------------- #
def test_moe_records_reach_jsonl_with_snapshot(tmp_path):
    engine, cfg = _moe_engine(tmp_path)
    _run(engine, 5)
    engine.monitor.close()
    recs = [json.loads(line) for line in open(engine.monitor.jsonl_path)]
    moe = [r for r in recs if r.get(R.F_KIND) == KIND_MOE]
    # windows [1-2], [3-4], [5] — one moe record each
    assert len(moe) == 3
    assert [m[R.M_WINDOW_END] for m in moe] == [2, 4, 5]
    n_moe_layers = sum(cfg.is_moe_layer(i) for i in range(cfg.num_layers))
    for m in moe:
        assert m[R.M_EXPERTS] == 4
        assert m[R.M_LAYERS_PER_STEP] == n_moe_layers
        # token-slot accounting: layers x tokens x k per optimizer step
        assert m[R.M_TOKENS_PER_STEP] == n_moe_layers * 8 * S * 2
        assert 0.0 <= m[R.M_DROP_FRAC] <= 1.0
        assert m[R.M_IMBALANCE] >= 1.0
        assert 0.0 < m[R.M_ENTROPY] <= 1.0
        assert len(m[R.M_COUNTS]) == 4 and len(m[R.M_OVERFLOW]) == 4
        # routed + overflowed slots == wanted slots (drop accounting)
        total = sum(m[R.M_COUNTS]) + sum(m[R.M_OVERFLOW])
        assert total == pytest.approx(
            m[R.M_TOKENS_PER_STEP] * m[R.M_STEPS], rel=1e-6)
        # identity triple rides moe records too (schema v2)
        assert m[R.F_PROCESS_INDEX] == 0 and R.F_HOST in m
        snap = snapshot_from_record(m)
        assert validate_snapshot(snap) == [], snap
    # step records are untouched alongside
    assert [r[R.F_STEP] for r in recs
            if r.get(R.F_KIND) == KIND_STEP] == [1, 2, 3, 4, 5]


def test_snapshot_roundtrip_pins_skewed_router():
    """Acceptance: a rigged skewed router produces ranked hot/cold
    lists and a hit-rate-under-K curve that survive a JSONL round-trip
    — the exact artifact ROADMAP item 6's streamer will key on."""
    agg = MoeRoutingAggregator(ewma_alpha=1.0, hot_k=2)
    # 8 experts, popularity heavily skewed: 3 hot, 5 cold
    counts = np.array([400., 10., 300., 5., 20., 200., 50., 15.])
    raw = {"expert_counts": counts,
           "overflow_counts": np.zeros(8),
           "tokens": counts.sum(), "dropped": 0.0,
           "entropy": 1000.0 * np.log(8) * 0.5, "confidence": 700.0,
           "gate_tokens": 1000.0, "l_aux": 1.1, "layers": 1.0,
           "steps": 2}
    rec = agg.observe_window(raw, 1, 2)
    assert rec[R.F_KIND] == KIND_MOE
    line = json.dumps(rec)                 # JSONL round-trip
    back = json.loads(line)
    snap = snapshot_from_record(back)
    assert snap == rec[R.M_POPULARITY]
    assert validate_snapshot(snap) == []
    assert snap["schema"] == SNAPSHOT_SCHEMA
    # ranked hot list (hot_k=2): experts 0 then 2; cold ranked from the
    # least popular up: 3, 1, 15-count 7, 4, 6 (the complement)
    assert snap["hot"] == [0, 2]
    assert snap["cold"] == [3, 1, 7, 4, 6, 5]
    share = counts / counts.sum()
    # hit-rate-under-K: pinning the top-K experts in HBM catches this
    # fraction of routed tokens (cumulative sorted share)
    expected = np.cumsum(np.sort(share)[::-1])
    np.testing.assert_allclose(snap["hit_rate_under_k"], expected,
                               atol=1e-5)
    assert snap["hit_rate_under_k"][-1] == pytest.approx(1.0)
    # EWMA with alpha=1 equals the window share
    np.testing.assert_allclose(snap["ewma_share"], share, atol=1e-5)


def test_popularity_ewma_smooths_windows():
    agg = MoeRoutingAggregator(ewma_alpha=0.5, hot_k=1)

    def raw(counts):
        counts = np.asarray(counts, np.float64)
        return {"expert_counts": counts, "overflow_counts": np.zeros(4),
                "tokens": counts.sum(), "dropped": 0.0, "entropy": 1.0,
                "confidence": 1.0, "gate_tokens": 4.0, "l_aux": 1.0,
                "layers": 1.0, "steps": 1}
    agg.observe_window(raw([100, 0, 0, 0]), 1, 2)
    rec = agg.observe_window(raw([0, 100, 0, 0]), 3, 4)
    snap = rec[R.M_POPULARITY]
    # one window at alpha=.5 cannot dethrone the incumbent: 0.5 vs 0.5
    # share — hot stays stable (argsort is stable, expert 0 first)
    assert snap["ewma_share"][0] == pytest.approx(0.5)
    assert snap["ewma_share"][1] == pytest.approx(0.5)
    assert snap["windows_seen"] == 2


def test_summarize_window_dense_is_none():
    assert summarize_window({"layers": 0.0}) is None


def test_validate_snapshot_catches_garbage():
    assert validate_snapshot({"schema": "wrong"})
    good = {"schema": SNAPSHOT_SCHEMA, R.M_EXPERTS: 2,
            "ewma_share": [0.5, 0.5], "hit_rate_under_k": [0.5, 1.0],
            "hot": [0], "cold": [1], "hot_k": 1}
    assert validate_snapshot(good) == []
    bad = dict(good, hit_rate_under_k=[1.0, 0.5])
    assert any("non-decreasing" in p for p in validate_snapshot(bad))
    bad = dict(good, ewma_share=[0.9, 0.9])
    assert any("sums" in p for p in validate_snapshot(bad))
    bad = dict(good, cold=[0])
    assert any("overlap" in p for p in validate_snapshot(bad))


# --------------------------------------------------------------------- #
# host-sync audit regression (acceptance: ZERO new findings, unchanged
# lockstep signature + wire bytes with monitor.moe on)
# --------------------------------------------------------------------- #
def test_moe_monitor_on_adds_zero_host_sync_findings(tmp_path):
    from deepspeed_tpu.analysis import RULE_HOST_SYNC, audit_engine
    plain, _ = _moe_engine(tmp_path, monitor=False)
    plain_report = audit_engine(plain, multihost=False)
    monitored, _ = _moe_engine(tmp_path, monitor_moe=True)
    _run(monitored, 2)
    report = audit_engine(monitored, multihost=False)
    monitored.monitor.close()
    host_sync = [f for f in report.findings if f.rule == RULE_HOST_SYNC]
    assert host_sync == [], [f.format() for f in host_sync]
    # routing stats ride as pure device math: the collective story is
    # bit-identical — signature AND traced wire unchanged
    assert report.signature == plain_report.signature
    assert report.wire_bytes_per_step == plain_report.wire_bytes_per_step


def test_moe_monitor_fused_audit_clean_and_gas_accumulates(tmp_path):
    from deepspeed_tpu.analysis import RULE_HOST_SYNC, audit_engine
    engine, cfg = _moe_engine(tmp_path, fused=True, gas=2)
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    ids = np.random.RandomState(0).randint(0, V, (8, S)).astype(np.int32)

    def it():
        while True:
            yield (ids,)

    for _ in range(4):
        engine.train_batch(it())
    report = audit_engine(engine, multihost=False)
    assert [f for f in report.findings
            if f.rule == RULE_HOST_SYNC] == []
    engine.monitor.close()
    recs = [json.loads(line) for line in open(engine.monitor.jsonl_path)]
    moe = [r for r in recs if r.get(R.F_KIND) == KIND_MOE]
    assert len(moe) == 2
    n_moe_layers = sum(cfg.is_moe_layer(i) for i in range(cfg.num_layers))
    for m in moe:
        # the gas scan summed IN-program: both microbatches' slots land
        # in one per-step total (layers x tokens x k x gas)
        assert m[R.M_TOKENS_PER_STEP] == n_moe_layers * 8 * S * 2 * 2
        assert m[R.M_STEPS] == 2


def test_dense_model_under_monitor_moe_is_inert(tmp_path):
    """monitor.moe on a dense model: no moe records, NaN-absent fleet
    slots, nothing crashes — the accumulator simply never fills."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    cfg = GPT2Config(vocab_size=V, n_positions=S, hidden_size=H,
                     num_layers=2, num_heads=4, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    engine, _, _, _ = ds.initialize(
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "monitor": {"enabled": True, "output_path": str(tmp_path),
                            "writers": ["jsonl"], "write_interval": 2,
                            "moe": {"enabled": True}},
                "steps_per_print": 10 ** 9})
    ids = np.random.RandomState(0).randint(0, V, (2, S)).astype(np.int32)
    for _ in range(3):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
    engine.monitor.close()
    recs = [json.loads(line) for line in open(engine.monitor.jsonl_path)]
    assert [r for r in recs if r.get(R.F_KIND) == KIND_MOE] == []
    assert len([r for r in recs if r.get(R.F_KIND) == KIND_STEP]) == 3


# --------------------------------------------------------------------- #
# boundary-only cadence + overhead tolerance (acceptance)
# --------------------------------------------------------------------- #
def test_moe_fetch_is_flush_boundary_only():
    """The accumulator fetch runs once per FLUSH, never per step — the
    same cadence as the loss/memory reads (host-sync contract)."""
    calls = []

    def fake_fetch():
        calls.append(1)
        return {"expert_counts": np.array([5., 5.]),
                "overflow_counts": np.zeros(2), "tokens": 10.0,
                "dropped": 0.0, "entropy": 1.0, "confidence": 1.0,
                "gate_tokens": 10.0, "l_aux": 1.0, "layers": 1.0,
                "steps": 1}

    agg = MoeRoutingAggregator()

    def hook(raw, start, end):
        rec = agg.observe_window(raw, start, end)
        return rec, agg.fleet_fields()

    sunk = []
    stream = MetricsStream(window=4, sink=sunk.extend,
                           moe_stats_fn=fake_fetch, moe_hook=hook)
    for step in range(1, 13):
        stream.mark_step_start()
        stream.end_step(step, loss=1.0)
    assert len(calls) == 3                  # 12 steps / window 4
    stream.flush()                          # nothing pending: no fetch
    assert len(calls) == 3
    moe = [r for r in sunk if r.get(R.F_KIND) == KIND_MOE]
    assert len(moe) == 3
    assert [m[R.M_WINDOW_START] for m in moe] == [1, 5, 9]


def test_moe_monitor_overhead_within_tolerance(tmp_path):
    """Monitor-on (moe included) vs monitor-off on the MoE row: same
    generous band as the dense row — a per-step device sync regression
    in the stats accumulator would blow it by far more."""
    steps = 20

    def timed(monitor):
        engine, _ = _moe_engine(tmp_path, monitor=monitor)
        loss = _run(engine, 3)              # warmup + compile
        float(np.asarray(loss))
        t0 = time.perf_counter()
        loss = _run(engine, steps)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        if engine.monitor is not None:
            engine.monitor.close()
        return dt

    t_off = timed(False)
    t_on = timed(True)
    assert t_on < t_off * 2.0 + 0.75, (
        f"moe-monitored loop {t_on:.3f}s vs bare {t_off:.3f}s — routing "
        "telemetry is not boundary-only anymore?")


# --------------------------------------------------------------------- #
# trace counter lanes + config validation satellites
# --------------------------------------------------------------------- #
def test_trace_moe_counter_lanes(tmp_path):
    rawgen = iter(range(100))

    def fake_fetch():
        next(rawgen)
        return {"expert_counts": np.array([9., 1.]),
                "overflow_counts": np.array([3., 0.]), "tokens": 13.0,
                "dropped": 3.0, "entropy": 2.0, "confidence": 8.0,
                "gate_tokens": 13.0, "l_aux": 1.0, "layers": 1.0,
                "steps": 1}

    cfg = MonitorConfig.from_dict({
        "enabled": True, "output_path": str(tmp_path),
        "writers": ["jsonl"], "write_interval": 2, "trace": True,
        "reconcile": False, "moe": {"enabled": True}})
    mon = TrainingMonitor(cfg, moe_stats_fn=fake_fetch)
    for step in range(1, 5):
        mon.mark_step_start()
        mon.end_step(step, loss=1.0)
    mon.close()
    payload = json.load(open(mon.trace_path))
    assert validate_trace_events(payload) == []
    counters = [e for e in payload["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) == 2               # one per full window
    assert counters[0]["name"] == "moe routing"
    args = counters[0]["args"]
    assert args["drop_fraction"] == pytest.approx(3.0 / 13.0, rel=1e-4)
    assert args["imbalance"] == pytest.approx(9.0 / 5.0, rel=1e-4)
    # the moe record rode the JSONL stream alongside
    recs = [json.loads(line) for line in open(mon.jsonl_path)]
    assert [r for r in recs if r.get(R.F_KIND) == KIND_MOE]


def test_monitor_moe_config_validation():
    ok = MonitorConfig.from_dict({"enabled": True,
                                  "moe": {"enabled": True, "hot_k": 2}})
    assert ok.moe.enabled and ok.moe.hot_k == 2
    # `true` shorthand like monitor.capture
    assert MonitorConfig.from_dict({"moe": True}).moe.enabled
    assert not MonitorConfig.from_dict({}).moe.enabled
    with pytest.raises(DeepSpeedConfigError, match="ewma_alpha"):
        MonitorConfig.from_dict(
            {"moe": {"popularity_ewma_alpha": 0.0}})
    with pytest.raises(DeepSpeedConfigError, match="hot_k"):
        MonitorConfig.from_dict({"moe": {"hot_k": 0}})
    with pytest.raises(DeepSpeedConfigError, match="dead_expert"):
        MonitorConfig.from_dict({"moe": {"dead_expert_threshold": 1.5}})
    with pytest.raises(DeepSpeedConfigError, match="entropy_floor"):
        MonitorConfig.from_dict({"moe": {"entropy_floor": 1.0}})
    with pytest.raises(DeepSpeedConfigError, match="ep_imbalance_ratio"):
        MonitorConfig.from_dict({"moe": {"ep_imbalance_ratio": 1.0}})
    with pytest.raises(DeepSpeedConfigError, match="windows"):
        MonitorConfig.from_dict({"moe": {"collapse_windows": 0}})
    with pytest.raises(DeepSpeedConfigError, match="config object"):
        MonitorConfig.from_dict({"moe": "yes"})


# --------------------------------------------------------------------- #
# bench-row satellite: the moe row's routing summary helper
# --------------------------------------------------------------------- #
def test_bench_moe_routing_summary_helper(tmp_path):
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
    import bench
    engine, _ = _moe_engine(tmp_path)
    _run(engine, 3)
    routing = bench._moe_routing_summary(engine, hot_k=2)
    engine.monitor.close()
    assert routing is not None
    assert 0.0 <= routing["drop_fraction"] <= 1.0
    assert routing["imbalance_max_mean"] >= 1.0
    assert 0.0 < routing["router_entropy"] <= 1.0
    assert len(routing["popularity_top_k"]) == 2
    assert routing["hit_rate_under_k"][-1] == pytest.approx(1.0)
    # a dense engine yields None (the row embeds routing: null)
    assert bench._moe_routing_summary(object()) is None


def test_local_expert_slice_is_union_of_local_devices(tmp_path,
                                                      monkeypatch):
    """Review regression: a host whose local devices span SEVERAL
    expert-axis coordinates owns the union of their shards — resolving
    only local_devices()[0] would report shard 0's load on every host
    and blind the EP-imbalance rule."""
    engine, _ = _moe_engine(tmp_path, monitor=False)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # on the 8-device sim this process's devices cover ALL 4 expert
    # coordinates: the slice is the whole axis (exactly-fair load),
    # never shard 0's (0, 2) range
    assert engine._moe_local_expert_slice(8) == (0, 8)
    # indivisible expert counts and ep=1 meshes degrade to exactly-fair
    assert engine._moe_local_expert_slice(6) == (0, 6)
