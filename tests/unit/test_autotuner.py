"""Config autotuner (deepspeed_tpu/analysis/autotuner.py,
docs/autotuner.md).

The fast-lane cells the ISSUE pins: a golden leaderboard regression
over the example search space (ordering exact, lower bounds
band-tolerant), monotonicity properties (qwZ never increases wire
bytes; shrinking the HBM budget never adds candidates), the
calibration round-trip (rigged reconciliation windows -> fitted
constants -> the re-ranked search flips the winner as designed), the
bounded smoke search (<= 12 candidates on the simulated 8-device mesh,
nonzero survivors, valid autotune_results.json schema), loud
empty-search failures naming the binding constraint, the NVMe swap
lane (a streamed config must NOT rank like a resident one), and the
bench-ladder ingestion + row -> calibrate loop.

The module-scoped fixture runs the example search ONCE (ten traced
candidates, ~12 s); every cheap cell reads it instead of re-searching.
"""

import copy
import json
import os
import sys
from pathlib import Path

import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu import constants as C
from deepspeed_tpu.analysis.autotuner import (
    AutotuneEmptySearch, AutotuneError, RESULTS_FILENAME,
    emit_results, extract_reconciliation_windows, fit_hw_calibration,
    load_calibration, run_search, static_hbm_floor_bytes,
    validate_results)
from deepspeed_tpu.analysis.cli import (calibrate_main, main as cli_main,
                                        tune_main)
from deepspeed_tpu.analysis.cost_model import (build_step_time_model,
                                               hw_constants, swap_lane)
from deepspeed_tpu.analysis.search_space import (batch_splits,
                                                 enumerate_candidates,
                                                 mesh_factorizations)
from deepspeed_tpu.config import (AnalysisConfig, AutotuningConfig,
                                  DeepSpeedConfigError, ZeroConfig,
                                  validate_hw_constants)

REPO = Path(__file__).resolve().parents[2]
EXAMPLE_TUNE_CFG = REPO / "docs" / "examples" / "gpt2_autotune.json"
GOLDEN_LEADERBOARD = (REPO / "tests" / "unit" / "golden" /
                      "gpt2_autotune_leaderboard.json")

BASE = {
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "AdamW", "params": {"lr": 6e-4}},
    "zero_optimization": {"stage": 2},
    "steps_per_print": 10 ** 9,
}


def _search(axes, **kw):
    raw = copy.deepcopy(BASE)
    raw["autotuning"] = dict({"chips": 8, "global_batch": 16,
                              "max_candidates": 12}, **axes)
    ds.reset_mesh_context()
    try:
        return run_search(raw, chips=8, **kw)
    finally:
        ds.reset_mesh_context()


@pytest.fixture(scope="module")
def example_outcome():
    """The checked-in example search, run once per module (the same
    space the golden pins and the CLI example documents)."""
    raw = json.loads(EXAMPLE_TUNE_CFG.read_text())
    ds.reset_mesh_context()
    try:
        return run_search(raw, base_config_path=str(EXAMPLE_TUNE_CFG))
    finally:
        ds.reset_mesh_context()


@pytest.fixture(scope="module")
def emitted(example_outcome, tmp_path_factory):
    """Top-K emission of the example search (runs the emit gate)."""
    out_dir = tmp_path_factory.mktemp("autotune_out")
    payload = emit_results(example_outcome, str(out_dir), top_k=3)
    return out_dir, payload


# --------------------------------------------------------------------- #
# golden leaderboard regression
# --------------------------------------------------------------------- #
def test_golden_leaderboard_ordering_and_bounds(example_outcome):
    """Candidate ORDERING and names pinned exactly; the static lower
    bounds band-tolerant (25% — the model is deterministic but jaxpr
    byte/flop counts may drift slightly across jax versions).
    Regenerate with: python -m deepspeed_tpu.analysis tune --config
    docs/examples/gpt2_autotune.json --update-golden"""
    golden = json.loads(GOLDEN_LEADERBOARD.read_text())
    assert golden["chips"] == example_outcome.chips == 8
    assert golden["global_batch"] == example_outcome.global_batch == 16
    assert golden["n_candidates"] == len(
        example_outcome.space.candidates)
    assert golden["n_survivors"] == len(example_outcome.ranked)
    got = [(i + 1, rc.candidate.name)
           for i, rc in enumerate(example_outcome.ranked)]
    want = [(e["rank"], e["name"]) for e in golden["ranking"]]
    assert got == want, "ranking ORDER diverged from the golden"
    for entry, rc in zip(golden["ranking"], example_outcome.ranked):
        lb = rc.predicted_step_time_lb_s
        pinned = entry["predicted_step_time_lb_s"]
        assert lb == pytest.approx(pinned, rel=0.25), (
            f"{entry['name']}: lb {lb} left the golden band around "
            f"{pinned}")
        assert rc.report.step_time["bound"] == entry["bound"]
    # default (uncalibrated) search ranks with the canonical constants
    assert golden["hw"] == dict(C.ANALYSIS_HW_DEFAULTS)


def test_golden_search_space_is_bounded(example_outcome):
    """The CI smoke-search bound the ISSUE pins: <= 12 candidates on
    the simulated 8-device mesh, nonzero survivors."""
    assert jax.device_count() == 8
    assert 0 < len(example_outcome.space.candidates) <= 12
    assert len(example_outcome.ranked) > 0


# --------------------------------------------------------------------- #
# emission: schema + auditor-clean bench-ready configs
# --------------------------------------------------------------------- #
def test_emitted_results_schema_and_configs(emitted):
    out_dir, payload = emitted
    on_disk = json.loads((out_dir / RESULTS_FILENAME).read_text())
    validate_results(on_disk)  # the smoke-search schema assert
    assert on_disk["schema"] == C.AUTOTUNE_RESULTS_SCHEMA
    assert on_disk["n_survivors"] > 0
    assert len(on_disk["leaderboard"]) == 3
    for entry in on_disk["leaderboard"]:
        cfg = json.loads((out_dir / entry["config_file"]).read_text())
        # bench-ready: engine knobs only — the search block must not
        # ride along, the provenance block must
        assert C.AUTOTUNING not in cfg
        assert cfg["_autotune"]["name"] == entry["name"]
        assert cfg["_autotune"]["rank"] == entry["rank"]
        mesh = cfg[C.MESH]
        knobs = entry["knobs"]
        assert mesh[C.MESH_DATA_AXIS] == knobs["mesh"]["data"]
        # per-lane attribution present for every winner
        for lane in ("compute", "memory", "hidden_comm",
                     "exposed_comm", "swap"):
            assert lane in entry["lanes"]
    lbs = [e["predicted_step_time_lb_s"] for e in on_disk["leaderboard"]]
    assert lbs == sorted(lbs)


def test_emitted_configs_pass_error_mode_gate(emitted, capsys):
    """Never emit a config the auditor rejects: every written config
    must itself pass the literal CI lint (cli.main --mode error) — the
    emit gate ran in emit_results; re-run it here independently."""
    out_dir, payload = emitted
    entry = payload["leaderboard"][0]
    ds.reset_mesh_context()
    rc = cli_main(["--config", str(out_dir / entry["config_file"]),
                   "--mode", "error"])
    capsys.readouterr()
    ds.reset_mesh_context()
    assert rc == 0


def test_validate_results_rejects_malformed(emitted):
    _, payload = emitted
    bad = copy.deepcopy(payload)
    bad["schema"] = "nope"
    with pytest.raises(AutotuneError, match="schema tag"):
        validate_results(bad)
    bad = copy.deepcopy(payload)
    bad["leaderboard"][0]["rank"] = 7
    with pytest.raises(AutotuneError, match="consecutive"):
        validate_results(bad)
    bad = copy.deepcopy(payload)
    del bad["leaderboard"][0]["lanes"]["swap"]
    with pytest.raises(AutotuneError, match="lanes missing"):
        validate_results(bad)
    bad = copy.deepcopy(payload)
    bad["leaderboard"] = list(reversed(bad["leaderboard"]))
    with pytest.raises(AutotuneError):
        validate_results(bad)


# --------------------------------------------------------------------- #
# monotonicity cells
# --------------------------------------------------------------------- #
def test_qwz_never_increases_wire_bytes(example_outcome):
    """Turning qwZ on (int8 weight gathers) must never INCREASE the
    predicted wire bytes of the otherwise-identical candidate."""
    by_name = {rc.candidate.name: rc for rc in example_outcome.ranked}
    pairs = 0
    for name, rc in by_name.items():
        if "-qwz8" not in name:
            continue
        twin = by_name.get(name.replace("-qwz8", ""))
        assert twin is not None, f"no qwz-off twin for {name}"
        assert (rc.report.wire_bytes_per_step
                <= twin.report.wire_bytes_per_step), (
            f"{name} moved MORE wire than its dense twin")
        pairs += 1
    assert pairs >= 4  # the example space carries 4 qwz pairs


def test_fcm_never_increases_wire_bytes():
    """ISSUE 13 satellite: enabling fused_collective_matmul must never
    INCREASE the predicted wire bytes of the otherwise-identical
    candidate — the per-tile ring moves (W-1)/W of the monolithic
    gather payload (and the fused hops ARE accounted: step_wire_bytes
    counts FCM-scoped ppermutes), while the fused classification moves
    the bytes to the hidden-comm lane."""
    outcome = _search({
        "zero_stages": [3], "stage3_variants": ["streamed"],
        "prefetch_modes": ["carried"], "micro_batches": [2],
        "qwz_bits": [8], "qgz_bits": [8],
        "fused_collective_matmul": [False, True], "top_k": 2})
    by_name = {rc.candidate.name: rc for rc in outcome.ranked}
    pairs = 0
    for name, rc in by_name.items():
        if "-fcm-" not in name:
            continue
        twin = by_name.get(name.replace("-fcm-", "-"))
        assert twin is not None, f"no fcm-off twin for {name}"
        assert rc.candidate.knobs["fused_collective_matmul"] is True
        assert twin.candidate.knobs["fused_collective_matmul"] is False
        assert (rc.report.wire_bytes_per_step
                <= twin.report.wire_bytes_per_step), (
            f"{name} moved MORE wire than its modular twin")
        # the fused candidate's hot wire prices hidden: its exposed-comm
        # lane must not exceed the modular twin's
        assert (rc.report.step_time["t_comm_exposed_s"]
                <= twin.report.step_time["t_comm_exposed_s"] + 1e-12)
        assert rc.report.step_time["wire_bytes_fused"] > 0
        assert twin.report.step_time["wire_bytes_fused"] == 0
        pairs += 1
    assert pairs >= 1


def test_onebit_never_increases_wire_bytes():
    """ISSUE 16 satellite: the autotuning.onebit axis swaps the base
    optimizer for its OneBit counterpart and prices the candidate on
    its STEADY-STATE (compressed-phase) program.  The dense twin's grad
    allreduce is GSPMD-inserted (jaxpr-invisible), so monotonicity is
    asserted on the compiled-HLO wire — which the 1-bit candidate's
    explicit packed sync must undercut, never exceed."""
    raw = copy.deepcopy(BASE)
    raw["analysis"] = {"hlo_audit": True}
    raw["autotuning"] = {"chips": 8, "global_batch": 16,
                         "max_candidates": 12, "zero_stages": [2],
                         "micro_batches": [2], "fused": [False],
                         "onebit": [False, True]}
    ds.reset_mesh_context()
    try:
        outcome = run_search(raw, chips=8)
    finally:
        ds.reset_mesh_context()
    by_name = {rc.candidate.name: rc for rc in outcome.ranked}
    pairs = 0
    for name, rc in by_name.items():
        if "-1bit-" not in name:
            continue
        twin = by_name.get(name.replace("-1bit-", "-"))
        assert twin is not None, f"no onebit-off twin for {name}"
        assert rc.candidate.knobs["onebit"] is True
        assert twin.candidate.knobs["onebit"] is False
        # the compressed program's wire is explicit -> jaxpr-counted
        assert rc.report.wire_bytes_per_step > 0
        assert (rc.report.hlo["hlo_wire_bytes_per_step"]
                <= twin.report.hlo["hlo_wire_bytes_per_step"]), (
            f"{name} moved MORE compiled wire than its dense twin")
        pairs += 1
    assert pairs >= 1
    # the 1-bit candidate rode in on a OneBit optimizer swap
    onebit_rc = next(rc for rc in outcome.ranked
                     if rc.candidate.knobs["onebit"])
    opt = onebit_rc.candidate.config[C.OPTIMIZER]["type"].lower()
    assert opt.startswith("onebit"), opt


def test_shrinking_hbm_budget_never_adds_candidates(example_outcome):
    """Budget monotonicity, both pruning layers.  Traced layer: a full
    search under a mid budget must survive a strict SUBSET of the
    unrestricted search, with the over-budget candidates pruned by the
    auditor's hbm_budget rule.  Static layer: the pre-trace floor prune
    is monotone in the budget by construction."""
    unrestricted = {rc.candidate.name for rc in example_outcome.ranked}
    peaks = {rc.candidate.name: int(rc.report.peak_hbm_bytes)
             for rc in example_outcome.ranked}
    # halfway between the smallest and largest traced peak: at least
    # one candidate survives, at least one is pruned
    mid = (min(peaks.values()) + max(peaks.values())) / 2 / 2 ** 20
    restricted = _search(
        {"zero_stages": [2, 3], "stage3_variants": ["streamed"],
         "prefetch_modes": ["carried", "off"], "micro_batches": [1, 2],
         "qwz_bits": [0, 8], "top_k": 3},
        hbm_budget_mb=mid)
    survivors = {rc.candidate.name for rc in restricted.ranked}
    assert survivors < unrestricted  # strict subset: some were pruned
    assert survivors == {n for n, p in peaks.items()
                         if p <= mid * 2 ** 20}
    for p in restricted.space.pruned:
        assert p.stage in ("auditor", "hbm_floor")
        assert "hbm" in p.reason.lower() or "hbm_budget" in p.reason

    # static floor layer: pure-math monotonicity over the same knobs
    for cand in example_outcome.space.candidates:
        mesh = cand.knobs["mesh"]
        dp = mesh["data"] * mesh["expert"]
        floor = static_hbm_floor_bytes(cand.knobs, 2 ** 21, 2 ** 22, dp)
        assert floor >= 0
        # a bigger budget admits a superset by definition of a single
        # threshold — assert the floor itself is stage-monotone: zero-3
        # sharding can only shrink the resident floor
        if cand.knobs["zero_stage"] == 3:
            z1 = dict(cand.knobs, zero_stage=1)
            assert floor <= static_hbm_floor_bytes(z1, 2 ** 21, 2 ** 22,
                                                   dp)


# --------------------------------------------------------------------- #
# calibration: fit + round-trip through the search
# --------------------------------------------------------------------- #
def _rigged_windows():
    """Two windows designed to fit hbm_gbps and ici_gbps 10x FASTER
    than the v5e defaults: a memory-bound window measured at a tenth of
    its predicted binding lane, and a comm-exposed window whose exposed
    term absorbs a tenth of its predicted time."""
    return [
        {"measured_step_time_s": 0.1,
         "lanes": {"compute": 0.01, "memory": 1.0, "hidden_comm": 0.0,
                   "exposed_comm": 0.0}},
        {"measured_step_time_s": 0.2,
         "lanes": {"compute": 0.1, "memory": 0.05, "hidden_comm": 0.0,
                   "exposed_comm": 1.0}},
    ]


def test_fit_hw_calibration_skips_swap_windows():
    """An NVMe window's disk seconds sit in the measured step but in no
    roofline lane — fitting from it would read 'compute is 6x slower'.
    Swap-tier windows must be skipped, not attributed."""
    base = dict(C.ANALYSIS_HW_DEFAULTS)
    swap_window = {"measured_step_time_s": 6.0,
                   "lanes": {"compute": 1.0, "memory": 0.1,
                             "exposed_comm": 0.0, "swap": 5.0}}
    payload = fit_hw_calibration([swap_window], base)
    assert payload["windows_used"] == 0
    assert payload["windows_skipped"] == 1
    assert payload["hw"] == base  # nothing fitted, nothing corrupted
    mixed = fit_hw_calibration([swap_window] + _rigged_windows(), base)
    assert mixed["windows_used"] == 2 and mixed["windows_skipped"] == 1
    assert mixed["fitted"][C.ANALYSIS_HW_PEAK_TFLOPS] is False


def test_fit_hw_calibration_scales_constants():
    base = dict(C.ANALYSIS_HW_DEFAULTS)
    payload = fit_hw_calibration(_rigged_windows(), base, source="rig")
    assert payload["schema"] == C.HW_CALIBRATION_SCHEMA
    assert payload["windows_used"] == 2
    assert payload["fitted"][C.ANALYSIS_HW_HBM_GBPS] is True
    assert payload["fitted"][C.ANALYSIS_HW_ICI_GBPS] is True
    assert payload["fitted"][C.ANALYSIS_HW_PEAK_TFLOPS] is False
    hw = payload["hw"]
    assert hw[C.ANALYSIS_HW_HBM_GBPS] == pytest.approx(
        base[C.ANALYSIS_HW_HBM_GBPS] * 10, rel=1e-6)
    assert hw[C.ANALYSIS_HW_ICI_GBPS] == pytest.approx(
        base[C.ANALYSIS_HW_ICI_GBPS] * 10, rel=1e-6)
    assert hw[C.ANALYSIS_HW_PEAK_TFLOPS] == base[
        C.ANALYSIS_HW_PEAK_TFLOPS]


def test_calibration_roundtrip_flips_winner(tmp_path, capsys):
    """The designed flip: under the v5e defaults the z2 candidate wins
    (memory-bound roofline); under a calibration fitted from windows
    showing this host's HBM and ICI 10x faster, the wire/io terms
    deflate and the streamed-qwZ candidate overtakes it.  The fit runs
    through the REAL calibrate CLI over monitor-style JSONL records,
    and the re-ranked search loads the written file."""
    records = tmp_path / "monitor.jsonl"
    with records.open("w") as f:
        for w in _rigged_windows():
            f.write(json.dumps(dict(w, kind="reconcile")) + "\n")
    cal_file = tmp_path / "hw_calibration.json"
    rc = calibrate_main(["--records", str(records),
                         "--out", str(cal_file)])
    out = capsys.readouterr()
    assert rc == 0, out.err
    assert "fitted" in out.out
    hw = load_calibration(str(cal_file))
    assert hw[C.ANALYSIS_HW_HBM_GBPS] == pytest.approx(
        C.ANALYSIS_HW_HBM_GBPS_DEFAULT * 10, rel=1e-6)

    axes = {"zero_stages": [2, 3], "stage3_variants": ["streamed"],
            "prefetch_modes": ["off"], "micro_batches": [2],
            "qwz_bits": [8]}
    default = _search(axes)
    calibrated = _search(axes, calibration=str(cal_file))
    assert default.ranked[0].candidate.name.startswith("z2")
    assert calibrated.ranked[0].candidate.name.startswith("z3s")
    assert (calibrated.ranked[0].candidate.name
            != default.ranked[0].candidate.name)
    # the calibrated constants ride the outcome's analysis config (and
    # thus the results payload's hw block) under the canonical names
    assert hw_constants(calibrated.analysis_cfg) == hw
    assert calibrated.calibration_file == str(cal_file)


def test_load_calibration_rejects_non_calibration_files(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"schema": "other"}))
    with pytest.raises(AutotuneError, match="not a calibration file"):
        load_calibration(str(p))
    p.write_text(json.dumps({"schema": C.HW_CALIBRATION_SCHEMA,
                             "hw": {C.ANALYSIS_HW_HBM_GBPS: 100.0}}))
    with pytest.raises(AutotuneError, match="missing"):
        load_calibration(str(p))
    p.write_text(json.dumps({
        "schema": C.HW_CALIBRATION_SCHEMA,
        "hw": {k: -1.0 for k in C.ANALYSIS_HW_KEYS}}))
    with pytest.raises(DeepSpeedConfigError, match="must be > 0"):
        load_calibration(str(p))


def test_calibrate_cli_no_windows_exits_nonzero(tmp_path, capsys):
    records = tmp_path / "empty.jsonl"
    records.write_text(json.dumps({"kind": "step", "loss": 1.0}) + "\n")
    rc = calibrate_main(["--records", str(records),
                         "--out", str(tmp_path / "cal.json")])
    err = capsys.readouterr().err
    assert rc == 1
    assert "no reconciliation windows" in err


def test_bench_row_reconciliation_feeds_calibrate(tmp_path):
    """A bench row's embedded reconciliation (stale-marked or not) is a
    calibration source — the ISSUE's 'validate on chip once' loop."""
    row = {"metric": "x", "value": 1.0, "stale": True,
           "reconciliation": {"measured_step_time_s": 0.5,
                              "lanes": {"compute": 0.2, "memory": 0.1,
                                        "exposed_comm": 0.0}}}
    p = tmp_path / "row.json"
    p.write_text(json.dumps(row))
    windows = extract_reconciliation_windows(str(p))
    assert len(windows) == 1
    assert windows[0]["measured_step_time_s"] == 0.5


# --------------------------------------------------------------------- #
# swap lane: streamed != resident
# --------------------------------------------------------------------- #
def test_swap_lane_prices_nvme_traffic():
    zero = ZeroConfig.from_dict({
        "stage": 3,
        "offload_param": {"device": "nvme", "prefetch_depth": 2},
        "offload_optimizer": {"device": "nvme", "pipeline_depth": 2}})
    swap = swap_lane(zero, None, param_bytes=10 ** 9,
                     opt_state_bytes=2 * 10 ** 9)
    assert swap is not None
    # double-buffered tiers hide under compute like hidden comm
    assert swap["t_hidden_s"] > 0 and swap["t_exposed_s"] == 0
    assert swap["read_bytes"] == 2 * 10 ** 9 + 2 * 10 ** 9
    assert swap["write_bytes"] == 10 ** 9 + 2 * 10 ** 9

    serialized = ZeroConfig.from_dict({
        "stage": 3,
        "offload_param": {"device": "nvme", "prefetch_depth": 1}})
    sswap = swap_lane(serialized, None, param_bytes=10 ** 9,
                      opt_state_bytes=0)
    assert sswap["t_exposed_s"] > 0 and sswap["t_hidden_s"] == 0

    resident = ZeroConfig.from_dict({"stage": 3})
    assert swap_lane(resident, None, param_bytes=10 ** 9,
                     opt_state_bytes=10 ** 9) is None
    cpu = ZeroConfig.from_dict({
        "stage": 2, "offload_optimizer": {"device": "cpu"}})
    assert swap_lane(cpu, None, param_bytes=10 ** 9,
                     opt_state_bytes=10 ** 9) is None


def test_swap_lane_changes_step_time_bound():
    """The satellite regression: with the swap lane folded in, a
    streamed (NVMe) config must rank strictly slower than the identical
    resident one — before this PR they ranked identically."""
    cfg = AnalysisConfig.from_dict({"mode": "off"})
    flops, io = 10 ** 12, 10 ** 9
    without = build_step_time_model(flops, io, [], cfg)
    hidden = {"t_hidden_s": 10.0, "t_exposed_s": 0.0, "read_bytes": 1,
              "write_bytes": 1, "read_gbps": 1.0, "write_gbps": 1.0,
              "source": "test"}
    with_hidden = build_step_time_model(flops, io, [], cfg, swap=hidden)
    assert with_hidden["predicted_step_time_lb_s"] > \
        without["predicted_step_time_lb_s"]
    assert with_hidden["bound"] == "swap"
    assert with_hidden["t_swap_s"] == 10.0
    exposed = dict(hidden, t_hidden_s=0.0, t_exposed_s=3.0)
    with_exposed = build_step_time_model(flops, io, [], cfg,
                                         swap=exposed)
    assert with_exposed["predicted_step_time_lb_s"] == pytest.approx(
        without["predicted_step_time_lb_s"] + 3.0)


def test_nvme_candidate_ranks_slower_than_resident():
    """End-to-end through the search: the NVMe candidate audits its
    resident twin but pays the disk trips via the swap lane."""
    nvme = _search({"zero_stages": [3], "stage3_variants": ["streamed"],
                    "prefetch_modes": ["carried"], "micro_batches": [2],
                    "offload": ["nvme"]})
    resident = _search({"zero_stages": [3],
                        "stage3_variants": ["streamed"],
                        "prefetch_modes": ["carried"],
                        "micro_batches": [2], "offload": ["none"]})
    n, r = nvme.ranked[0], resident.ranked[0]
    assert "off-nvme" in n.candidate.name
    assert n.report.step_time["t_swap_s"] > 0
    assert n.report.step_time["swap"]["source"] in (
        "fallback_default",) or n.report.step_time["swap"][
        "source"].startswith("sweep_ceiling:")
    assert n.predicted_step_time_lb_s > r.predicted_step_time_lb_s


# --------------------------------------------------------------------- #
# loud empty searches
# --------------------------------------------------------------------- #
def test_empty_search_batch_infeasible_names_nearest_worlds():
    with pytest.raises(AutotuneEmptySearch) as ei:
        _search({"zero_stages": [2]}, global_batch=7)
    msg = str(ei.value)
    assert "batch-triple infeasibility" in msg
    assert "Nearest chip counts" in msg
    assert "[7, 1]" in msg


def test_empty_search_hbm_binding_names_budget():
    with pytest.raises(AutotuneEmptySearch) as ei:
        _search({"zero_stages": [2, 3],
                 "stage3_variants": ["streamed"],
                 "micro_batches": [2]}, hbm_budget_mb=0.001)
    msg = str(ei.value)
    assert "HBM budget is the binding constraint" in msg
    assert "smallest feasible estimate" in msg


def test_empty_search_message_not_misattributed_to_hbm():
    """A search where auditor prunes were NOT hbm_budget findings must
    not tell the operator to raise the HBM budget — raising it would
    change nothing."""
    from deepspeed_tpu.analysis.autotuner import (SearchOutcome,
                                                  _empty_search_message)
    from deepspeed_tpu.analysis.search_space import Pruned, SearchSpace
    space = SearchSpace(n_enumerated=2)
    space.pruned = [
        Pruned(name="a", stage="hbm_floor", reason="floor over budget"),
        Pruned(name="b", stage="auditor",
               reason="[overlap] serialized hot-loop gather"),
    ]
    outcome = SearchOutcome(
        space=space, ranked=[], analysis_cfg=None, chips=8,
        global_batch=16, hbm_budget_mb=1.0, model_kw={},
        floor_prunes=[("a", 123)])
    msg = _empty_search_message(outcome)
    assert "HBM budget is the binding constraint" not in msg
    assert "overlap" in msg  # falls through to the per-prune listing


def test_hbm_floor_optimizer_state_is_sound():
    """The floor only assumes state the configured optimizer must
    carry: a hardcoded Adam 2x would over-prune plain-SGD searches."""
    from deepspeed_tpu.analysis.autotuner import _optimizer_moments
    assert _optimizer_moments("AdamW") == 2
    assert _optimizer_moments("adam") == 2
    assert _optimizer_moments("SGDMomentum") == 1
    assert _optimizer_moments("sgd") == 0
    assert _optimizer_moments(None) == 0


def test_tune_cli_empty_search_exits_nonzero(tmp_path, capsys):
    raw = dict(BASE)
    raw["autotuning"] = {"chips": 8, "global_batch": 7,
                         "zero_stages": [2], "max_candidates": 12}
    cfg = tmp_path / "t.json"
    cfg.write_text(json.dumps(raw))
    ds.reset_mesh_context()
    rc = tune_main(["--config", str(cfg), "--out",
                    str(tmp_path / "out")])
    ds.reset_mesh_context()
    err = capsys.readouterr().err
    assert rc == 1
    assert "EMPTY SEARCH" in err
    assert "Nearest chip counts" in err
    assert not (tmp_path / "out" / RESULTS_FILENAME).exists()


def test_tune_cli_requires_chips(tmp_path, capsys):
    cfg = tmp_path / "t.json"
    cfg.write_text(json.dumps(BASE))
    rc = tune_main(["--config", str(cfg)])
    assert rc == 2
    assert "--chips" in capsys.readouterr().err


def test_oversized_space_refuses_silent_truncation():
    with pytest.raises(AutotuneError, match="never truncates silently"):
        _search({"zero_stages": [2, 3], "micro_batches": [1, 2],
                 "qwz_bits": [0, 4, 8], "qgz_bits": [0, 4, 8],
                 "max_candidates": 4})


# --------------------------------------------------------------------- #
# search-space + config validation
# --------------------------------------------------------------------- #
def test_mesh_factorizations_and_batch_splits():
    assert mesh_factorizations(8, (1, 2), (1,)) == [(8, 1, 1), (4, 2, 1)]
    assert mesh_factorizations(8, (3,), (1,)) == []
    assert batch_splits(16, 8) == [(1, 2), (2, 1)]
    assert batch_splits(16, 8, micro_filter=(2,)) == [(2, 1)]
    assert batch_splits(7, 8) == []


def test_autotuning_config_validation():
    with pytest.raises(DeepSpeedConfigError, match="top_k"):
        AutotuningConfig.from_dict({"top_k": 0})
    with pytest.raises(DeepSpeedConfigError, match="zero_stages"):
        AutotuningConfig.from_dict({"zero_stages": [4]})
    with pytest.raises(DeepSpeedConfigError, match="offload"):
        AutotuningConfig.from_dict({"offload": ["gpu"]})
    with pytest.raises(DeepSpeedConfigError, match="hbm_budget_mb"):
        AutotuningConfig.from_dict({"hbm_budget_mb": -1})
    with pytest.raises(DeepSpeedConfigError, match="fixed"):
        AutotuningConfig.from_dict({"fixed": ["not-a-dict"]})
    with pytest.raises(DeepSpeedConfigError, match="prefetch_modes"):
        AutotuningConfig.from_dict({"prefetch_modes": ["bogus"]})
    cfg = AutotuningConfig.from_dict({"chips": 8, "qwz_bits": [0, 8]})
    assert cfg.chips == 8 and cfg.qwz_bits == (0, 8)


def test_hw_constants_single_sourced():
    """The canonical names: config block, cost-model payload, and
    calibration override all speak C.ANALYSIS_HW_KEYS."""
    cfg = AnalysisConfig.from_dict({"mode": "off"})
    assert hw_constants(cfg) == dict(C.ANALYSIS_HW_DEFAULTS)
    with pytest.raises(DeepSpeedConfigError, match="must be > 0"):
        validate_hw_constants({C.ANALYSIS_HW_HBM_GBPS: 0.0})
    with pytest.raises(DeepSpeedConfigError, match="must be > 0"):
        AnalysisConfig.from_dict({"mode": "off", "hw_ici_gbps": -5})
    over = cfg.hw_overridden({C.ANALYSIS_HW_ICI_GBPS: 42.0})
    assert over.hw_ici_gbps == 42.0
    assert over.hw_peak_tflops == cfg.hw_peak_tflops


def test_enumeration_is_gated():
    """Stage-1/2 candidates collapse the streamed-only knobs; NVMe
    requires the streamed stage-3 shape; hpZ must divide the dp world."""
    tune = AutotuningConfig.from_dict({
        "chips": 8, "global_batch": 16, "zero_stages": [1, 3],
        "stage3_variants": ["streamed"], "prefetch_modes": ["carried"],
        "micro_batches": [2], "qwz_bits": [0, 8],
        "offload": ["none", "nvme"], "hpz_group_sizes": [0, 3],
        "max_candidates": 64})
    space = enumerate_candidates(dict(BASE), tune, 8, 16)
    names = [c.name for c in space.candidates]
    assert all("qwz" not in n for n in names if n.startswith("z1"))
    assert all("nvme" not in n for n in names if n.startswith("z1"))
    assert not any("hpz3" in n for n in names)  # 3 does not divide 8
    hpz_prunes = [p for p in space.pruned
                  if p.reason.startswith("hpz_group_size 3")]
    # one record per genuinely distinct rejection (per mesh), not one
    # per unrelated knob combination
    assert len(hpz_prunes) == 1
    # NVMe names carry their prefetch depth; cpu-tier names must not
    # grow a bogus 'None' depth suffix
    assert any(n.endswith("off-nvme2") for n in names)
    cpu_space = enumerate_candidates(
        dict(BASE), AutotuningConfig.from_dict({
            "chips": 8, "global_batch": 16, "zero_stages": [2],
            "micro_batches": [2], "offload": ["cpu"],
            "max_candidates": 12}), 8, 16)
    cpu_names = [c.name for c in cpu_space.candidates]
    assert cpu_names and all(n.endswith("off-cpu") for n in cpu_names)


# --------------------------------------------------------------------- #
# bench-ladder ingestion
# --------------------------------------------------------------------- #
def test_bench_autotune_ingests_top_rank(emitted, monkeypatch):
    """bench.py --config autotune runs the rank-1 emitted config
    verbatim and embeds the search's prediction next to the measured
    step time (the reconciliation a later `calibrate` reads)."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    out_dir, payload = emitted
    monkeypatch.setenv("DS_BENCH_AUTOTUNE_RESULTS",
                       str(out_dir / RESULTS_FILENAME))
    monkeypatch.setenv("DS_BENCH_AUTOTUNE_RANK", "1")
    ds.reset_mesh_context()
    try:
        row = bench.bench_autotune()
    finally:
        ds.reset_mesh_context()
    assert row["metric"] == "autotune_candidate_train_tokens_per_sec"
    assert row["value"] > 0
    assert row["autotune_rank"] == 1
    assert row["autotune_name"] == payload["leaderboard"][0]["name"]
    assert row["autotune_predicted_step_time_lb_s"] == pytest.approx(
        payload["leaderboard"][0]["predicted_step_time_lb_s"])
    assert row["autotune_measured_over_predicted"] > 0
    rec = row.get("reconciliation")
    assert rec and rec["measured_step_time_s"] > 0 and rec["lanes"]


def test_bench_autotune_missing_rank_fails_loudly(emitted, monkeypatch):
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    out_dir, _ = emitted
    monkeypatch.setenv("DS_BENCH_AUTOTUNE_RESULTS",
                       str(out_dir / RESULTS_FILENAME))
    monkeypatch.setenv("DS_BENCH_AUTOTUNE_RANK", "99")
    with pytest.raises(RuntimeError, match="no rank 99"):
        bench.bench_autotune()
