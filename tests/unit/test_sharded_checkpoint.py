"""Sharded checkpoint layout (runtime/sharded_checkpoint.py): per-process
slice-keyed shard files, resharding-on-load, dp-resize restore, offline
fp32 consolidation, and a REAL 2-process jax.distributed run.

Reference: engine.py:1821-1878 per-rank shard files; stage2.py:1948-2126
elastic dp-resize; utils/zero_to_fp32.py:281 consolidation; the reference's
multi-process unit harness is tests/unit/common.py:16 distributed_test.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.runtime import sharded_checkpoint as sc

SEQ = 16
GLOBAL_BATCH = 8


def _mesh(n):
    ds.reset_mesh_context()
    return ds.initialize_mesh(data=-1, devices=jax.devices()[:n])


def test_save_load_roundtrip_resharded(tmp_path):
    """Shards written under one sharding must reassemble exactly under a
    DIFFERENT sharding (the dp-resize primitive)."""
    mesh8 = _mesh(8)
    x = jnp.arange(64 * 6, dtype=jnp.float32).reshape(64, 6)
    xs = jax.device_put(x, NamedSharding(mesh8.mesh, P("data", None)))
    sc.save_sharded(str(tmp_path), "model", {"w": xs, "b": np.arange(3)})

    mesh4 = _mesh(4)
    tmpl = {"w": jax.device_put(jnp.zeros((64, 6)),
                                NamedSharding(mesh4.mesh, P("data", None))),
            "b": np.zeros(3, np.int64)}
    out = sc.load_sharded(str(tmp_path), "model", tmpl)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(x))
    np.testing.assert_array_equal(out["b"], np.arange(3))
    assert out["w"].sharding.is_equivalent_to(
        NamedSharding(mesh4.mesh, P("data", None)), 2)
    ds.reset_mesh_context()


def test_bfloat16_roundtrip(tmp_path):
    """npz degrades bf16 to a '|V2' void payload — the catalog must re-view
    it from the index dtype (default models are bf16)."""
    mesh8 = _mesh(8)
    x = jnp.arange(32 * 4, dtype=jnp.bfloat16).reshape(32, 4)
    xs = jax.device_put(x, NamedSharding(mesh8.mesh, P("data", None)))
    sc.save_sharded(str(tmp_path), "model", {"w": xs})
    tmpl = {"w": jax.device_put(
        jnp.zeros((32, 4), jnp.bfloat16),
        NamedSharding(mesh8.mesh, P("data", None)))}
    out = sc.load_sharded(str(tmp_path), "model", tmpl)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["w"]).view(np.uint16),
        np.asarray(x).view(np.uint16))
    # consolidation upcasts to fp32
    cons = sc.consolidate_sharded_to_fp32(str(tmp_path), "model")
    vals = list(cons.values())[0]
    assert vals.dtype == np.float32
    np.testing.assert_array_equal(vals, np.asarray(x, np.float32))
    ds.reset_mesh_context()


def _train(nsteps, n_devices, tmp_path=None, save_at=None, load_from=None,
           tag="t0"):
    mesh = _mesh(n_devices)
    cfg = GPT2Config(vocab_size=64, n_positions=SEQ, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    dp = mesh.data_parallel_world_size
    conf = {
        "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "checkpoint": {"sharded": True},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=mesh, rng=jax.random.PRNGKey(1))
    if load_from is not None:
        engine.load_checkpoint(load_from, tag=tag)
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(2),
                                        (GLOBAL_BATCH, SEQ), 0, 64),
                     np.int32)
    losses = []
    for step in range(nsteps):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
        if save_at is not None and engine.global_steps == save_at:
            engine.save_checkpoint(str(tmp_path), tag=tag)
    ds.reset_mesh_context()
    return losses, engine


def test_dp_resize_restore(tmp_path):
    """Kill-and-resume at a different dp world size reproduces the loss
    curve (matched global batch): dp=8 saves at step 2, dp=4 resumes."""
    full_losses, _ = _train(4, 8)
    _train(2, 8, tmp_path=tmp_path, save_at=2)
    resumed_losses, engine = _train(2, 4, load_from=str(tmp_path))
    assert engine.global_steps == 4
    np.testing.assert_allclose(resumed_losses, full_losses[2:], rtol=1e-5)


def test_engine_sharded_layout_files(tmp_path):
    _train(1, 8, tmp_path=tmp_path, save_at=1)
    ckpt = tmp_path / "t0"
    assert (ckpt / "model_index.json").is_file()
    assert (ckpt / "model_shards_p00000.npz").is_file()
    assert (ckpt / "optim_shards_p00000.npz").is_file()
    # index covers every leaf with shapes
    idx = json.loads((ckpt / "model_index.json").read_text())
    assert any("wte" in k for k in idx)


def test_consolidate_sharded_to_fp32(tmp_path):
    _, engine0 = _train(1, 8, tmp_path=tmp_path, save_at=1)
    out = sc.consolidate_sharded_to_fp32(str(tmp_path / "t0"), "model")
    assert all(v.dtype == np.float32 for v in out.values()
               if np.issubdtype(np.asarray(v).dtype, np.floating))
    # consolidated weights equal the engine's own (gathered) params
    flat = {jax.tree_util.keystr(p): np.asarray(leaf) for p, leaf in
            jax.tree_util.tree_flatten_with_path(
                {"module": engine0.params})[0]}
    for k, v in out.items():
        np.testing.assert_allclose(v, flat[k].astype(np.float32),
                                   rtol=1e-6)


def test_expert_shards_stored_separately(tmp_path):
    """MoE analog of the reference's per-expert checkpoint files
    (engine.py:2230-2298): expert-stacked leaves sharded over the expert
    axis produce one slice-keyed shard entry per expert partition, so
    experts restore independently under a different expert-parallel size."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1, expert=4)
    w = jnp.arange(4 * 8 * 8, dtype=jnp.float32).reshape(4, 8, 8)
    ws = jax.device_put(
        w, NamedSharding(mesh.mesh, P("expert", None, None)))
    sc.save_sharded(str(tmp_path), "model", {"experts": ws})
    with np.load(tmp_path / "model_shards_p00000.npz") as z:
        expert_keys = [k for k in z.files if "experts" in k]
    assert len(expert_keys) == 4  # one slice entry per expert shard
    # reload onto expert=2 topology
    mesh2 = ds.initialize_mesh(data=-1, expert=2)
    tmpl = {"experts": jax.device_put(
        jnp.zeros((4, 8, 8)),
        NamedSharding(mesh2.mesh, P("expert", None, None)))}
    out = sc.load_sharded(str(tmp_path), "model", tmpl)
    np.testing.assert_array_equal(np.asarray(out["experts"]), np.asarray(w))
    ds.reset_mesh_context()


def test_two_process_distributed_checkpoint(tmp_path):
    """Real 2-process jax.distributed run: per-process batch feeding
    (make_array_from_process_local_data), cross-process checkpoint tag
    agreement, per-process shard files, save/load round-trip."""
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()
    worker = os.path.join(os.path.dirname(__file__),
                          "distributed_ckpt_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))) +
        os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, "2", str(pid), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)]
    try:
        outs = [p.communicate(timeout=540)[0].decode() for p in procs]
    finally:  # a deadlocked pair must not leak workers / the coord port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    # both processes wrote their own shard files
    assert (tmp_path / "tag0" / "model_shards_p00000.npz").is_file()
    assert (tmp_path / "tag0" / "model_shards_p00001.npz").is_file()
    results = [json.loads((tmp_path / f"result_p{pid}.json").read_text())
               for pid in range(2)]
    # both processes observed identical (global) losses
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["final_loss"],
                               results[1]["final_loss"], rtol=1e-6)


def test_two_process_distributed_training_matches_single_process(tmp_path):
    """2-process jax.distributed TRAINING run (VERDICT round-2 #9): each
    process feeds its half of the global batch; the loss trajectory and
    final global param norm must match the identical training run done
    single-process on the same 8-device mesh (reference analog:
    tests/unit/common.py:16 forks real workers for training paths)."""
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()
    worker = os.path.join(os.path.dirname(__file__),
                          "distributed_train_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))) +
        os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, "2", str(pid), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)]
    try:
        outs = [p.communicate(timeout=540)[0].decode() for p in procs]
    finally:  # a deadlocked pair must not leak workers / the coord port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = [json.loads((tmp_path / f"train_p{pid}.json").read_text())
               for pid in range(2)]
    # both processes observed the same global losses
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)

    # single-process reference on the same 8-device mesh, same seeds/batch
    import jax
    from tests.unit import distributed_train_worker as w

    ds.reset_mesh_context()
    engine = w.build()
    full = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (8, 16),
                                         0, 64), np.int32)
    ref_losses = w.train_losses(engine, full)
    ref_norm = w.global_param_norm(engine.params)
    ds.reset_mesh_context()

    np.testing.assert_allclose(results[0]["losses"], ref_losses, rtol=1e-5)
    np.testing.assert_allclose(results[0]["param_norm"], ref_norm,
                               rtol=1e-5)
