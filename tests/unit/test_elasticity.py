"""Elasticity solver tests (modeled on reference tests/unit/test_elastic.py)."""

import pytest

import deepspeed_tpu.elasticity as el
from deepspeed_tpu.config import DeepSpeedConfig


def base_ds_config():
    return {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 10000,
            "micro_batch_sizes": [8, 12, 16, 17],
            "min_gpus": 32,
            "max_gpus": 1500,
            "min_time": 20,
            "version": 0.1,
        }
    }


def test_basic_10k():
    final_batch, valid_gpus = el.compute_elastic_config(base_ds_config())
    for gpus in valid_gpus:
        assert final_batch % gpus == 0, f"{final_batch} not divisible by {gpus}"
        micros = base_ds_config()["elasticity"]["micro_batch_sizes"]
        assert any((final_batch // gpus) % mb == 0 for mb in micros)
    assert 32 <= min(valid_gpus)
    assert max(valid_gpus) <= 1500


def test_target_world_size_valid():
    _, valid_gpus = el.compute_elastic_config(base_ds_config())
    ws = valid_gpus[len(valid_gpus) // 2]
    final_batch, valid_gpus2, micro = el.compute_elastic_config(
        base_ds_config(), world_size=ws)
    assert ws in valid_gpus2
    assert final_batch % ws == 0
    assert (final_batch // ws) % micro == 0


def test_invalid_world_size():
    _, valid_gpus = el.compute_elastic_config(base_ds_config())
    bad = max(valid_gpus) + 1
    while bad in valid_gpus:
        bad += 1
    with pytest.raises(el.ElasticityIncompatibleWorldSize):
        el.compute_elastic_config(base_ds_config(), world_size=bad)


def test_invalid_world_size_error_is_actionable():
    """The incompatible-world-size error names the nearest valid world
    sizes WITH the micro-batch/gas each would run at — an operator (or
    the fleet supervisor) picks a target from the message instead of
    bisecting chip counts against a bare exception."""
    final_batch, valid_gpus = el.compute_elastic_config(base_ds_config())
    bad = max(valid_gpus) + 1
    while bad in valid_gpus:
        bad += 1
    with pytest.raises(el.ElasticityIncompatibleWorldSize) as ei:
        el.compute_elastic_config(base_ds_config(), world_size=bad)
    msg = str(ei.value)
    assert f"World size ({bad})" in msg
    assert "Nearest valid world sizes" in msg
    for g in el.nearest_valid_world_sizes(valid_gpus, bad):
        # each suggestion carries a consistent (micro, gas) solve
        assert f"{g} chips (micro_batch=" in msg
        start = msg.index(f"{g} chips (micro_batch=") + len(f"{g} chips (")
        fields = dict(kv.split("=") for kv in
                      msg[start:msg.index(")", start)].split(", "))
        assert (int(fields["micro_batch"]) * int(fields["gas"]) * g
                == final_batch)


def test_nearest_valid_world_sizes_ordering():
    assert el.nearest_valid_world_sizes([2, 4, 8, 16], 7) == [8, 4, 2]
    # ties resolve smaller-first; k bounds the list
    assert el.nearest_valid_world_sizes([4, 8], 6) == [4, 8]
    assert el.nearest_valid_world_sizes([1, 2, 3], 10, k=2) == [3, 2]


def test_future_version_rejected():
    d = base_ds_config()
    d["elasticity"]["version"] = 0.2
    with pytest.raises(el.ElasticityConfigError):
        el.compute_elastic_config(d)


def test_missing_fields():
    with pytest.raises(el.ElasticityConfigError):
        el.compute_elastic_config({"elasticity": {"enabled": True}})


def test_non_elastic_batch_info_rejected():
    d = base_ds_config()
    d["train_batch_size"] = 4
    d["elasticity"]["min_gpus"] = 1
    d["elasticity"]["max_gpus"] = 4
    with pytest.raises(el.ElasticityConfigError):
        DeepSpeedConfig(d, world_size=2)


def test_config_rewrites_batch_keys():
    d = base_ds_config()
    d["elasticity"]["min_gpus"] = 1
    d["elasticity"]["max_gpus"] = 4
    cfg = DeepSpeedConfig(d, world_size=2)
    assert cfg.elasticity_enabled
    assert cfg.train_batch_size == (cfg.train_micro_batch_size_per_gpu *
                                    cfg.gradient_accumulation_steps * 2)
