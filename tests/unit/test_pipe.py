"""End-to-end pipeline-parallel training on the simulated 8-device mesh
(reference: tests/unit/test_pipe.py:268 — tiny-model pipeline convergence)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipeLayer,
                                               PipelineModule, TiedLayerSpec)

HIDDEN = 16
IN_DIM = 8
OUT_DIM = 8


class EmbedLayer(PipeLayer):
    def __init__(self, in_dim=IN_DIM, hidden=HIDDEN):
        self.in_dim, self.hidden = in_dim, hidden

    def init_params(self, rng, x):
        return {"w": jax.random.normal(rng, (self.in_dim, self.hidden),
                                       jnp.float32) * 0.5}

    def apply(self, params, x, rng=None):
        return x @ params["w"]


class Block(PipeLayer):
    """Shape-preserving residual block — the homogeneous pipeline body."""

    def init_params(self, rng, x):
        k1, k2 = jax.random.split(rng)
        return {"w": jax.random.normal(k1, (HIDDEN, HIDDEN),
                                       jnp.float32) * 0.3,
                "b": jnp.zeros((HIDDEN,), jnp.float32)}

    def apply(self, params, x, rng=None):
        return x + jnp.tanh(x @ params["w"] + params["b"])


class HeadLayer(PipeLayer):
    def __init__(self, hidden=HIDDEN, out_dim=OUT_DIM):
        self.hidden, self.out_dim = hidden, out_dim

    def init_params(self, rng, x):
        return {"w": jax.random.normal(rng, (self.hidden, self.out_dim),
                                       jnp.float32) * 0.5}

    def apply(self, params, x, rng=None):
        return x @ params["w"]


def mse_loss(pred, target):
    return jnp.mean((pred - target.astype(pred.dtype)) ** 2)


def make_module(n_blocks=4, num_stages=None):
    layers = [LayerSpec(EmbedLayer)] + \
        [LayerSpec(Block) for _ in range(n_blocks)] + [LayerSpec(HeadLayer)]
    return PipelineModule(layers, num_stages=num_stages, loss_fn=mse_loss)


def make_data(n, rng_seed=0):
    rs = np.random.RandomState(rng_seed)
    w = rs.randn(IN_DIM, OUT_DIM).astype(np.float32)
    x = rs.randn(n, IN_DIM).astype(np.float32)
    y = x @ w
    return x, y


CONFIG = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "gradient_accumulation_steps": 4,
    "steps_per_print": 100,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "mesh": {"pipe": 4, "data": -1},
}


def _engine(n_blocks=4, config=None):
    deepspeed_tpu.initialize_mesh(pipe=4, data=-1)
    module = make_module(n_blocks=n_blocks)
    cfg = dict(config or CONFIG)
    example = jnp.zeros((4, IN_DIM), jnp.float32)  # one global microbatch
    return PipelineEngine(model=module, config=cfg,
                          example_input=example,
                          rng=jax.random.PRNGKey(0))


def _batch_iter(x, y, micro_global):
    i = 0
    while True:
        xs = x[i:i + micro_global]
        ys = y[i:i + micro_global]
        if len(xs) < micro_global:
            i = 0
            continue
        i += micro_global
        yield (xs, ys)


class TestPipelineModule:
    def test_body_detection(self):
        module = make_module(n_blocks=4, num_stages=4)
        params = module.build(jax.random.PRNGKey(0),
                              jnp.zeros((4, IN_DIM), jnp.float32))
        assert module.body_range == (1, 5)
        leaf = jax.tree.leaves(params["blocks"])[0]
        assert leaf.shape[:2] == (4, 1)
        assert len(params["pre"]) == 1
        assert len(params["post"]) == 1

    def test_indivisible_body_raises(self):
        module = make_module(n_blocks=5, num_stages=4)
        with pytest.raises(ValueError, match="not\\s+divisible"):
            module.build(jax.random.PRNGKey(0),
                         jnp.zeros((4, IN_DIM), jnp.float32))

    def test_tied_layers_share_params(self):
        layers = [
            TiedLayerSpec("emb", EmbedLayer),
            LayerSpec(Block), LayerSpec(Block),
            TiedLayerSpec("emb", EmbedLayer,
                          forward_fn=lambda p, x: x @ p["w"].T),
        ]
        module = PipelineModule(layers, num_stages=2, loss_fn=mse_loss)
        params = module.build(jax.random.PRNGKey(0),
                              jnp.zeros((4, IN_DIM), jnp.float32))
        assert "emb" in params["tied"]
        assert params["pre"] == [None]
        assert params["post"] == [None]
        # forward through chain_apply uses the tied weight both times
        x = jnp.ones((4, IN_DIM), jnp.float32)
        h = module.chain_apply(range(0, 1), params["pre"], params["tied"], x)
        assert h.shape == (4, HIDDEN)
        out = module.chain_apply(range(3, 4), params["post"], params["tied"], h)
        assert out.shape == (4, IN_DIM)


from tests.unit.seed_xfails import (  # noqa: E402 — marker for the triaged seed failures
    PARTITION_ID_XFAIL as _PARTITION_ID_XFAIL)


class TestPipelineEngine:
    @_PARTITION_ID_XFAIL
    def test_parity_with_sequential(self):
        """The pipelined program computes exactly what the sequential layer
        chain computes."""
        engine = _engine()
        params = jax.device_get(engine.params)
        x, y = make_data(16, rng_seed=1)

        loss_pipe = float(engine.forward(x, y))

        # sequential reference: same params, plain layer chain
        M = engine.micro_batches
        xm = x.reshape(M, -1, IN_DIM)
        ym = y.reshape(M, -1, OUT_DIM)
        blocks = params["blocks"]
        total = 0.0
        for m in range(M):
            h = xm[m] @ params["pre"][0]["w"]
            S, k = jax.tree.leaves(blocks)[0].shape[:2]
            for s in range(S):
                for j in range(k):
                    lp = jax.tree.map(lambda a: a[s, j], blocks)
                    h = h + jnp.tanh(h @ lp["w"] + lp["b"])
            pred = h @ params["post"][0]["w"]
            total += float(mse_loss(pred, ym[m]))
        assert loss_pipe == pytest.approx(total / M, rel=1e-4)

    @_PARTITION_ID_XFAIL
    def test_train_batch_convergence(self):
        engine = _engine()
        x, y = make_data(256, rng_seed=2)
        it = _batch_iter(x, y, micro_global=4)
        losses = [engine.train_batch(it) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.5, losses
        assert engine.global_steps == 30

    def test_eval_batch(self):
        engine = _engine()
        x, y = make_data(16, rng_seed=3)
        loss = engine.eval_batch(_batch_iter(x, y, micro_global=4))
        assert np.isfinite(loss)

    @_PARTITION_ID_XFAIL
    def test_checkpoint_roundtrip(self, tmp_path):
        engine = _engine()
        x, y = make_data(64, rng_seed=4)
        it = _batch_iter(x, y, micro_global=4)
        for _ in range(3):
            engine.train_batch(it)
        engine.save_checkpoint(str(tmp_path), tag="pipe_test")

        engine2 = _engine()
        engine2.load_checkpoint(str(tmp_path), tag="pipe_test")
        assert engine2.global_steps == 3
        p1 = jax.tree.leaves(jax.device_get(engine.params))
        p2 = jax.tree.leaves(jax.device_get(engine2.params))
        for a, b in zip(p1, p2):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_block_params_sharded_over_pipe(self):
        engine = _engine()
        leaf = jax.tree.leaves(engine.params["blocks"])[0]
        spec = leaf.sharding.spec
        assert spec[0] == "pipe"
