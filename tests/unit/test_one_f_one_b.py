"""1F1B pipeline executor (pipe/one_f_one_b.py): schedule simulation
invariants, trajectory equality vs the GPipe executor, and the 1F1B memory
property asserted on the compiled program.

Reference: runtime/pipe/engine.py:1209 _exec_schedule + schedule.py:182
TrainSchedule — the repo executes the same declarative schedule as static
tick tables inside one compiled scan.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
from deepspeed_tpu.runtime.pipe.one_f_one_b import simulate_global_clock
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_pipe import CONFIG, make_data, make_module  # noqa: E402


@pytest.mark.parametrize("M,S", [(4, 2), (8, 4), (4, 4), (2, 4), (16, 4),
                                 (8, 8), (1, 4), (3, 3), (4, 1)])
def test_global_clock_executes_full_schedule(M, S):
    t = simulate_global_clock(M, S)
    # every (stage, microbatch) forward and backward executed exactly once
    assert t.fwd_active.sum() == M * S
    assert t.bwd_active.sum() == M * S
    # per-stage order: each tick consumes the next ops of TrainSchedule's
    # own 1F1B compute order (a tick's fwd+bwd pair may run in either lane
    # order — they are schedule-adjacent and independent)
    for s in range(S):
        ops = list(TrainSchedule(M, S, s)._compute_order())
        ptr = 0
        for tt in range(t.num_ticks):
            tick_ops = set()
            if t.fwd_active[tt, s]:
                tick_ops.add(("fwd", int(t.fwd_mb[tt, s])))
            if t.bwd_active[tt, s]:
                tick_ops.add(("bwd", int(t.bwd_mb[tt, s])))
            expect = set(ops[ptr:ptr + len(tick_ops)])
            assert tick_ops == expect, (s, tt, tick_ops, expect)
            ptr += len(tick_ops)
        assert ptr == len(ops)


@pytest.mark.parametrize("M,S", [(8, 4), (16, 4), (32, 4), (8, 8)])
def test_live_set_independent_of_microbatches(M, S):
    """The rotating store needs O(S) slots per stage, never O(M)."""
    t = simulate_global_clock(M, S)
    assert t.max_slots <= S + 1
    # deeper stages hold fewer in-flight microbatches (warmup+1 shape)
    assert list(t.slot_counts) == sorted(t.slot_counts, reverse=True)


def _train(schedule, steps=4, gated=True):
    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=4, data=-1)
    module = make_module(n_blocks=4)
    x, y = make_data(64)
    cfg = dict(CONFIG)
    cfg["pipeline"] = {"gated": gated}
    engine = PipelineEngine(
        model=module, config=cfg, schedule=schedule,
        example_input=jnp.zeros((4, x.shape[1]), jnp.float32),
        rng=jax.random.PRNGKey(3))
    losses = []
    for i in range(steps):
        # DISTINCT microbatches each step — cross-microbatch activation
        # mix-ups in the executor must show up as a trajectory divergence
        micro = [(x[j * 4:(j + 1) * 4], y[j * 4:(j + 1) * 4])
                 for j in range(i * 4, i * 4 + 4)]
        losses.append(engine.train_batch(iter(micro)))
    params = jax.tree.map(np.asarray, engine.params)
    deepspeed_tpu.reset_mesh_context()
    return losses, params


def test_1f1b_matches_gpipe_trajectory():
    l_g, p_g = _train("gpipe")
    l_f, p_f = _train("1f1b")  # gated executor (the default)
    np.testing.assert_allclose(l_f, l_g, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_f), jax.tree.leaves(p_g)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


from tests.unit.seed_xfails import (  # noqa: E402 — marker for the triaged seed failures
    PARTITION_ID_XFAIL as _PARTITION_ID_XFAIL)


@_PARTITION_ID_XFAIL
def test_gated_matches_masked_trajectory():
    """The gated (lax.cond under shard_map) and masked (branch-free)
    executors run the same schedule — full-trajectory equality keeps the
    fallback honest."""
    l_m, p_m = _train("1f1b", gated=False)
    l_g, p_g = _train("1f1b", gated=True)
    np.testing.assert_allclose(l_g, l_m, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_g), jax.tree.leaves(p_m)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def _compiled_temp_bytes(schedule, micro_batches):
    """Temp (activation/workspace) bytes of the compiled grad program."""
    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=4, data=-1)
    cfg = dict(CONFIG)
    cfg["gradient_accumulation_steps"] = micro_batches
    cfg["train_batch_size"] = 2 * 2 * micro_batches
    module = make_module(n_blocks=4)
    engine = PipelineEngine(
        model=module, config=cfg, schedule=schedule,
        example_input=jnp.zeros((4, 8), jnp.float32),
        rng=jax.random.PRNGKey(3))
    x = jnp.zeros((4 * micro_batches, 8), jnp.float32)
    y = jnp.zeros((4 * micro_batches, 8), jnp.float32)
    (xs, ys), _ = engine._shard_batch(((x, y), {}))
    lowered = engine._grad_fn.lower(engine.params, engine.scaler_state,
                                    jax.random.PRNGKey(0), xs, ys)
    stats = lowered.compile().memory_analysis()
    deepspeed_tpu.reset_mesh_context()
    return int(stats.temp_size_in_bytes)


def test_1f1b_memory_does_not_scale_with_microbatches():
    """THE 1F1B property: peak live activation memory is bounded by the
    warmup depth, not the microbatch count (reference schedule.py:192
    num_pipe_buffers).  GPipe's grows linearly with M."""
    m4 = _compiled_temp_bytes("1f1b", 4)
    m16 = _compiled_temp_bytes("1f1b", 16)
    # 4x the microbatches must cost well under 2x the temp memory
    assert m16 < 2 * m4, (m4, m16)

    g4 = _compiled_temp_bytes("gpipe", 4)
    g16 = _compiled_temp_bytes("gpipe", 16)
    # and the GPipe executor demonstrably scales with M (sanity check that
    # the measurement sees what we claim it sees)
    assert g16 > 2 * g4, (g4, g16)


def test_schedule_efficiency_quantified():
    """The masked-idle-work accounting (VERDICT r2 weak #8): every useful
    cell is counted exactly once, the clock tracks the textbook critical
    path, and utilization degrades exactly as the schedule predicts."""
    from deepspeed_tpu.runtime.pipe.one_f_one_b import (schedule_efficiency,
                                                        simulate_global_clock)

    for M, S in [(4, 4), (8, 4), (32, 4), (4, 8)]:
        eff = schedule_efficiency(simulate_global_clock(M, S))
        assert eff["useful_fwd"] == M * S
        assert eff["useful_bwd"] == M * S
        # measured clock law: T ~ 1.5*M + 2*(S-1) - 1 (+/- a tick)
        expect = 1.5 * M + 2 * (S - 1) - 1
        assert abs(eff["ticks"] - expect) <= 2, (M, S, eff["ticks"])
        assert eff["lane_utilization"] == pytest.approx(
            M / eff["ticks"], rel=1e-9)
    # the M >> S regime the executor targets: utilization approaches the
    # 2/3 asymptote as M grows
    big = schedule_efficiency(simulate_global_clock(64, 4))
    assert big["lane_utilization"] > 0.6


def test_gated_with_tensor_parallel_guard():
    """Explicit gated=true under TP with a body that has NO manual-TP
    mode (test_pipe's plain Block declares only GSPMD specs) must be a
    loud config error (GSPMD would put the TP collectives inside the
    divergent branches — deadlock), and the default must silently select
    the masked executor there.  Bodies WITH the explicit-collective mode
    (GPT2BlockPipe) gate under TP — test_gated_tp_manual_default."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_pipe import CONFIG, make_module

    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    cfg = dict(CONFIG)
    cfg["pipeline"] = {"gated": True}
    with pytest.raises(ValueError, match="gated"):
        PipelineEngine(
            model=make_module(n_blocks=4), config=cfg, schedule="1f1b",
            example_input=jnp.zeros((4, 8), jnp.float32),
            rng=jax.random.PRNGKey(3))
    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    engine = PipelineEngine(
        model=make_module(n_blocks=4), config=dict(CONFIG),
        schedule="1f1b",
        example_input=jnp.zeros((4, 8), jnp.float32),
        rng=jax.random.PRNGKey(3))
    assert engine.schedule_gated is False
    deepspeed_tpu.reset_mesh_context()


@_PARTITION_ID_XFAIL
def test_gated_tp_manual_default():
    """pipe×model with a manual-TP-capable body (GPT2BlockPipe) defaults
    to the GATED executor — the round-4 explicit-collective Megatron
    split keeps the TP psums inside uniform-predicate branches, so the
    GSPMD-auto deadlock mechanism never arises.  One train_batch runs as
    the deadlock regression check; trajectory equality vs the pipe=1/tp=1
    baseline is test_3d_matrix's job."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module

    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=4, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    conf = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 10 ** 9,
        # explicit gated=true must be ACCEPTED on this mesh (the guard
        # only fires for bodies without apply_manual_tp)
        "pipeline": {"gated": True},
    }
    engine = PipelineEngine(
        model=gpt2_pipeline_module(cfg, num_stages=2), config=conf,
        example_input=jnp.zeros((4, 16), jnp.int32),
        rng=jax.random.PRNGKey(0))
    assert engine.schedule_gated is True
    assert engine._tp_manual is True
    # vocab-parallel aux chains active (vocab 64 divides tp 2): the
    # embedding lookup and head+CE run vocab-sharded, not replicated
    assert engine._tp_aux_manual is True
    ids = np.random.RandomState(0).randint(0, 64, size=(4, 16)).astype(
        np.int32)
    loss = engine.train_batch(iter([(ids, ids), (ids, ids)]))
    assert np.isfinite(loss)
    deepspeed_tpu.reset_mesh_context()

    # dropout ON must also trace and run: the manual mode folds
    # lax.axis_index(model) into the attention-dropout key (head-shard
    # decorrelation) — a trace-time failure there would only surface in
    # real training configs
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    cfg_do = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                        num_layers=4, num_heads=4, bf16=False,
                        embd_dropout=0.1, attn_dropout=0.1,
                        hidden_dropout=0.1)
    engine2 = PipelineEngine(
        model=gpt2_pipeline_module(cfg_do, num_stages=2), config=conf,
        example_input=jnp.zeros((4, 16), jnp.int32),
        rng=jax.random.PRNGKey(0))
    assert engine2.schedule_gated is True
    loss2 = engine2.train_batch(iter([(ids, ids), (ids, ids)]))
    assert np.isfinite(loss2)
    deepspeed_tpu.reset_mesh_context()


def test_gated_tp_config_level_fallbacks():
    """The gated-manual default must be a CONFIG-level decision, not a
    type-level one (round-4 review): a sparse-attention body (layouts
    built for global head counts) and a heads-indivisible body must both
    fall back to the masked executor, and explicit gated=true must be a
    clean ValueError — not an AttributeError or a shard_map crash."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

    def build(cfg, gated=None):
        conf = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10 ** 9,
        }
        if gated is not None:
            conf["pipeline"] = {"gated": gated}
        return PipelineEngine(
            model=gpt2_pipeline_module(cfg, num_stages=2), config=conf,
            example_input=jnp.zeros((4, 32), jnp.int32),
            rng=jax.random.PRNGKey(0))

    sparse_cfg = GPT2Config(
        vocab_size=64, n_positions=32, hidden_size=32, num_layers=4,
        num_heads=4, bf16=False, embd_dropout=0.0, attn_dropout=0.0,
        hidden_dropout=0.0,
        sparse_attention=FixedSparsityConfig(num_heads=4, block=16))
    odd_heads_cfg = GPT2Config(
        vocab_size=64, n_positions=32, hidden_size=24, num_layers=4,
        num_heads=3, bf16=False, embd_dropout=0.0, attn_dropout=0.0,
        hidden_dropout=0.0)
    for cfg in (sparse_cfg, odd_heads_cfg):
        deepspeed_tpu.reset_mesh_context()
        deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
        engine = build(cfg)
        assert engine.schedule_gated is False, cfg
        assert engine._tp_manual is False
        deepspeed_tpu.reset_mesh_context()
        deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
        with pytest.raises(ValueError, match="manual TP"):
            build(cfg, gated=True)
        deepspeed_tpu.reset_mesh_context()


def test_gated_tp_partial_api_body_falls_back():
    """A body implementing only part of the manual-TP API must hit the
    guard (masked fallback / clean error), not an AttributeError inside
    _make_1f1b_program."""
    from deepspeed_tpu.runtime.pipe.module import (LayerSpec,
                                                   PipelineModule)
    from test_pipe import EmbedLayer, HeadLayer, Block, mse_loss

    class HalfManualBlock(Block):
        def apply_manual_tp(self, params, x, rng=None, tp_axis=None):
            return self.apply(params, x, rng)

        def tp_manual_views(self, params):
            return params
        # tp_manual_unview / tp_manual_view_specs MISSING on purpose

    module = PipelineModule(
        [LayerSpec(EmbedLayer)] + [LayerSpec(HalfManualBlock)
                                   for _ in range(4)] +
        [LayerSpec(HeadLayer)], num_stages=2, loss_fn=mse_loss)
    deepspeed_tpu.reset_mesh_context()
    deepspeed_tpu.initialize_mesh(pipe=2, model=2, data=-1)
    cfg = dict(CONFIG)
    cfg["mesh"] = {"pipe": 2, "model": 2, "data": -1}
    cfg["pipeline"] = {"gated": True}
    with pytest.raises(ValueError, match="gated"):
        PipelineEngine(model=module, config=cfg, schedule="1f1b",
                       example_input=jnp.zeros((4, 8), jnp.float32),
                       rng=jax.random.PRNGKey(3))
    deepspeed_tpu.reset_mesh_context()


def test_gated_executor_efficiency():
    """VERDICT r3 #4 done-criterion: the gated executor's executed work
    is within 1.1x of useful at (M=8, S=4) — in fact exactly 1.0x, since
    lax.cond skips inactive cells instead of masking them."""
    from deepspeed_tpu.runtime.pipe.one_f_one_b import (schedule_efficiency,
                                                        simulate_global_clock)

    for M, S in [(8, 4), (4, 8), (32, 4)]:
        eff = schedule_efficiency(simulate_global_clock(M, S), gated=True)
        executed = eff["executed_fwd"] + eff["executed_bwd"]
        useful = eff["useful_fwd"] + eff["useful_bwd"]
        assert executed / useful <= 1.1, (M, S, executed, useful)
        assert eff["executed_over_useful"] <= 1.1
        # aux chains amortize to one execution per microbatch
        assert eff["aux_chain_ticks"] == M
        # the masked path really is the ~1.5x the gated one eliminates
        masked = schedule_efficiency(simulate_global_clock(M, S))
        assert masked["executed_over_useful"] > 1.4
