"""Flops profiler, 1-bit optimizers, launcher, state-dict factory,
env report (reference tests: test_flops_profiler.py:115, test_onebit.py,
test_run.py:108 launcher arg parsing, test_configurable_parallel.py MP
resize)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds


# ---------------------------------------------------------------------- #
# flops profiler
# ---------------------------------------------------------------------- #
def test_flops_count_matmul_exact():
    from deepspeed_tpu.profiling import get_model_profile

    def f(a, b):
        return a @ b

    a = jnp.zeros((64, 128))
    b = jnp.zeros((128, 32))
    flops, macs, _ = get_model_profile(f, (a, b))
    assert macs == 64 * 128 * 32
    assert flops >= 2 * macs


def test_flops_scan_multiplies():
    from deepspeed_tpu.profiling import get_model_profile

    w = jnp.zeros((4, 16, 16))

    def stacked(x):
        def body(c, wi):
            return c @ wi, None
        out, _ = jax.lax.scan(body, x, w)
        return out

    flops, macs, _ = get_model_profile(stacked, (jnp.zeros((8, 16)),))
    assert macs == 4 * 8 * 16 * 16  # scan length multiplies the body


def test_profiler_on_gpt2_matches_analytic():
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.profiling import get_model_profile

    cfg = GPT2Config(vocab_size=256, n_positions=64, hidden_size=64,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.zeros((2, 32), jnp.int32)
    flops, macs, n_params = get_model_profile(
        lambda p: model.loss(p, None, ids), (params,), params=params)
    assert n_params == cfg.num_params()
    # forward MACs ~ tokens * (2N_layer + head) — sanity band, not exact
    tokens = 2 * 32
    rough = tokens * cfg.num_params(include_embeddings=False)
    assert 0.5 * rough < macs < 6 * rough


def test_module_tree_attention_matches_analytic():
    """Per-module tree (round 5 — the reference's module-hierarchy dump,
    profiler.py:11): the layer/attn scope must carry the analytic
    attention FLOPs (qkv + scores + ctx + out-proj) within the
    elementwise slack, and the printed profile must show the hierarchy."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.profiling import FlopsProfiler

    B, S, H, L = 2, 128, 64, 3
    cfg = GPT2Config(vocab_size=512, n_positions=S, hidden_size=H,
                     num_layers=L, num_heads=4, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jnp.zeros((B, S), jnp.int32)

    prof = FlopsProfiler()
    prof.set_params(params)
    prof.start_profile()
    prof.profile_fn(lambda p: model.loss(p, None, ids), params)
    prof.stop_profile()

    tree = prof.module_tree()
    # embed / layer / head all present, layer split into attn + mlp
    for key in ("embed", "layer", "head", "layer/attn", "layer/mlp"):
        assert key in tree and tree[key] > 0, (key, sorted(tree))
    # attention: qkv (6BSH^2) + scores/ctx (4BS^2H) + out-proj (2BSH^2)
    analytic_attn = L * (8 * B * S * H * H + 4 * B * S * S * H)
    assert abs(tree["layer/attn"] - analytic_attn) / analytic_attn < 0.10
    # mlp: 2 matmuls of [S,H]x[H,4H] per layer = 16BSH^2
    analytic_mlp = L * 16 * B * S * H * H
    assert abs(tree["layer/mlp"] - analytic_mlp) / analytic_mlp < 0.10
    # hierarchy: the layer scope contains its children
    assert tree["layer"] >= tree["layer/attn"] + tree["layer/mlp"]

    import tempfile

    with tempfile.NamedTemporaryFile("r", suffix=".txt") as f:
        prof.print_model_profile(detailed=True, top_modules=4,
                                 output_file=f.name)
        out = open(f.name).read()
    assert "per-module tree" in out
    assert "layer/attn" in out and "layer/mlp" in out


def test_engine_flops_profiler_integration(capsys):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    params = {"w": np.zeros((8, 4), np.float32)}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "flops_profiler": {"enabled": True, "profile_step": 1},
        "steps_per_print": 10 ** 9,
    }
    eng, _, _, _ = ds.initialize(model=model, config=cfg,
                                 model_parameters=params, mesh=mesh)
    x = np.zeros((8, 8), np.float32)
    y = np.zeros((8, 4), np.float32)
    for _ in range(3):
        loss = eng.forward(x, y); eng.backward(loss); eng.step()
    assert getattr(eng, "flops_profiler", None) is not None
    assert eng.flops_profiler.flops > 0
    assert eng.flops_profiler.params == 32


# ---------------------------------------------------------------------- #
# 1-bit optimizers
# ---------------------------------------------------------------------- #
def test_onebit_adam_matches_adam_during_warmup():
    import optax
    from deepspeed_tpu.runtime.comm.onebit import onebit_adam

    params = {"w": jnp.ones((8,)) * 0.5}
    tx1 = onebit_adam(0.1, freeze_step=100)
    tx2 = optax.adam(0.1)
    s1, s2 = tx1.init(params), tx2.init(params)
    p1 = p2 = params
    for i in range(5):
        g = {"w": jnp.sin(jnp.arange(8.0) + i)}
        u1, s1 = tx1.update(g, s1, p1)
        u2, s2 = tx2.update(g, s2, p2)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-5)


def test_onebit_adam_converges_after_freeze():
    import optax
    from deepspeed_tpu.runtime.comm.onebit import onebit_adam

    target = jnp.asarray(np.random.RandomState(0).randn(16), jnp.float32)
    params = {"w": jnp.zeros((16,))}
    tx = onebit_adam(0.05, freeze_step=10)
    state = tx.init(params)

    def loss(p):
        return jnp.mean((p["w"] - target) ** 2)

    for i in range(120):
        g = jax.grad(loss)(params)
        u, state = tx.update(g, state, params)
        params = optax.apply_updates(params, u)
    assert float(loss(params)) < 0.05  # compressed stage still converges
    assert int(state.count) == 120


def test_compressed_allreduce_error_feedback():
    from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
    from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce

    reset_mesh_context()
    mesh = initialize_mesh(data=-1)
    w = mesh.data_parallel_world_size
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(w, 64), jnp.float32)  # per-worker rows
    err = jnp.zeros_like(x)
    true_mean = np.asarray(x).mean(axis=0)

    # repeated reduction of the same tensors: error feedback must drive the
    # accumulated average toward the true mean (1-bit Adam's core property,
    # bias ~ O(1/n)); check the error actually SHRINKS with more rounds.
    def avg_err(n):
        acc = np.zeros(64)
        e = err
        for _ in range(n):
            red, e = compressed_allreduce(x, e, mesh_ctx=mesh)
            acc += np.asarray(red)[0]
        return np.abs(acc / n - true_mean).max()

    e8, e64 = avg_err(8), avg_err(64)
    assert e64 < e8 / 2, (e8, e64)
    assert e64 < 0.25, e64
    # a single uncompensated round is much worse than the 64-round average
    single = np.abs(np.asarray(compressed_allreduce(
        x, jnp.zeros_like(x), mesh_ctx=mesh)[0])[0] - true_mean).max()
    assert e64 < single
    reset_mesh_context()


def test_compressed_allreduce_int8_wire():
    """The int8 wire format (shared scale, sign rides as int8 — the
    variant with an actual 4x wire-width win, benchmarks/onebit_cost.py)
    keeps the error-feedback convergence property and stays close to the
    full-width variant."""
    from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
    from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce

    reset_mesh_context()
    mesh = initialize_mesh(data=-1)
    w = mesh.data_parallel_world_size
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(w, 64), jnp.float32)
    true_mean = np.asarray(x).mean(axis=0)

    def avg_err(n):
        acc = np.zeros(64)
        e = jnp.zeros_like(x)
        for _ in range(n):
            red, e = compressed_allreduce(x, e, mesh_ctx=mesh, wire="int8")
            acc += np.asarray(red)[0]
        return np.abs(acc / n - true_mean).max()

    e8, e64 = avg_err(8), avg_err(64)
    assert e64 < e8 / 2, (e8, e64)
    assert e64 < 0.3, e64
    # every worker sees the identical reduced tensor (psum symmetry)
    red, _ = compressed_allreduce(x, jnp.zeros_like(x), mesh_ctx=mesh,
                                  wire="int8")
    red = np.asarray(red)
    np.testing.assert_array_equal(red[0], red[-1])
    reset_mesh_context()


def test_engine_accepts_onebit_adam():
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    params = {"w": np.random.RandomState(0).randn(8, 4).astype(np.float32)}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-2, "freeze_step": 2}},
        "steps_per_print": 10 ** 9,
    }
    eng, _, _, _ = ds.initialize(model=model, config=cfg,
                                 model_parameters=params, mesh=mesh)
    rs = np.random.RandomState(1)
    x, y = rs.randn(8, 8).astype(np.float32), rs.randn(8, 4).astype(
        np.float32)
    losses = []
    for _ in range(8):
        loss = eng.forward(x, y); eng.backward(loss); eng.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------- #
# launcher
# ---------------------------------------------------------------------- #
def test_hostfile_parse_and_filter(tmp_path):
    from deepspeed_tpu.launcher.runner import (fetch_hostfile,
                                               parse_resource_filter)
    hf = tmp_path / "hostfile"
    hf.write_text("# comment\nworker-0 slots=4\nworker-1 slots=4\n"
                  "worker-2 slots=8\n")
    res = fetch_hostfile(str(hf))
    assert list(res) == ["worker-0", "worker-1", "worker-2"]
    assert res["worker-2"] == 8

    inc = parse_resource_filter(res, include_str="worker-0@worker-2:0,1")
    assert list(inc) == ["worker-0", "worker-2"]
    assert inc["worker-2"] == [0, 1]

    exc = parse_resource_filter(res, exclude_str="worker-1")
    assert list(exc) == ["worker-0", "worker-2"]

    with pytest.raises(ValueError):
        parse_resource_filter(res, include_str="a", exclude_str="b")
    with pytest.raises(ValueError):
        parse_resource_filter(res, include_str="missing-host")


def test_launcher_dry_run_emits_env(tmp_path, capsys):
    from deepspeed_tpu.launcher.runner import main
    hf = tmp_path / "hostfile"
    hf.write_text("nodeA slots=4\nnodeB slots=4\n")
    rc = main(["--hostfile", str(hf), "--master_port", "12345",
               "--dry_run", "train.py", "--foo", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ssh" in out and "nodeB" in out
    assert "DS_COORDINATOR=nodeA:12345" in out
    assert "DS_NUM_PROCESSES=2" in out
    assert "DS_PROCESS_ID=1" in out
    assert "train.py --foo 1" in out


def test_world_info_roundtrip():
    from deepspeed_tpu.launcher.runner import (decode_world_info,
                                               encode_world_info)
    info = {"a": [0, 1], "b": [0]}
    assert decode_world_info(encode_world_info(info)) == info


def test_env_report_runs():
    from deepspeed_tpu.env_report import get_report_lines
    lines = get_report_lines()
    text = "\n".join(lines)
    assert "cpu_adam" in text and "async_io" in text and "jax" in text


# ---------------------------------------------------------------------- #
# state-dict factory (MP resize)
# ---------------------------------------------------------------------- #
def test_qkv_split_merge_roundtrip():
    from deepspeed_tpu.runtime.state_dict_factory import merge_qkv, split_qkv
    qkv = np.arange(4 * 12, dtype=np.float32).reshape(4, 12)  # H=4, 3H=12
    shards = split_qkv(qkv, mp=2)
    assert shards[0].shape == (4, 6)
    # each shard holds its half of q, k, AND v — not the naive first half
    np.testing.assert_array_equal(shards[0][:, :2], qkv[:, 0:2])   # q half
    np.testing.assert_array_equal(shards[0][:, 2:4], qkv[:, 4:6])  # k half
    np.testing.assert_array_equal(shards[0][:, 4:6], qkv[:, 8:10])  # v half
    np.testing.assert_array_equal(merge_qkv(shards), qkv)


def test_mp_resize_2_to_4(tmp_path):
    """Save at mp=2, reload at mp=4 (reference:
    test_configurable_parallel.py:458)."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.runtime.state_dict_factory import (
        MegatronSDLoader, SDLoaderFactory, merge_state_dicts,
        split_state_dict)

    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False)
    model = GPT2Model(cfg)
    params = jax.tree.map(np.asarray,
                          model.init_params(jax.random.PRNGKey(0)))
    specs = model.param_partition_specs()

    # split -> per-rank files -> reload merged at a different degree
    paths = MegatronSDLoader.save_shards(
        params, specs, 2, str(tmp_path / "mp_rank_{:02d}.npz"))
    loader = SDLoaderFactory.get_sd_loader(paths)
    rank0_of_4 = loader.load(4, 0, specs, params)
    full = loader.load(1, 0, specs, params)
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, b)
    # mp=4 shard has quarter-width qkv columns
    assert rank0_of_4["h"]["attn_qkvw"].shape[-1] == \
        params["h"]["attn_qkvw"].shape[-1] // 4
    # splitting then merging is identity
    again = merge_state_dicts(split_state_dict(params, specs, 4), specs)
    for a, b in zip(jax.tree.leaves(again), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, b)


def test_init_distributed_single_process(monkeypatch):
    from deepspeed_tpu.utils import distributed as dist_mod
    monkeypatch.setattr(dist_mod, "_INITIALIZED", False)
    for var in ("DS_COORDINATOR", "MASTER_ADDR", "RANK"):
        monkeypatch.delenv(var, raising=False)
    dist_mod.init_distributed()  # no env: single-process no-op
    assert dist_mod._INITIALIZED


def test_ds_ssh_local_fallback(tmp_path, capsys):
    """ds_ssh (reference: bin/ds_ssh): no hostfile -> run locally; with a
    hostfile it fans out over ssh/pdsh (not exercisable here)."""
    from deepspeed_tpu.launcher.ds_ssh import build_parser, main

    rc = main(["-H", str(tmp_path / "none"), "echo", "hello_ds_ssh"])
    assert rc == 0
    # parser surfaces the hostfile flag and trailing command
    args = build_parser().parse_args(["-H", "hf", "uptime", "-a"])
    assert args.hostfile == "hf" and args.command == ["uptime", "-a"]


# --------------------------------------------------------------------- #
# TPU-pod launcher discovery (round 5 — the multinode_runner.py:35
# family's TPU form, launcher/tpu_discovery.py)
# --------------------------------------------------------------------- #
def test_tpu_metadata_discovery_mocked():
    from deepspeed_tpu.launcher.tpu_discovery import discover_from_metadata

    meta = {
        "worker-network-endpoints":
            "8833c7a:10.164.0.2:8470,9b01d22:10.164.0.3:8470,"
            "77aa001:10.164.0.4:8470,45cc9ef:10.164.0.5:8470",
        "agent-worker-number": "2",
        "accelerator-type": "v5litepod-16",
    }
    pod = discover_from_metadata(fetch=lambda attr: meta[attr])
    assert pod.workers == ["10.164.0.2", "10.164.0.3",
                           "10.164.0.4", "10.164.0.5"]
    assert pod.my_index == 2
    assert pod.accelerator_type == "v5litepod-16"
    assert list(pod.resources().items()) == [
        ("10.164.0.2", 1), ("10.164.0.3", 1),
        ("10.164.0.4", 1), ("10.164.0.5", 1)]


def test_tpu_metadata_discovery_bad_payload():
    import pytest as _pytest

    from deepspeed_tpu.launcher.tpu_discovery import discover_from_metadata

    with _pytest.raises(RuntimeError, match="no worker IPs"):
        discover_from_metadata(fetch=lambda attr: "not-an-endpoint-list")


def test_tpu_metadata_missing_worker_number():
    """Absent agent-worker-number: unknowable on a multi-worker pod
    (None — never a silent worker-0 claim), trivially 0 on one worker."""
    from deepspeed_tpu.launcher.tpu_discovery import discover_from_metadata

    multi = {"worker-network-endpoints": "a:10.0.0.1:1,b:10.0.0.2:1"}
    pod = discover_from_metadata(fetch=lambda a: multi[a])
    assert pod.my_index is None
    single = {"worker-network-endpoints": "a:10.0.0.1:1"}
    pod = discover_from_metadata(fetch=lambda a: single[a])
    assert pod.my_index == 0


def test_tpu_gcloud_discovery_mocked():
    import json as _json
    import subprocess as _sp

    from deepspeed_tpu.launcher.tpu_discovery import discover_from_gcloud

    desc = {
        "acceleratorType": "v4-16",
        "networkEndpoints": [
            # external IP preferred (off-pod launches can't route 10.x);
            # internal is the in-VPC fallback
            {"ipAddress": "10.130.0.9",
             "accessConfig": {"externalIp": "10.130.0.10"}},
            {"ipAddress": "10.130.0.11"},
        ],
    }
    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)
        return _sp.CompletedProcess(cmd, 0, stdout=_json.dumps(desc),
                                    stderr="")

    pod = discover_from_gcloud("my-pod", zone="us-central2-b",
                               project="proj", run=fake_run)
    assert pod.workers == ["10.130.0.10", "10.130.0.11"]
    assert pod.accelerator_type == "v4-16"
    assert calls[0][:6] == ["gcloud", "compute", "tpus", "tpu-vm",
                            "describe", "my-pod"]
    assert "--zone" in calls[0] and "us-central2-b" in calls[0]


def test_dslaunch_tpu_dry_run(monkeypatch, capsys, tmp_path):
    """dslaunch --tpu <name> end-to-end (dry run): discovery feeds the
    per-host ssh commands, coordinator = worker 0."""
    from deepspeed_tpu.launcher import runner, tpu_discovery

    pod = tpu_discovery.PodInfo(
        workers=["10.0.0.5", "10.0.0.6"], my_index=None,
        accelerator_type="v5litepod-8")
    monkeypatch.setattr(tpu_discovery, "discover",
                        lambda *a, **k: pod)
    script = tmp_path / "train.py"
    script.write_text("pass\n")
    rc = runner.main(["--tpu", "my-pod", "--dry_run", str(script)])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    assert "ssh" in out[0] and "10.0.0.5" in out[0]
    assert "DS_COORDINATOR=10.0.0.5:29500" in out[0]
    assert "DS_NUM_PROCESSES=2" in out[1] and "DS_PROCESS_ID=1" in out[1]
