"""zero.Init / GatheredParameters / TiledLinear, runtime utils, memory
estimators (reference tests: test_zero_context.py:362 Init semantics,
zero/tiling.py, stage2.py:2141 estimators)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
from deepspeed_tpu.runtime.utils import (clip_grad_norm_,
                                         estimate_zero2_model_states_mem_needs,
                                         estimate_zero3_model_states_mem_needs,
                                         global_grad_norm, partition_balanced,
                                         partition_uniform, see_memory_usage)


@pytest.fixture
def mesh8():
    reset_mesh_context()
    yield initialize_mesh(data=-1)
    reset_mesh_context()


def test_zero_init_materializes_sharded(mesh8):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (64, 32)),
                "b": jnp.zeros((32,))}

    with ds.zero.Init(stage=3, mesh_ctx=mesh8) as zinit:
        params = zinit.materialize(init_fn, jax.random.PRNGKey(0))
    # stage-3: large leaves sharded over the data axis
    assert len(params["w"].sharding.device_set) == 8
    ref = init_fn(jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(params["w"]),
                               np.asarray(ref["w"]), rtol=1e-6)


def test_gathered_parameters_roundtrip(mesh8):
    with ds.zero.Init(stage=3, mesh_ctx=mesh8) as zinit:
        params = zinit.shard_existing(
            {"w": np.arange(64, dtype=np.float32).reshape(8, 8)})
    with ds.zero.GatheredParameters(params, modifier_rank=0) as full:
        assert isinstance(full["w"], np.ndarray)
        full["w"][0, 0] = 999.0
    gp = ds.zero.GatheredParameters(params, modifier_rank=0)
    with gp as full:
        pass
    # the context object re-scatters edits (updated tree)
    gp2 = ds.zero.GatheredParameters(params, modifier_rank=0)
    with gp2 as full:
        full["w"][...] = full["w"] * 2
    doubled = gp2.updated
    np.testing.assert_allclose(np.asarray(doubled["w"]),
                               np.asarray(params["w"]) * 2)
    assert doubled["w"].sharding == params["w"].sharding


def test_tiled_linear_matches_dense():
    lin = ds.zero.TiledLinear(32, 48, in_splits=4, out_splits=2)
    params = lin.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 32))
    out = lin.apply(params, x)
    assert out.shape == (5, 48)

    dense_w = np.random.RandomState(0).randn(32, 48).astype(np.float32)
    dense_b = np.random.RandomState(1).randn(48).astype(np.float32)
    lin2, p2 = ds.zero.TiledLinear.from_dense(dense_w, dense_b, 4, 2)
    got = np.asarray(lin2.apply(p2, x))
    ref = np.asarray(x) @ dense_w + dense_b
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_clip_grad_norm():
    grads = {"a": jnp.ones((10,)) * 3.0, "b": jnp.ones((5,)) * 4.0}
    norm = float(global_grad_norm(grads))
    assert norm == pytest.approx(np.sqrt(10 * 9 + 5 * 16))
    clipped, pre = clip_grad_norm_(grads, max_norm=1.0)
    assert float(pre) == pytest.approx(norm)
    assert float(global_grad_norm(clipped)) == pytest.approx(1.0, rel=1e-4)
    # under the limit: untouched
    same, _ = clip_grad_norm_(grads, max_norm=1e9)
    np.testing.assert_allclose(np.asarray(same["a"]),
                               np.asarray(grads["a"]), rtol=1e-6)


def test_partition_math():
    assert partition_uniform(10, 3) == [0, 4, 7, 10]
    assert partition_uniform(9, 3) == [0, 3, 6, 9]
    bounds = partition_balanced([1, 1, 1, 10, 1, 1, 1], 3)
    assert bounds[0] == 0 and bounds[-1] == 7
    # the heavy item sits alone-ish: max part weight near 10
    weights = [1, 1, 1, 10, 1, 1, 1]
    parts = [sum(weights[bounds[i]:bounds[i + 1]]) for i in range(3)]
    assert max(parts) <= 13


def test_memory_estimators():
    n = 1_000_000_000  # 1B params
    z2 = estimate_zero2_model_states_mem_needs(n, num_chips=8, bf16=True)
    z3 = estimate_zero3_model_states_mem_needs(n, num_chips=8, bf16=True)
    z3_off = estimate_zero3_model_states_mem_needs(n, num_chips=8,
                                                   cpu_offload=True)
    assert z3["per_chip_hbm_bytes"] < z2["per_chip_hbm_bytes"]
    assert z3_off["per_chip_hbm_bytes"] < z3["per_chip_hbm_bytes"]
    assert z3_off["per_chip_host_bytes"] > 0
    # stage-3 at 8 chips: 16 bytes/param / 8 chips * 1.5 buffer factor
    # (additional_buffer_factor, runtime/utils.py) = 3.0 bytes/param
    assert z3["per_chip_hbm_bytes"] < 2 * n * 1.5 + 1


def test_see_memory_usage_runs():
    stats = see_memory_usage("unit-test probe", force=True)
    assert isinstance(stats, dict)
