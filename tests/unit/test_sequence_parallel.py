"""Sequence-parallelism tests: ring attention and Ulysses vs dense reference,
on a simulated multi-device CPU mesh (the fake-backend improvement over the
reference's NCCL-only test strategy — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.flash_attention import mha_reference
from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
from deepspeed_tpu.parallel.sequence import (ring_attention,
                                             sequence_parallel_attention,
                                             ulysses_attention)


def _qkv(b=2, h=4, s=64, d=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.fixture
def seq_mesh():
    reset_mesh_context()
    yield initialize_mesh(data=-1, seq=4)
    reset_mesh_context()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, causal=causal, mesh_ctx=seq_mesh)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(seq_mesh, causal):
    q, k, v = _qkv()
    out = ulysses_attention(q, k, v, causal=causal, mesh_ctx=seq_mesh)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_allgather_attention_matches_dense(seq_mesh, causal):
    """psum-allgather-KV attention — the divergent-branch-safe variant
    the gated pipeline executor uses (round 5)."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.sequence import allgather_attention_inner

    q, k, v = _qkv()
    spec = P(None, None, "seq", None)
    fn = jax.shard_map(
        lambda a, b, c: allgather_attention_inner(a, b, c, causal=causal),
        mesh=seq_mesh.mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_allgather_attention_grad_matches_dense(seq_mesh):
    """Grads through the psum-allgather path (psum transpose + local
    softmax) must match dense-attention grads."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.sequence import allgather_attention_inner

    q, k, v = _qkv(s=32)
    spec = P(None, None, "seq", None)

    def sp_loss(q, k, v):
        fn = jax.shard_map(
            lambda a, b, c: allgather_attention_inner(a, b, c, causal=True),
            mesh=seq_mesh.mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=True).astype(
            jnp.float32) ** 2).sum()

    g_sp = jax.grad(sp_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ring_attention_grad_flows(seq_mesh):
    q, k, v = _qkv(s=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True,
                                      mesh_ctx=seq_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_auto_mode_dispatch(seq_mesh):
    q, k, v = _qkv()
    out = sequence_parallel_attention(q, k, v, mode="auto", causal=True,
                                      mesh_ctx=seq_mesh)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sp1_falls_back_to_flash():
    reset_mesh_context()
    ctx = initialize_mesh(data=-1)  # seq=1
    q, k, v = _qkv(s=32)
    out = sequence_parallel_attention(q, k, v, mode="auto", mesh_ctx=ctx)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    q, k, v = _qkv(h=3)
    with pytest.raises(Exception):
        jax.block_until_ready(
            ulysses_attention(q, k, v, mesh_ctx=seq_mesh))


def test_ring_attention_bf16(seq_mesh):
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, causal=True, mesh_ctx=seq_mesh)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_engine_trains_with_sequence_parallel_attention():
    """SP composes with the engine: a toy attention LM whose attention
    runs ring-parallel over the seq axis trains under dp x sp, and its
    loss trajectory matches the dense-attention run at matched data
    (ring attention is exact)."""
    import numpy as np
    import optax
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel.sequence import sequence_parallel_attention
    from deepspeed_tpu.ops.flash_attention import mha_reference

    B, H, S, D, V = 4, 2, 32, 8, 64

    def build(attn):
        def model(p, rng, ids, labels):
            x = p["emb"][ids]                            # [B, S, H*D]
            qkv = x @ p["qkv"]                           # [B, S, 3*H*D]
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):
                return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

            ctx = attn(heads(q), heads(k), heads(v))
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, H * D)
            logits = ctx @ p["emb"].T
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels).mean()
        return model

    rng = np.random.RandomState(0)
    params = {
        "emb": jnp.asarray(rng.randn(V, H * D) * 0.05, jnp.float32),
        "qkv": jnp.asarray(rng.randn(H * D, 3 * H * D) * 0.05, jnp.float32),
    }
    ids = rng.randint(0, V, (B, S)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    def run(attn, mesh_kwargs):
        ds.reset_mesh_context()
        mesh = ds.initialize_mesh(**mesh_kwargs)
        engine, _, _, _ = ds.initialize(
            model=build(attn), model_parameters=params, mesh=mesh,
            config={"train_micro_batch_size_per_gpu": B // max(
                        1, mesh.data_parallel_world_size),
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 1},
                    "steps_per_print": 10 ** 9})
        losses = []
        for _ in range(6):
            loss = engine.forward(ids, labels)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        return losses

    dense_losses = run(
        lambda q, k, v: mha_reference(q, k, v, causal=True),
        dict(data=4, seq=2))
    for mode in ("ring", "ulysses"):
        sp_losses = run(
            lambda q, k, v, m=mode: sequence_parallel_attention(
                q, k, v, mode=m, causal=True),
            dict(data=4, seq=2))
        assert sp_losses[-1] < sp_losses[0], mode
        # both SP modes are exact — trajectories match dense attention
        np.testing.assert_allclose(sp_losses, dense_losses, rtol=2e-4,
                                   atol=2e-5, err_msg=mode)
