"""Symbolic pipeline-schedule tests (reference: tests/unit/test_pipe_schedule.py:157)."""

import pytest

from deepspeed_tpu.runtime.pipe import schedule as sched


def _flat(s):
    return [cmd for step in s.steps() for cmd in step]


class TestInferenceSchedule:
    def test_forward_counts(self):
        for stages in (1, 2, 4):
            for stage_id in range(stages):
                s = sched.InferenceSchedule(micro_batches=4, stages=stages,
                                            stage_id=stage_id)
                cmds = _flat(s)
                fwd = [c for c in cmds if isinstance(c, sched.ForwardPass)]
                assert len(fwd) == 4

    def test_stagger(self):
        # stage s first forwards at tick s
        s = sched.InferenceSchedule(micro_batches=3, stages=4, stage_id=2)
        steps = list(s.steps())
        first_fwd = next(i for i, step in enumerate(steps)
                         if any(isinstance(c, sched.ForwardPass) for c in step))
        assert first_fwd == 2

    def test_load_only_ends(self):
        s = sched.InferenceSchedule(micro_batches=3, stages=4, stage_id=1)
        assert not any(isinstance(c, sched.LoadMicroBatch) for c in _flat(s))
        for sid in (0, 3):
            s = sched.InferenceSchedule(micro_batches=3, stages=4, stage_id=sid)
            loads = [c for c in _flat(s) if isinstance(c, sched.LoadMicroBatch)]
            assert len(loads) == 3


class TestTrainSchedule:
    @pytest.mark.parametrize("micro_batches", [1, 2, 4, 8])
    @pytest.mark.parametrize("stages", [1, 2, 4])
    def test_counts(self, micro_batches, stages):
        for stage_id in range(stages):
            s = sched.TrainSchedule(micro_batches, stages, stage_id)
            cmds = _flat(s)
            fwd = [c for c in cmds if isinstance(c, sched.ForwardPass)]
            bwd = [c for c in cmds if isinstance(c, sched.BackwardPass)]
            assert len(fwd) == micro_batches
            assert len(bwd) == micro_batches
            assert len([c for c in cmds
                        if isinstance(c, sched.OptimizerStep)]) == 1
            assert len([c for c in cmds
                        if isinstance(c, sched.ReduceGrads)]) == 1

    @pytest.mark.parametrize("stages", [2, 4])
    def test_send_recv_pairing(self, stages):
        """Every SendActivation at stage s has a matching RecvActivation at
        s+1 (same microbatch order), and symmetrically for grads."""
        micro = 4
        streams = {sid: _flat(sched.TrainSchedule(micro, stages, sid))
                   for sid in range(stages)}

        def order(sid, cls):
            # microbatch order reconstructed from the compute stream: buffer
            # ids recycle, so pair sends/recvs positionally
            return [c.buffer_id for c in streams[sid] if isinstance(c, cls)]

        for sid in range(stages - 1):
            sends = order(sid, sched.SendActivation)
            recvs = order(sid + 1, sched.RecvActivation)
            assert len(sends) == micro and len(recvs) == micro
            grads_send = order(sid + 1, sched.SendGrad)
            grads_recv = order(sid, sched.RecvGrad)
            assert len(grads_send) == micro and len(grads_recv) == micro

    def test_one_f_one_b_memory(self):
        """Live activations never exceed num_pipe_buffers."""
        for stages in (2, 4):
            for stage_id in range(stages):
                s = sched.TrainSchedule(8, stages, stage_id)
                live = 0
                peak = 0
                for kind, _mb in s._compute_order():
                    if kind == "fwd":
                        live += 1
                    else:
                        live -= 1
                    peak = max(peak, live)
                assert peak <= s.num_pipe_buffers()

    def test_buffer_no_collision(self):
        """A pipe buffer is never reused before its backward consumed it."""
        for stages in (2, 4):
            for stage_id in range(stages):
                s = sched.TrainSchedule(8, stages, stage_id)
                in_use = {}
                for kind, mb in s._compute_order():
                    buf = s._buffer_idx(mb)
                    if kind == "fwd":
                        assert buf not in in_use, \
                            f"buffer {buf} reused while live (stage {stage_id})"
                        in_use[buf] = mb
                    else:
                        assert in_use.pop(buf) == mb

    def test_first_stage_no_recv_activation(self):
        s = sched.TrainSchedule(4, 4, 0)
        cmds = _flat(s)
        assert not any(isinstance(c, sched.RecvActivation) for c in cmds)
        assert not any(isinstance(c, sched.SendGrad) for c in cmds)

    def test_last_stage_no_send_activation(self):
        s = sched.TrainSchedule(4, 4, 3)
        cmds = _flat(s)
        assert not any(isinstance(c, sched.SendActivation) for c in cmds)
        assert not any(isinstance(c, sched.RecvGrad) for c in cmds)

    def test_single_stage_is_pure_compute(self):
        s = sched.TrainSchedule(4, 1, 0)
        cmds = _flat(s)
        assert not any(isinstance(c, (sched.SendActivation,
                                      sched.RecvActivation, sched.SendGrad,
                                      sched.RecvGrad)) for c in cmds)


class TestDataParallelSchedule:
    def test_stream(self):
        s = sched.DataParallelSchedule(micro_batches=2, stages=1, stage_id=0)
        steps = list(s.steps())
        assert len(steps) == 2
        assert any(isinstance(c, sched.OptimizerStep) for c in steps[-1])


def test_instruction_repr_eq():
    assert sched.ForwardPass(1) == sched.ForwardPass(1)
    assert sched.ForwardPass(1) != sched.ForwardPass(2)
    assert sched.ForwardPass(1) != sched.BackwardPass(1)
    assert "buffer_id=1" in repr(sched.ForwardPass(1))
