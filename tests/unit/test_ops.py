"""Kernel-parity tests — the analog of the reference's
tests/unit/test_cuda_forward.py:333 / test_cuda_backward.py:335 (fused kernels
vs a plain implementation within fp16/fp32 tolerances).

The Pallas kernels run in interpreter mode on the CPU test mesh; the same
kernel code compiles for real TPUs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer, bias_gelu,
                               flash_attention, fused_layer_norm, gelu,
                               layer_norm_reference, mha_reference)
from deepspeed_tpu.ops.flash_attention import flash_attention_pallas
from deepspeed_tpu.ops.normalize import layer_norm_pallas


def _qkv(b=2, h=4, s=128, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype)
    k = jax.random.normal(ks[1], (b, h, s, d), dtype)
    v = jax.random.normal(ks[2], (b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_pallas_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention_pallas(q, k, v, causal=causal, block_q=64,
                                 block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_interpret_mode_dropout_error_is_actionable():
    """ISSUE 13 satellite: the interpret-mode dropout refusal must name
    the knob and the workarounds (rate 0 / impl='xla' / the saved
    dropout_mask for the backward), not just state the PRNG limitation."""
    from deepspeed_tpu.ops.flash_attention import flash_attention_bwd_pallas
    q, k, v = _qkv()
    with pytest.raises(ValueError) as ei:
        flash_attention_pallas(q, k, v, block_q=64, block_k=64,
                               interpret=True, dropout_rate=0.1,
                               dropout_seed=0)
    msg = str(ei.value)
    assert "dropout_rate=0" in msg and "impl='xla'" in msg
    assert "pltpu.prng_seed" in msg  # still explains WHY

    out, lse = flash_attention_pallas(q, k, v, block_q=64, block_k=64,
                                      interpret=True, return_lse=True)
    do = jnp.ones_like(q)
    with pytest.raises(ValueError) as ei:
        flash_attention_bwd_pallas(q, k, v, out, lse, do, block_q=64,
                                   block_k=64, interpret=True,
                                   dropout_rate=0.1, dropout_seed=0)
    msg = str(ei.value)
    assert "dropout_rate=0" in msg and "dropout_mask" in msg
    assert "set_dropout_mask_reuse" in msg


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_bwd_pallas_matches_reference(causal):
    from deepspeed_tpu.ops.flash_attention import flash_attention_bwd_pallas
    q, k, v = _qkv(s=128)
    do = jax.random.normal(jax.random.PRNGKey(7), q.shape, q.dtype)
    out, lse = flash_attention_pallas(q, k, v, causal=causal, block_q=64,
                                      block_k=64, interpret=True,
                                      return_lse=True)
    dq, dk, dv = flash_attention_bwd_pallas(
        q, k, v, out, lse, do, causal=causal, block_q=64, block_k=64,
        interpret=True)

    def ref_loss(q_, k_, v_):
        r = mha_reference(q_, k_, v_, causal=causal).astype(jnp.float32)
        return jnp.vdot(r, do.astype(jnp.float32))

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), rtol=1e-4,
                               atol=1e-4)


def test_flash_attention_public_dispatch_and_grad():
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def loss(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(mha_reference(q_, k_, v_, causal=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_attention_bias_path():
    q, k, v = _qkv(s=32)
    bias = jax.random.normal(jax.random.PRNGKey(9), (2, 1, 32, 32))
    out = flash_attention(q, k, v, bias=bias)
    ref = mha_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_layer_norm_pallas_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 256))
    gamma = jax.random.normal(jax.random.PRNGKey(1), (256,)) + 1.0
    beta = jax.random.normal(jax.random.PRNGKey(2), (256,))
    ref = layer_norm_reference(x, gamma, beta)
    out = layer_norm_pallas(x, gamma, beta, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_layer_norm_grad():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64))
    gamma, beta = jnp.ones((64,)), jnp.zeros((64,))

    g = jax.grad(lambda x_: jnp.sum(fused_layer_norm(x_, gamma, beta) ** 2))(x)
    gr = jax.grad(
        lambda x_: jnp.sum(layer_norm_reference(x_, gamma, beta) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4,
                               atol=1e-5)


def test_gelu_matches_tanh_formula():
    x = jnp.linspace(-3, 3, 64)
    expected = 0.5 * x * (1 + jnp.tanh(0.7978845608 * (x + 0.044715 * x ** 3)))
    np.testing.assert_allclose(np.asarray(gelu(x)), np.asarray(expected),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(bias_gelu(x, jnp.zeros_like(x))),
                               np.asarray(expected), rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("pre_ln", [True, False])
def test_transformer_layer_shapes_and_determinism(pre_ln):
    cfg = DeepSpeedTransformerConfig(
        batch_size=2, hidden_size=64, heads=4, num_hidden_layers=2,
        pre_layer_norm=pre_ln, bf16=False, causal=True,
        attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 64))
    out = layer(params, x, deterministic=True)
    assert out.shape == x.shape
    out2 = layer(params, x, deterministic=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2))
    # differentiable end-to-end
    g = jax.grad(lambda p: jnp.sum(layer(p, x, deterministic=True) ** 2))(
        params)
    assert jax.tree.all(jax.tree.map(
        lambda t: bool(jnp.all(jnp.isfinite(t))), g))


def test_transformer_layer_dropout_uses_rng():
    cfg = DeepSpeedTransformerConfig(
        batch_size=2, hidden_size=32, heads=2, num_hidden_layers=1,
        bf16=False, attn_dropout_ratio=0.5, hidden_dropout_ratio=0.5)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))
    a = layer(params, x, rng=jax.random.PRNGKey(2))
    b = layer(params, x, rng=jax.random.PRNGKey(3))
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_tp_partition_specs_cover_all_params():
    cfg = DeepSpeedTransformerConfig(batch_size=1, hidden_size=32, heads=2,
                                     num_hidden_layers=1)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    specs = DeepSpeedTransformerLayer.param_partition_specs()
    assert set(specs) == set(params)


@pytest.mark.parametrize("tp", [2, 4])
def test_transformer_layer_manual_tp_matches_single(tp):
    """The explicit-collective TP mode (tp_axis=, used by the gated 1F1B
    executor) must match the single-device layer bit-for-tolerance:
    forward, input grad, and EVERY param grad — the f/g operator pair
    (tp_fcast/tp_psum, ops/tp_collectives.py) restores full cotangents per device, so no
    post-hoc grad correction exists to hide an error."""
    from jax.sharding import Mesh, PartitionSpec as P

    cfg = DeepSpeedTransformerConfig(
        batch_size=2, hidden_size=32, heads=4, num_hidden_layers=1,
        bf16=False, causal=True,
        attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))

    def ref_loss(p, x):
        return (layer(p, x, deterministic=True).astype(jnp.float32)
                ** 2).sum()

    ref_y = layer(params, x, deterministic=True)
    ref_gp, ref_gx = jax.grad(ref_loss, argnums=(0, 1))(params, x)

    mesh = Mesh(np.array(jax.devices()[:tp]).reshape(tp), ("model",))
    specs = DeepSpeedTransformerLayer.tp_manual_view_specs()

    def region(p_local, x):
        def loss(p, x):
            y = layer(p, x, deterministic=True, tp_axis="model")
            return (y.astype(jnp.float32) ** 2).sum()

        y = layer(p_local, x, deterministic=True, tp_axis="model")
        gp, gx = jax.grad(loss, argnums=(0, 1))(p_local, x)
        return y, gp, gx

    f = jax.jit(jax.shard_map(
        region, mesh=mesh, in_specs=(specs, P()),
        out_specs=(P(), specs, P()),
        axis_names=frozenset({"model"}), check_vma=False))
    viewed = DeepSpeedTransformerLayer.tp_manual_views(params, cfg.heads)
    y, gp, gx = f(viewed, x)
    gp = DeepSpeedTransformerLayer.tp_manual_unview(gp)

    np.testing.assert_allclose(np.asarray(y), np.asarray(ref_y), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(ref_gx),
                               atol=1e-4)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(gp[key]), np.asarray(ref_gp[key]), atol=1e-4,
            err_msg=f"param grad mismatch: {key}")


def test_tp_manual_view_roundtrip():
    """tp_manual_views/unview must be exact inverses on stacked
    [S, k, ...] pipeline leaves (the engine applies the view before the
    shard_map and the unview to the returned grads)."""
    cfg = DeepSpeedTransformerConfig(batch_size=1, hidden_size=32, heads=4,
                                     num_hidden_layers=1)
    layer = DeepSpeedTransformerLayer(cfg)
    single = layer.init_params(jax.random.PRNGKey(0))
    stacked = jax.tree.map(
        lambda leaf: jnp.stack([jnp.stack([leaf, leaf + 1.0])] * 3), single)
    viewed = DeepSpeedTransformerLayer.tp_manual_views(stacked, cfg.heads)
    assert viewed["attn_qkvw"].shape == (3, 2, 32, 4, 3, 8)
    assert viewed["attn_qkvb"].shape == (3, 2, 4, 3, 8)
    back = DeepSpeedTransformerLayer.tp_manual_unview(viewed)
    for key in stacked:
        np.testing.assert_array_equal(np.asarray(back[key]),
                                      np.asarray(stacked[key]))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_bsh_layout_matches_reference(causal):
    """The transpose-free [B, S, heads, d] layout (BlockSpecs index the
    head dim) must be numerically identical to the classic [B, H, S, D]
    path — forward and backward."""
    from deepspeed_tpu.ops.flash_attention import flash_attention_bwd_pallas
    q, k, v = _qkv(s=128)

    def to_bsh(t):
        return t.transpose(0, 2, 1, 3)  # [B,H,S,D] -> [B,S,H,D]

    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention_pallas(
        to_bsh(q), to_bsh(k), to_bsh(v), causal=causal, block_q=64,
        block_k=64, interpret=True, layout="bshd")
    np.testing.assert_allclose(np.asarray(to_bsh(out)), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    do = jax.random.normal(jax.random.PRNGKey(7), q.shape, q.dtype)
    out_b, lse = flash_attention_pallas(
        to_bsh(q), to_bsh(k), to_bsh(v), causal=causal, block_q=64,
        block_k=64, interpret=True, return_lse=True, layout="bshd")
    dq, dk, dv = flash_attention_bwd_pallas(
        to_bsh(q), to_bsh(k), to_bsh(v), out_b, lse, to_bsh(do),
        causal=causal, block_q=64, block_k=64, interpret=True,
        layout="bshd")

    def ref_loss(q_, k_, v_):
        r = mha_reference(q_, k_, v_, causal=causal).astype(jnp.float32)
        return jnp.vdot(r, do.astype(jnp.float32))

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(to_bsh(dq)), np.asarray(rq),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(to_bsh(dk)), np.asarray(rk),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(to_bsh(dv)), np.asarray(rv),
                               rtol=1e-4, atol=1e-4)


def test_flash_attention_bsh_public_fallback_and_grad():
    """flash_attention_bsh on CPU (pallas unusable) falls back to the
    transposed XLA reference and stays differentiable."""
    from deepspeed_tpu.ops.flash_attention import flash_attention_bsh
    q, k, v = _qkv(s=64)

    def to_bsh(t):
        return t.transpose(0, 2, 1, 3)

    out = flash_attention_bsh(to_bsh(q), to_bsh(k), to_bsh(v), causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(to_bsh(out)), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def loss(q_):
        o = flash_attention_bsh(to_bsh(q_), to_bsh(k), to_bsh(v),
                                causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def ref_l(q_):
        return jnp.sum(mha_reference(q_, k, v,
                                     causal=True).astype(jnp.float32) ** 2)

    np.testing.assert_allclose(np.asarray(jax.grad(loss)(q)),
                               np.asarray(jax.grad(ref_l)(q)),
                               rtol=1e-4, atol=1e-4)


def test_transformer_layer_bshd_layout_matches_bhsd():
    """attn_layout='bshd' (transpose-free) must be numerically identical
    to the classic layout at the LAYER level — both routes feed the same
    reference math on CPU and the same kernel pair on TPU."""
    from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                               DeepSpeedTransformerLayer)

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32), jnp.float32)

    outs = []
    for layout in ("bhsd", "bshd"):
        cfg = DeepSpeedTransformerConfig(
            hidden_size=32, heads=4, attn_dropout_ratio=0.0,
            hidden_dropout_ratio=0.0, bf16=False, causal=True,
            attn_layout=layout)
        layer = DeepSpeedTransformerLayer(cfg)
        params = layer.init_params(jax.random.PRNGKey(1))
        outs.append(np.asarray(layer(params, x, deterministic=True)))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("rows,hidden", [(64, 128), (96, 256)])
def test_layer_norm_bwd_pallas_matches_autodiff(rows, hidden):
    """One-pass LN backward kernel vs XLA autodiff of the reference
    (reference analog: normalize_kernels.cu backward)."""
    from deepspeed_tpu.ops.normalize import (layer_norm_bwd_pallas,
                                             layer_norm_reference)

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(ks[0], (rows, hidden), jnp.float32)
    gamma = 1.0 + 0.1 * jax.random.normal(ks[1], (hidden,), jnp.float32)
    beta = 0.1 * jax.random.normal(ks[2], (hidden,), jnp.float32)
    dy = jax.random.normal(jax.random.PRNGKey(7), (rows, hidden),
                           jnp.float32)

    dx, dg, db = layer_norm_bwd_pallas(x, gamma, dy, eps=1e-5,
                                       block_rows=32, interpret=True)
    _, vjp = jax.vjp(
        lambda x_, g_, b_: layer_norm_reference(x_, g_, b_, 1e-5),
        x, gamma, beta)
    rx, rg, rb = vjp(dy)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rx), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(dg), np.asarray(rg), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(db), np.asarray(rb), rtol=1e-5,
                               atol=1e-5)


def test_fused_ln_bwd_dispatch_via_pallas(monkeypatch):
    """The production gradient path of fused_layer_norm on TPU — the
    pallas_available() branch in _fused_ln_bwd with its block guard and
    dgamma/dbeta dtype casts — exercised here by forcing the dispatch and
    running the kernel in interpret mode on a 3-D bf16 activation."""
    import functools as ft

    from deepspeed_tpu.ops import normalize as nm

    monkeypatch.setattr("deepspeed_tpu.ops.dispatch._ln_impl", "pallas")
    monkeypatch.setattr(
        "deepspeed_tpu.ops.dispatch.pallas_available", lambda: True)
    monkeypatch.setattr(
        nm, "layer_norm_pallas",
        ft.partial(nm.layer_norm_pallas, interpret=True))
    monkeypatch.setattr(
        nm, "layer_norm_bwd_pallas",
        ft.partial(nm.layer_norm_bwd_pallas, interpret=True))

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 128),
                          jnp.bfloat16)
    gamma = jnp.ones((128,), jnp.float32) * 1.05
    beta = jnp.zeros((128,), jnp.float32) + 0.05
    dy = jax.random.normal(jax.random.PRNGKey(1), x.shape, jnp.bfloat16)

    def loss(f):
        def inner(x_, g_, b_):
            return jnp.vdot(f(x_, g_, b_).astype(jnp.float32),
                            dy.astype(jnp.float32))
        return inner

    gx, gg, gb = jax.grad(
        loss(lambda a, b, c: nm.fused_layer_norm(a, b, c, 1e-5)),
        argnums=(0, 1, 2))(x, gamma, beta)
    rx, rg, rb = jax.grad(
        loss(lambda a, b, c: nm.layer_norm_reference(a, b, c, 1e-5)),
        argnums=(0, 1, 2))(x, gamma, beta)
    assert gx.dtype == x.dtype and gg.dtype == gamma.dtype
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(rg), rtol=2e-2,
                               atol=2e-1)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=2e-2,
                               atol=2e-1)


def test_transformer_layer_bshd_under_tensor_parallel():
    """attn_layout='bshd' with Megatron-split qkv over the model axis:
    the head dim the BlockSpecs index is the SHARDED dim under TP, so
    parity with the bhsd path on a model=2 mesh de-risks the layout flip
    for TP configs."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                               DeepSpeedTransformerLayer)
    from jax.sharding import NamedSharding

    ds.reset_mesh_context()
    ctx = ds.initialize_mesh(data=-1, model=2)
    try:
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 32),
                              jnp.float32)
        outs = []
        for layout in ("bhsd", "bshd"):
            cfg = DeepSpeedTransformerConfig(
                hidden_size=32, heads=4, attn_dropout_ratio=0.0,
                hidden_dropout_ratio=0.0, bf16=False, causal=True,
                attn_layout=layout)
            layer = DeepSpeedTransformerLayer(cfg)
            params = layer.init_params(jax.random.PRNGKey(1))
            specs = DeepSpeedTransformerLayer.param_partition_specs()
            sharded = {
                k: jax.device_put(v, NamedSharding(ctx.mesh, specs[k]))
                for k, v in params.items()}
            out = jax.jit(lambda p, xx: layer(p, xx, deterministic=True))(
                sharded, x)
            outs.append(np.asarray(out))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-5)
    finally:
        ds.reset_mesh_context()


def test_flash_attention_dropout_xla_path():
    """CPU (XLA fallback) probability-dropout semantics: deterministic per
    seed, ~rate fraction of attention entries dropped (visible through a
    ones-valued v), exact equality at rate 0, seed requirement."""
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 2, 64, 16), jnp.float32)
               for kk in ks)
    ones_v = jnp.ones_like(v)

    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(q, k, v, dropout_rate=0.1)

    o1 = flash_attention(q, k, ones_v, dropout_rate=0.2, dropout_seed=7)
    o2 = flash_attention(q, k, ones_v, dropout_rate=0.2, dropout_seed=7)
    o3 = flash_attention(q, k, ones_v, dropout_rate=0.2, dropout_seed=8)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert float(jnp.max(jnp.abs(o1 - o3))) > 0.0
    # rows of dropout(P)/keep against ones-v have mean 1 in expectation
    assert abs(float(jnp.mean(o1)) - 1.0) < 0.05

    o0 = flash_attention(q, k, v, dropout_rate=0.0)
    onodrop = flash_attention(q, k, v)
    np.testing.assert_array_equal(np.asarray(o0), np.asarray(onodrop))

    # grads flow and are finite through the dropout path
    g = jax.grad(lambda q_: jnp.sum(
        flash_attention(q_, k, v, dropout_rate=0.2, dropout_seed=7) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_transformer_layer_training_uses_attention_dropout():
    """In training mode the layer's attention dropout changes the output
    (vs deterministic) and stays reproducible for a fixed rng."""
    from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                               DeepSpeedTransformerLayer)
    cfg = DeepSpeedTransformerConfig(
        hidden_size=32, heads=4, attn_dropout_ratio=0.3,
        hidden_dropout_ratio=0.0, bf16=False, causal=True)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 32))
    rng = jax.random.PRNGKey(2)
    det = layer(params, x, deterministic=True)
    tr1 = layer(params, x, rng=rng, deterministic=False)
    tr2 = layer(params, x, rng=rng, deterministic=False)
    np.testing.assert_array_equal(np.asarray(tr1), np.asarray(tr2))
    assert float(jnp.max(jnp.abs(tr1 - det))) > 1e-3


def test_fused_dequant_matmul_interpret_parity():
    """Pallas fused dequant-matmul (interpret) vs the XLA dequant path and
    vs exact fp math, across tiling-friendly and fitted shapes."""
    from deepspeed_tpu.ops.quant import (QuantizedWeight,
                                         fused_dequant_matmul, dequant)
    rng = np.random.RandomState(0)
    for (m, k, n, groups) in [(8, 256, 384, 4), (16, 768, 2304, 8),
                              (128, 128, 128, 1)]:
        x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
        qw = jnp.asarray(rng.randint(-127, 128, (k, n)).astype(np.int8))
        scale = jnp.asarray(
            np.abs(rng.standard_normal((groups, 1))).astype(np.float32))
        w = QuantizedWeight(qw, scale)
        out = fused_dequant_matmul(x, w, interpret=True)
        ref = x @ dequant(w, jnp.float32)
        # blocked-K accumulation reorders fp32 sums vs the single dot
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-2)


def test_matmul_maybe_int8_nd_and_plain():
    from deepspeed_tpu.ops.quant import QuantizedWeight, matmul_maybe_int8
    rng = np.random.RandomState(1)
    x3 = jnp.asarray(rng.standard_normal((2, 4, 64)).astype(np.float32))
    qw = jnp.asarray(rng.randint(-127, 128, (64, 96)).astype(np.int8))
    scale = jnp.ones((4, 1), jnp.float32) * 0.5
    w = QuantizedWeight(qw, scale)
    out = matmul_maybe_int8(x3, w)
    assert out.shape == (2, 4, 96)
    ref = jnp.einsum("bsk,kn->bsn", x3, qw.astype(jnp.float32) * 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)
    # plain (unquantized) weights unchanged
    wplain = jnp.asarray(rng.standard_normal((64, 96)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(matmul_maybe_int8(x3, wplain)),
                               np.asarray(jnp.einsum("bsk,kn->bsn", x3,
                                                     wplain)), rtol=1e-5)
    # stacked (3-D) quantized weights rejected loudly
    import pytest as _pytest
    wbad = QuantizedWeight(jnp.zeros((2, 64, 96), jnp.int8),
                           jnp.ones((2, 4, 1)))
    with _pytest.raises(ValueError, match="2-D"):
        matmul_maybe_int8(x3, wbad)


def test_fused_dequant_matmul_grad():
    """Differentiation through the fused path (custom VJP: XLA matmul
    backward) matches the plain dequant matmul gradient."""
    from deepspeed_tpu.ops.quant import (QuantizedWeight, _fused_dq,
                                         dequant)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.standard_normal((8, 128)).astype(np.float32))
    qw = jnp.asarray(rng.randint(-127, 128, (128, 256)).astype(np.int8))
    scale = jnp.ones((2, 1), jnp.float32) * 0.1
    w = QuantizedWeight(qw, scale)

    # interpret-mode forward is exercised elsewhere; on CPU the public
    # dispatcher uses the XLA path, so drive the custom-vjp wrapper with
    # the kernel monkeypatched to interpret mode for the fwd
    import deepspeed_tpu.ops.quant as qmod
    import functools as ft
    orig = qmod.fused_dequant_matmul
    qmod.fused_dequant_matmul = ft.partial(orig, interpret=True)
    try:
        g1 = jax.grad(lambda a: jnp.sum(
            _fused_dq(a, w.qweight, w.scale) ** 2))(x)
    finally:
        qmod.fused_dequant_matmul = orig
    g2 = jax.grad(lambda a: jnp.sum((a @ dequant(w, jnp.float32)) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-2)


def test_fused_dequant_matmul_scale_grad():
    """The fused path's scale cotangent matches autodiff through the XLA
    dequant path — learned scales get identical gradients on both
    backends (round-3 review finding: it used to be silently zero)."""
    from deepspeed_tpu.ops.quant import (QuantizedWeight, _fused_dq,
                                         dequant)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.standard_normal((8, 128)).astype(np.float32))
    qw = jnp.asarray(rng.randint(-127, 128, (128, 256)).astype(np.int8))
    scale = jnp.asarray(rng.uniform(0.05, 0.2, (4, 1)).astype(np.float32))

    import deepspeed_tpu.ops.quant as qmod
    import functools as ft
    orig = qmod.fused_dequant_matmul
    qmod.fused_dequant_matmul = ft.partial(orig, interpret=True)
    try:
        ds1 = jax.grad(lambda s: jnp.sum(
            _fused_dq(x, qw, s) ** 2))(scale)
    finally:
        qmod.fused_dequant_matmul = orig
    ds2 = jax.grad(lambda s: jnp.sum(
        (x @ dequant(QuantizedWeight(qw, s), jnp.float32)) ** 2))(scale)
    np.testing.assert_allclose(np.asarray(ds1), np.asarray(ds2),
                               rtol=1e-3, atol=1e-2)


def test_dequantize_weight_delegates():
    from deepspeed_tpu.runtime.weight_quantizer import (quantize_weight,
                                                        dequantize_weight)
    rng = np.random.RandomState(4)
    wfull = rng.standard_normal((64, 32)).astype(np.float32)
    qw = quantize_weight(jnp.asarray(wfull), num_groups=4)
    deq = dequantize_weight(qw)
    assert deq.shape == (64, 32)
    np.testing.assert_allclose(np.asarray(deq), wfull, atol=0.05)


def test_dropout_keep_scale_quantization():
    """The in-kernel dropout scale must invert the EXACT quantized keep
    probability the kernel thresholds against — 8-bit mode quantizes the
    keep probability to n/256, and using 1/(1-rate) there would bias
    E[attention output] by up to ~0.2%."""
    from deepspeed_tpu.ops.flash_attention import (_keep_scale,
                                                   _quantized_threshold,
                                                   _effective_dropout_bits,
                                                   set_dropout_bits,
                                                   dropout_bits)
    assert abs(_keep_scale(0.1, 32) - 1 / 0.9) < 1e-6
    assert _keep_scale(0.1, 8) == 256.0 / round(0.9 * 256)
    assert _keep_scale(0.0, 8) == 1.0   # keep-all: no scaling
    # threshold*scale == 2^width exactly (the shared-definition invariant)
    for rate in (0.05, 0.1, 0.2, 0.5):
        for bits in (8, 32):
            assert (_keep_scale(rate, bits)
                    * _quantized_threshold(rate, bits) == float(2 ** bits))
    # non-multiple-of-4 k blocks force the 32-bit width for mask AND scale
    from deepspeed_tpu.ops.flash_attention import _DEFAULT_DROPOUT_BITS
    # the SHIPPED default (not the live global, which DS_DROPOUT_BITS or
    # an earlier set_dropout_bits may have overridden)
    assert _DEFAULT_DROPOUT_BITS == 8, \
        "repo default is 8-bit since r4 (chip-validated A/B)"
    prior = dropout_bits()
    try:
        set_dropout_bits(8)
        assert _effective_dropout_bits(128) == 8
        assert _effective_dropout_bits(6) == 32
        set_dropout_bits(32)
        assert _effective_dropout_bits(6) == 32
        assert _effective_dropout_bits(128) == 32
        assert dropout_bits() == 32
    finally:
        set_dropout_bits(prior)
    import pytest as _pytest
    with _pytest.raises(ValueError):
        set_dropout_bits(16)
    assert dropout_bits() == prior


def test_tp_psum_native_width_knob(monkeypatch):
    """DS_TP_PSUM_NATIVE=1 (the measured native-width mode, VERDICT r4
    weak #5) removes the f32 promotion around sub-f32 manual psums; the
    default keeps it (XLA-CPU AllReducePromotion crash + invariant 4)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.ops.tp_collectives import tp_psum

    mesh = Mesh(np.array(jax.devices()[:1]), ("model",))

    def jaxpr_of(x):
        fn = jax.shard_map(lambda v: tp_psum(v, "model"), mesh=mesh,
                           in_specs=P(), out_specs=P(), check_vma=False)
        return str(jax.make_jaxpr(fn)(x))

    x = jnp.ones((8,), jnp.bfloat16)
    monkeypatch.delenv("DS_TP_PSUM_NATIVE", raising=False)
    assert "f32" in jaxpr_of(x)          # promoted wire by default
    monkeypatch.setenv("DS_TP_PSUM_NATIVE", "1")
    native = jaxpr_of(x)
    assert "f32" not in native           # native bf16 wire
    assert "psum" in native
    # f32 inputs are untouched either way
    monkeypatch.delenv("DS_TP_PSUM_NATIVE", raising=False)
    assert "bf16" not in jaxpr_of(jnp.ones((8,), jnp.float32))


# --------------------------------------------------------------------------- #
# Dropout mask reuse: packing layout (CPU-checkable; the kernel-level
# reuse-vs-regen grad identity is chip-only — tests/tpu)
# --------------------------------------------------------------------------- #
def test_dropout_mask_pack_roundtrip():
    from deepspeed_tpu.ops.flash_attention import (_pack_keep32,
                                                   _unpack_keep32)
    rng = np.random.RandomState(3)
    for rows, cols in [(512, 1024), (256, 128), (1024, 256)]:
        keep = jnp.asarray(rng.rand(rows, cols) < 0.8)
        packed = _pack_keep32(keep)
        assert packed.shape == (rows // 32, cols)
        assert packed.dtype == jnp.uint32
        np.testing.assert_array_equal(np.asarray(_unpack_keep32(packed)),
                                      np.asarray(keep))


def test_dropout_mask_pack_bit_layout():
    """Bit j of word row r must hold keep[j*gr + r] — the fwd kernel
    packs and BOTH bwd kernels unpack through this exact layout, so pin
    it (a silent layout change would corrupt grads, not fail loudly)."""
    from deepspeed_tpu.ops.flash_attention import _pack_keep32
    rows, cols = 64, 128
    gr = rows // 32
    keep = np.zeros((rows, cols), bool)
    keep[5 * gr + 1, 7] = True  # -> word row 1, bit 5, col 7
    packed = np.asarray(_pack_keep32(jnp.asarray(keep)))
    assert packed[1, 7] == np.uint32(1 << 5)
    assert packed.sum() == np.uint32(1 << 5)


def test_dropout_mask_reuse_mode_guards():
    """save_dropout_mask demands return_lse + dropout; bwd rejects a
    mask when the fwd/bwd modes disagree.  Every guard must name the
    OFFENDING VALUE and the config knob that fixes it (round-5 feedback:
    'multiple of 256' / mask_block_q failures were not actionable)."""
    import importlib
    fa = importlib.import_module("deepspeed_tpu.ops.flash_attention")
    q = k = v = jnp.zeros((1, 1, 512, 64), jnp.float32)
    with pytest.raises(ValueError, match="save_dropout_mask"):
        fa.flash_attention_pallas(q, k, v, save_dropout_mask=True,
                                  interpret=True)
    # fwd 256-alignment guard: names q_len, the resolved block, and both
    # ways out (block_q config / reuse off).  q_len=384 resolves a 384
    # block — aligned but not packable.
    q384 = jnp.zeros((1, 1, 384, 64), jnp.float32)
    with pytest.raises(ValueError) as ei:
        fa.flash_attention_pallas(q384, q384, q384, save_dropout_mask=True,
                                  return_lse=True, dropout_rate=0.1)
    msg = str(ei.value)
    assert "q_len=384" in msg and "384" in msg
    assert "block_q" in msg and "DS_DROPOUT_REUSE" in msg
    lse = jnp.zeros((1, 1, 512), jnp.float32)
    mask = jnp.zeros((1, 1, 16, 512), jnp.uint32)
    # mask without dropout_rate: names the rate and the fix
    with pytest.raises(ValueError, match=r"dropout_rate=0\.0"):
        fa.flash_attention_bwd_pallas(q, k, v, q, lse, q, dropout_mask=mask,
                                      interpret=True)
    # mask at a non-packable backward block: names the value + knobs
    lse384 = jnp.zeros((1, 1, 384), jnp.float32)
    mask384 = jnp.zeros((1, 1, 12, 384), jnp.uint32)
    with pytest.raises(ValueError,
                       match=r"384.*not a multiple of 256.*DS_DROPOUT_REUSE"):
        fa.flash_attention_bwd_pallas(
            q384, q384, q384, q384, lse384, q384, dropout_rate=0.1,
            dropout_mask=mask384, dropout_mask_block_q=384, interpret=True)
    # block_q mismatch: the packed bit layout depends on the forward's
    # resolved q block — a mismatched direct call must error, not
    # corrupt, and the error names both blocks and the fix
    with pytest.raises(ValueError,
                       match=r"block_q=256.*block_q=512.*dropout_mask_block_q"):
        fa.flash_attention_bwd_pallas(
            q, k, v, q, lse, q, dropout_rate=0.1, dropout_mask=mask,
            dropout_mask_block_q=256, block_q=512, interpret=True)


def test_dropout_mask_reuse_setter():
    import importlib
    fa = importlib.import_module("deepspeed_tpu.ops.flash_attention")
    prev = fa.dropout_mask_reuse()
    try:
        fa.set_dropout_mask_reuse(True)
        assert fa.dropout_mask_reuse() is True
        fa.set_dropout_mask_reuse(False)
        assert fa.dropout_mask_reuse() is False
    finally:
        fa.set_dropout_mask_reuse(prev)
    assert fa._mask_reuse_usable(512)
    assert fa._mask_reuse_usable(256)
    assert not fa._mask_reuse_usable(128)
    assert not fa._mask_reuse_usable(384)


def test_dropout_mask_reuse_bwd_interpret_matches_reference():
    """Reuse-mode backward in interpret mode (legal: it never touches
    the TPU PRNG): pack a KNOWN keep mask the way the fwd kernel does
    (per-q-block tiles), run both bwd kernels with it, and compare
    against autodiff of a reference that applies exactly that mask with
    the kernel's quantized inverse scale.  Covers the unpack bit layout
    AND the dropout grad math on the CPU lane."""
    import importlib
    fa = importlib.import_module("deepspeed_tpu.ops.flash_attention")
    rng = np.random.RandomState(7)
    B, H, S, D = 1, 2, 512, 32
    bq, bk = 256, 128
    rate = 0.2
    q, k, v, do = (jnp.asarray(rng.standard_normal((B, H, S, D)),
                               jnp.float32) for _ in range(4))
    keep = rng.rand(B, H, S, S) < (1.0 - rate)
    inv = fa._keep_scale(rate, fa._effective_dropout_bits(bk))
    sm = 1.0 / np.sqrt(D)

    def ref(q_, k_, v_):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * sm
        p = jax.nn.softmax(s, axis=-1)
        a = jnp.where(jnp.asarray(keep), p * inv, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", a, v_)

    out = ref(q, k, v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
    lse = jax.nn.logsumexp(s, axis=-1)
    # pack exactly as the fwd kernel does: per q-block tile, local rows
    packed = jnp.concatenate(
        [fa._pack_keep32(jnp.asarray(keep[b, h, i * bq:(i + 1) * bq]))
         for b in range(B) for h in range(H) for i in range(S // bq)],
        axis=0).reshape(B, H, S // 32, S)
    dq, dk, dv = fa.flash_attention_bwd_pallas(
        q, k, v, out, lse, do, block_q=bq, block_k=bk, interpret=True,
        dropout_rate=rate, dropout_mask=packed, dropout_mask_block_q=bq)
    gq, gk, gv = jax.grad(
        lambda q_, k_, v_: jnp.vdot(ref(q_, k_, v_), do),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq, dk, dv), (gq, gk, gv)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
