"""Checkpoint round-trip tests (role of reference
tests/unit/test_checkpointing.py:897)."""

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from tests.unit.simple_model import (base_engine_config, random_dataloader,
                                     simple_model_apply, simple_model_params)

HIDDEN = 16


def make_engine(stage=0, **overrides):
    cfg = base_engine_config(micro_batch=8, gas=1, **(overrides or {}))
    if stage:
        cfg["zero_optimization"] = {"stage": stage}
    params = simple_model_params(HIDDEN)
    engine, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                                    model_parameters=params)
    return engine


def run_steps(engine, n, seed=3):
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    it = iter(RepeatingLoader(random_dataloader(HIDDEN, 32, 8, seed=seed)))
    for _ in range(n):
        x, y = next(it)
        engine.backward(engine.forward(x, y))
        engine.step()
    return it


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_checkpoint_roundtrip_trajectory(tmp_path, stage):
    """Train → save → train 5 more; reload into a fresh engine → train 5 —
    trajectories must be identical (optimizer state incl. Adam moments and
    step counts must survive)."""
    e1 = make_engine(stage=stage)
    run_steps(e1, 3)
    e1.save_checkpoint(str(tmp_path), tag="ckpt")
    p_saved = jax.tree.map(np.asarray, e1.params)
    run_steps(e1, 5, seed=3)
    p_after = jax.tree.map(np.asarray, e1.params)

    e2 = make_engine(stage=stage)
    path, client = e2.load_checkpoint(str(tmp_path), tag="ckpt")
    assert client["global_steps"] == 3
    assert e2.global_steps == 3
    jax.tree.map(np.testing.assert_array_equal,
                 jax.tree.map(np.asarray, e2.params), p_saved)
    run_steps(e2, 5, seed=3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
        jax.tree.map(np.asarray, e2.params), p_after)


def test_latest_tag(tmp_path):
    e = make_engine()
    run_steps(e, 2)
    e.save_checkpoint(str(tmp_path))  # default tag global_step2
    path, _ = e.load_checkpoint(str(tmp_path))  # resolves via latest
    assert "global_step2" in path


def test_load_missing_dir(tmp_path):
    e = make_engine()
    with pytest.raises(FileNotFoundError):
        e.load_checkpoint(str(tmp_path / "nope"))


def test_load_module_only(tmp_path):
    e1 = make_engine(stage=2)
    run_steps(e1, 3)
    e1.save_checkpoint(str(tmp_path), tag="m")
    e2 = make_engine(stage=2)
    e2.load_checkpoint(str(tmp_path), tag="m", load_module_only=True,
                       load_optimizer_states=False)
    jax.tree.map(np.testing.assert_array_equal,
                 jax.tree.map(np.asarray, e2.params),
                 jax.tree.map(np.asarray, e1.params))
    assert e2.global_steps == 0  # counters untouched


def test_zero_resharding_on_load(tmp_path):
    """Save under stage 0 (replicated), load under stage 3 (sharded) — the
    reshard-on-load path (role of reference elastic checkpoint +
    MegatronSDLoader merge/split)."""
    e1 = make_engine(stage=0)
    run_steps(e1, 2)
    e1.save_checkpoint(str(tmp_path), tag="t")
    e3 = make_engine(
        zero_optimization={"stage": 3, "stage3_param_persistence_threshold": 0})
    e3.load_checkpoint(str(tmp_path), tag="t", load_optimizer_states=False)
    jax.tree.map(np.testing.assert_array_equal,
                 jax.tree.map(np.asarray, e3.params),
                 jax.tree.map(np.asarray, e1.params))
    # params must carry stage-3 shardings after load
    sharded = any(
        any(p is not None for p in leaf.sharding.spec)
        for leaf in jax.tree.leaves(e3.params))
    assert sharded


def test_consolidate_to_fp32(tmp_path):
    from deepspeed_tpu.runtime.checkpoint import consolidate_to_fp32
    e = make_engine(
        zero_optimization={"stage": 3, "stage3_param_persistence_threshold": 0})
    run_steps(e, 2)
    e.save_checkpoint(str(tmp_path), tag="fp32")
    weights = consolidate_to_fp32(str(tmp_path))
    total = sum(w.size for w in weights.values())
    expect = sum(leaf.size for leaf in jax.tree.leaves(e.params))
    assert total == expect
    assert all(w.dtype == np.float32 for w in weights.values())


def test_resume_is_bit_exact_with_dropout(tmp_path):
    """The saved engine PRNG stream makes resume bit-exact even with
    dropout ON — post-resume losses equal the uninterrupted run's exactly
    (the torch reference loses RNG streams on resume; VERDICT-grade
    reproducibility claim, so asserted with == not allclose)."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False,
                     embd_dropout=0.1, attn_dropout=0.1, hidden_dropout=0.1,
                     scan_layers=False)
    conf = {"train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10 ** 9,
            # threefry: stable across backends, so the equality holds on
            # any CI host
            "prng_impl": "threefry"}
    ids = np.random.RandomState(0).randint(0, 64, (8, 16)).astype(np.int32)

    def steps(engine, n):
        out = []
        for _ in range(n):
            loss = engine.forward(ids)
            engine.backward(loss)
            engine.step()
            out.append(float(loss))
        return out

    model = GPT2Model(cfg)
    e1, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    steps(e1, 3)
    e1.save_checkpoint(str(tmp_path), tag="mid")
    cont = steps(e1, 2)  # the uninterrupted continuation

    e2, _, _, _ = ds.initialize(
        model=model, config=conf,
        model_parameters=model.init_params(jax.random.PRNGKey(9)))
    e2.load_checkpoint(str(tmp_path), tag="mid")
    resumed = steps(e2, 2)
    assert resumed == cont, (resumed, cont)
