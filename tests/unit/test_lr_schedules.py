"""LR schedule tests (role of reference tests/unit/test_lr_schedulers.py:527)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle,
                                                WarmupDecayLR, WarmupLR,
                                                get_lr_schedule)


def test_warmup_lr():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.01, warmup_num_steps=10)
    assert float(s.lr_at(0)) == 0.0
    np.testing.assert_allclose(float(s.lr_at(5)), 0.005)
    np.testing.assert_allclose(float(s.lr_at(10)), 0.01)
    np.testing.assert_allclose(float(s.lr_at(100)), 0.01)


def test_warmup_decay_lr():
    s = WarmupDecayLR(total_num_steps=100, warmup_min_lr=0.0,
                      warmup_max_lr=0.01, warmup_num_steps=10)
    np.testing.assert_allclose(float(s.lr_at(5)), 0.005)
    np.testing.assert_allclose(float(s.lr_at(10)), 0.01)
    np.testing.assert_allclose(float(s.lr_at(55)), 0.005)
    np.testing.assert_allclose(float(s.lr_at(100)), 0.0, atol=1e-9)


def test_lr_range_test():
    s = LRRangeTest(lr_range_test_min_lr=1e-4, lr_range_test_step_size=10,
                    lr_range_test_step_rate=1.0)
    np.testing.assert_allclose(float(s.lr_at(0)), 1e-4)
    np.testing.assert_allclose(float(s.lr_at(10)), 2e-4)
    s2 = LRRangeTest(lr_range_test_min_lr=1e-4, lr_range_test_step_size=10,
                     lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
    np.testing.assert_allclose(float(s2.lr_at(9)), 1e-4)
    np.testing.assert_allclose(float(s2.lr_at(10)), 2e-4)


def test_one_cycle():
    s = OneCycle(cycle_min_lr=0.001, cycle_max_lr=0.01,
                 cycle_first_step_size=10)
    np.testing.assert_allclose(float(s.lr_at(0)), 0.001)
    np.testing.assert_allclose(float(s.lr_at(10)), 0.01)
    np.testing.assert_allclose(float(s.lr_at(20)), 0.001)
    # decay phase
    s2 = OneCycle(cycle_min_lr=0.001, cycle_max_lr=0.01,
                  cycle_first_step_size=10, decay_lr_rate=0.1,
                  decay_step_size=5)
    assert float(s2.lr_at(30)) < 0.001


def test_one_cycle_momentum():
    s = OneCycle(cycle_min_lr=0.001, cycle_max_lr=0.01,
                 cycle_first_step_size=10, cycle_momentum=True,
                 cycle_min_mom=0.8, cycle_max_mom=0.9)
    np.testing.assert_allclose(float(s.mom_at(0)), 0.9)
    np.testing.assert_allclose(float(s.mom_at(10)), 0.8)
    np.testing.assert_allclose(float(s.mom_at(20)), 0.9)


def test_get_lr_schedule_dispatch():
    s = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.1})
    assert isinstance(s, WarmupLR)
    with pytest.raises(ValueError):
        get_lr_schedule("Nope", {})


def test_step_protocol_and_state_dict():
    s = WarmupLR(warmup_max_lr=0.01, warmup_num_steps=10)
    for _ in range(5):
        s.step()
    assert s.last_batch_iteration == 4
    sd = s.state_dict()
    s2 = WarmupLR(warmup_max_lr=0.01, warmup_num_steps=10)
    s2.load_state_dict(sd)
    assert s2.last_batch_iteration == 4
