"""Topology rank-grid math (reference: tests/unit/test_topology.py:222)."""

from deepspeed_tpu.runtime.pipe.topology import (PipeDataParallelTopology,
                                                 PipeModelDataParallelTopology,
                                                 PipelineParallelGrid,
                                                 ProcessTopology)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3


def test_topology_dims():
    topo = ProcessTopology(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size() == 24
    assert topo.get_dim("a") == 2
    assert topo.get_dim("b") == 3
    assert topo.get_dim("c") == 4
    assert topo.get_dim("missing") == 0


def test_topology_coord_roundtrip():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    for rank in range(8):
        coord = topo.get_coord(rank)
        assert topo.get_rank(pipe=coord.pipe, data=coord.data) == rank


def test_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    pipe_lists = topo.get_axis_comm_lists("pipe")
    data_lists = topo.get_axis_comm_lists("data")
    # ranks: (p,d) -> p*2+d
    assert sorted(map(tuple, pipe_lists)) == [(0, 2), (1, 3)]
    assert sorted(map(tuple, data_lists)) == [(0, 1), (2, 3)]


def test_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.filter_match(pipe=0) == [0, 1, 2, 3]
    assert topo.filter_match(pipe=1, model=0) == [4, 6]


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    # data omitted by default, like the reference's checkpoint shard names
    assert "pipe_00" in topo.get_rank_repr(0)
    assert "data" not in topo.get_rank_repr(0)


def test_grid_stage_queries():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topology=topo, process_rank=5)
    coord = topo.get_coord(5)
    assert grid.get_stage_id() == coord.pipe
    assert grid.get_data_parallel_id() == coord.data
    assert grid.get_pipe_parallel_world_size() == 4
    assert grid.get_data_parallel_world_size() == 2
    # walking stage_to_global visits one rank per stage, same data coord
    ranks = [grid.stage_to_global(s) for s in range(4)]
    assert len(set(ranks)) == 4
    assert all(topo.get_coord(r).data == coord.data for r in ranks)


def test_p2p_matrix():
    topo = PipeDataParallelTopology(num_pp=3, num_dp=2)
    grid = PipelineParallelGrid(topology=topo)
    pairs = grid.p2p_matrix()
    # every non-final stage sends to its successor within each data column
    assert len(pairs) == 2 * 2
    for src, dst in pairs:
        c_src, c_dst = topo.get_coord(src), topo.get_coord(dst)
        assert c_dst.pipe == c_src.pipe + 1
        assert c_dst.data == c_src.data


def test_grid_from_mesh():
    import deepspeed_tpu
    deepspeed_tpu.initialize_mesh(pipe=4, data=-1)
    grid = PipelineParallelGrid()
    assert grid.get_pipe_parallel_world_size() == 4
    assert grid.get_data_parallel_world_size() == 2
