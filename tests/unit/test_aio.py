"""Async I/O engine + NVMe optimizer swapper tests (reference shapes:
tests/unit/test_aio.py:335 single/parallel read-write; ZeRO-Infinity step
behavior from stage3.py:2777)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.swap_tensor import (AsyncIOHandle,
                                               AsyncTensorSwapper,
                                               NVMeOffloadOptimizer,
                                               SwapBufferPool, aligned_empty)


def test_native_aio_builds():
    h = AsyncIOHandle()
    assert h.using_native, "host_aio.cpp must compile in this image"
    h.close()


def test_sync_read_write_roundtrip(tmp_path):
    h = AsyncIOHandle(block_size=4096, thread_count=2)
    data = np.random.RandomState(0).randn(10000).astype(np.float32)
    path = str(tmp_path / "t.bin")
    h.pwrite(data, path, async_op=False)
    out = np.empty_like(data)
    h.pread(out, path, async_op=False)
    np.testing.assert_array_equal(data, out)
    h.close()


def test_async_batch(tmp_path):
    h = AsyncIOHandle(block_size=8192, queue_depth=4, thread_count=4)
    arrays = [np.random.RandomState(i).randn(5000 + i).astype(np.float32)
              for i in range(8)]
    for i, a in enumerate(arrays):
        h.pwrite(a, str(tmp_path / f"a{i}.bin"), async_op=True)
    completed = h.wait()
    assert completed == 8
    outs = [np.empty_like(a) for a in arrays]
    for i, o in enumerate(outs):
        h.pread(o, str(tmp_path / f"a{i}.bin"), async_op=True)
    h.wait()
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)
    h.close()


def test_aligned_buffers():
    buf = aligned_empty(1000)
    assert buf.ctypes.data % 4096 == 0
    pool = SwapBufferPool(4096, 3)
    b1, b2 = pool.allocate(), pool.allocate()
    assert pool.free_count == 1
    pool.release(b1)
    assert pool.free_count == 2
    with pytest.raises(RuntimeError):
        pool.release(b1)
    pool.release(b2)


def test_async_tensor_swapper(tmp_path):
    h = AsyncIOHandle(thread_count=2)
    sw = AsyncTensorSwapper(h, buffer_bytes=64 * 1024, buffer_count=2)
    arrays = [np.random.RandomState(i).randn(1000).astype(np.float32)
              for i in range(5)]
    for i, a in enumerate(arrays):
        sw.swap_out(a, str(tmp_path / f"g{i}.bin"))  # >2 forces sync cycles
    sw.synchronize()
    for i, a in enumerate(arrays):
        out = np.empty_like(a)
        h.pread(out, str(tmp_path / f"g{i}.bin"), async_op=False)
        np.testing.assert_array_equal(a, out)
    h.close()


def _params():
    rs = np.random.RandomState(0)
    return {"w1": rs.randn(32, 16).astype(np.float32),
            "w2": rs.randn(16, 8).astype(np.float32),
            "count": np.array(0, np.int32)}


def test_nvme_optimizer_matches_host_adam(tmp_path):
    from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam
    params = _params()
    nvme = NVMeOffloadOptimizer(params, str(tmp_path / "swap"),
                                optimizer_name="adamw",
                                optimizer_params={"lr": 1e-2,
                                                  "weight_decay": 0.01})
    ram = DeepSpeedCPUAdam({k: v for k, v in params.items()},
                           lr=1e-2, weight_decay=0.01, adamw_mode=True)
    for i in range(4):
        rs = np.random.RandomState(100 + i)
        grads = {"w1": rs.randn(32, 16).astype(np.float32),
                 "w2": rs.randn(16, 8).astype(np.float32),
                 "count": np.zeros((), np.int32)}
        out = nvme.apply(grads, scale_inv=1.0, lr=None,
                         store_dtype=jnp.float32)
        assert out is not None
        ram.step(grads)
    master = nvme.gather_master()
    for k in ("w1", "w2"):
        np.testing.assert_allclose(master[k], ram.params[k],
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(out[k], ram.params[k],
                                   rtol=1e-6, atol=1e-7)
    assert out["count"].dtype == np.int32


def test_nvme_overflow_skips(tmp_path):
    params = _params()
    nvme = NVMeOffloadOptimizer(params, str(tmp_path / "swap"))
    grads = {"w1": np.full((32, 16), np.inf, np.float32),
             "w2": np.zeros((16, 8), np.float32),
             "count": np.zeros((), np.int32)}
    assert nvme.apply(grads, 1.0, None, jnp.float32) is None
    assert nvme.step_count() == 0


def test_nvme_state_roundtrip(tmp_path):
    params = _params()
    a = NVMeOffloadOptimizer(params, str(tmp_path / "a"))
    rs = np.random.RandomState(3)
    g = {"w1": rs.randn(32, 16).astype(np.float32),
         "w2": rs.randn(16, 8).astype(np.float32),
         "count": np.zeros((), np.int32)}
    a.apply(g, 1.0, None, jnp.float32)
    sd = a.state_dict()
    b = NVMeOffloadOptimizer(params, str(tmp_path / "b"))
    b.load_state_dict(sd)
    assert b.step_count() == 1
    ga = a.apply(g, 1.0, None, jnp.float32)
    gb = b.apply(g, 1.0, None, jnp.float32)
    for k in ("w1", "w2"):
        np.testing.assert_allclose(ga[k], gb[k], rtol=1e-6)


def test_engine_nvme_offload(tmp_path):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean(((h @ params["w2"]) - y) ** 2)

    rs = np.random.RandomState(0)
    params = {"w1": rs.randn(8, 16).astype(np.float32) * 0.3,
              "w2": rs.randn(16, 4).astype(np.float32) * 0.3}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path)}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg,
                                    model_parameters=params, mesh=mesh)
    x = rs.randn(16, 8).astype(np.float32)
    y = rs.randn(16, 4).astype(np.float32)
    losses = []
    for _ in range(6):
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert engine.global_steps == 6
    # states really live on disk
    import os
    files = os.listdir(str(tmp_path / "zero_stage_3" / "optimizer"))
    assert any("exp_avg" in f for f in files)
