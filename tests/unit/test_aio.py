"""Async I/O engine + NVMe optimizer swapper tests (reference shapes:
tests/unit/test_aio.py:335 single/parallel read-write; ZeRO-Infinity step
behavior from stage3.py:2777)."""

import gc

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.constants import AIO_BACKENDS
from deepspeed_tpu.runtime.swap_tensor import (AsyncIOHandle,
                                               AsyncTensorSwapper,
                                               NVMeOffloadOptimizer,
                                               SwapBufferPool, aligned_empty,
                                               io_uring_available,
                                               resolve_backend)
from deepspeed_tpu.runtime.swap_tensor import aio_handle as aio_handle_mod


def test_native_aio_builds():
    h = AsyncIOHandle()
    assert h.using_native, "host_aio.cpp must compile in this image"
    h.close()


# ---------------------------------------------------------------------- #
# backend selection (ISSUE 8: aio.backend io_uring|batched|threadpool|auto)
# ---------------------------------------------------------------------- #

def test_explicit_backends_roundtrip(tmp_path):
    """Every portable backend honors the same pread/pwrite/wait contract."""
    data = np.random.RandomState(0).randn(50_000).astype(np.float32)
    for backend in ("threadpool", "batched"):
        h = AsyncIOHandle(block_size=8192, queue_depth=4, thread_count=2,
                          backend=backend)
        assert h.using_native
        assert h.backend_name == backend
        path = str(tmp_path / f"{backend}.bin")
        h.pwrite(data, path, async_op=True)
        assert h.wait() == 1
        out = np.empty_like(data)
        h.pread(out, path, async_op=True)
        h.wait()
        np.testing.assert_array_equal(data, out)
        h.close()


def test_auto_backend_resolution():
    """auto = io_uring when the kernel delivers it, else the batched pool
    — never the plain threadpool (the sweep's slower submission path)."""
    resolved = resolve_backend("auto")
    if io_uring_available():
        assert resolved == "io_uring"
    else:
        assert resolved == "batched"
    h = AsyncIOHandle(backend="auto")
    assert h.backend_name == resolved
    h.close()


def test_io_uring_request_falls_back_loudly(monkeypatch):
    """Explicit io_uring on a host that cannot run it must WARN and fall
    back to batched — not silently measure the wrong engine."""
    if io_uring_available():
        pytest.skip("io_uring works here; fallback path not reachable")
    monkeypatch.setattr(aio_handle_mod, "_URING_FALLBACK_WARNED", False)
    warnings = []
    monkeypatch.setattr(aio_handle_mod.logger, "warning",
                        lambda msg, *a: warnings.append(str(msg)))
    h = AsyncIOHandle(backend="io_uring")
    assert h.backend_name == "batched"
    assert any("io_uring" in w and "falling back" in w for w in warnings)
    h.close()
    # the fallback warns ONCE per process, not once per handle
    h2 = AsyncIOHandle(backend="io_uring")
    assert h2.backend_name == "batched"
    assert sum("falling back" in w for w in warnings) == 1
    h2.close()


def test_unknown_backend_rejected():
    with pytest.raises(ValueError, match="aio.backend"):
        resolve_backend("libaio")
    assert "auto" in AIO_BACKENDS


def test_batched_odd_sizes_and_block_boundaries(tmp_path):
    """Coalesced preadv/pwritev runs must be byte-exact across non-divisible
    sizes (short tail chunk) and many-chunk batches."""
    h = AsyncIOHandle(block_size=4096, queue_depth=8, thread_count=2,
                      backend="batched")
    for n in (1, 4095, 4096, 4097, 40_001, 1_000_003):
        data = np.random.RandomState(n % 97).randint(
            0, 256, size=n, dtype=np.uint8)
        path = str(tmp_path / f"n{n}.bin")
        h.pwrite(data, path, async_op=False)
        out = np.empty_like(data)
        h.pread(out, path, async_op=False)
        np.testing.assert_array_equal(data, out)
    h.close()


# ---------------------------------------------------------------------- #
# raw-pointer contract (ISSUE 8 bugfix satellite)
# ---------------------------------------------------------------------- #

def test_non_contiguous_buffer_rejected(tmp_path):
    """The engines transfer through the raw base pointer: a strided view
    would be silently corrupted (native) or silently detached (fallback
    reshape copy) — both must be refused up front."""
    h = AsyncIOHandle(thread_count=1)
    data = np.zeros((64, 64), np.float32)
    strided = data[:, ::2]
    with pytest.raises(ValueError, match="contiguous"):
        h.pwrite(strided, str(tmp_path / "x.bin"))
    with pytest.raises(ValueError, match="contiguous"):
        h.pread(strided, str(tmp_path / "x.bin"))
    h.close()


def test_short_read_fails_loudly(tmp_path):
    """Reading more bytes than the file holds is a torn/truncated swap
    file — native engines return -EIO; the Python fallback must match
    rather than hand back a half-stale buffer."""
    data = np.arange(1000, dtype=np.float32)
    path = str(tmp_path / "t.bin")
    native = AsyncIOHandle(thread_count=1)
    native.pwrite(data, path)
    big = np.empty(2000, np.float32)
    with pytest.raises(OSError):
        native.pread(big, path, async_op=False)
    native.close()
    # python fallback parity
    h = AsyncIOHandle.__new__(AsyncIOHandle)
    h._lib = None
    h._handle = None
    h._sync_completed = 0
    h.backend = "python"
    with pytest.raises(OSError):
        h.pread(big, path, async_op=False)


def test_inflight_write_buffer_lifetime(tmp_path):
    """Async submissions borrow the caller's buffer until wait() — the
    swapper layers must pin their bounce buffers for the whole flight.
    Stress: many swap_outs from short-lived temporaries, a gc sweep mid-
    flight, then verify every byte landed."""
    h = AsyncIOHandle(block_size=4096, queue_depth=4, thread_count=2,
                      backend="batched")
    sw = AsyncTensorSwapper(h, buffer_bytes=256 * 1024, buffer_count=3)
    expect = {}
    ops = []
    for i in range(12):
        a = np.random.RandomState(i).randn(50_000).astype(np.float32)
        expect[i] = a.copy()
        ops.append((i, sw.swap_out(a, str(tmp_path / f"g{i}.bin"))))
        del a                      # the temporary dies while in flight
        gc.collect()
    sw.synchronize()
    assert all(op.done for _, op in ops)
    check = AsyncIOHandle(thread_count=1)
    for i, a in expect.items():
        out = np.empty_like(a)
        check.pread(out, str(tmp_path / f"g{i}.bin"), async_op=False)
        np.testing.assert_array_equal(a, out)
    check.close()
    h.close()


def test_failed_write_reclaims_buffer(tmp_path):
    """A write that errors must surface the I/O error AND return its
    buffer — leaking the slot would wedge later swap_outs behind a
    misleading 'pool exhausted' instead of the real failure."""
    h = AsyncIOHandle(thread_count=1)
    sw = AsyncTensorSwapper(h, buffer_bytes=64 * 1024, buffer_count=2)
    a = np.zeros(100, np.float32)
    # submission-time failure (missing directory)
    with pytest.raises(OSError):
        sw.swap_out(a, str(tmp_path / "no" / "such" / "dir" / "x.bin"))
    assert sw.pool.free_count == 2
    # completion-time failure (reaped at wait)
    op = sw.swap_out(a, str(tmp_path / "ok.bin"))
    import unittest.mock as mock
    with mock.patch.object(op._handle, "wait",
                           side_effect=OSError(28, "injected ENOSPC")):
        with pytest.raises(OSError):
            op.wait()
    assert op.done
    assert sw.pool.free_count == 2
    h.close()


def test_sweep_ceiling_missing_backend_is_none(tmp_path):
    """A per-backend ceilings artifact must never hand one backend
    another backend's number as its denominator."""
    from deepspeed_tpu.runtime.zero.infinity import load_sweep_ceiling
    art = tmp_path / "sweep.txt"
    art.write_text(
        '{"metric": "aio_best_config", "read_gbps": 9.9, "write_gbps": '
        '1.0, "ceilings": {"batched": {"read_gbps": 2.0, "write_gbps": '
        '0.5}}}\n')
    assert load_sweep_ceiling("batched", str(art)) == {
        "read_gbps": 2.0, "write_gbps": 0.5}
    assert load_sweep_ceiling("io_uring", str(art)) is None
    # pre-backend-axis artifact (no ceilings key): global best applies
    old = tmp_path / "old.txt"
    old.write_text('{"metric": "aio_best_config", "read_gbps": 2.78, '
                   '"write_gbps": 0.39}\n')
    assert load_sweep_ceiling("threadpool", str(old)) == {
        "read_gbps": 2.78, "write_gbps": 0.39}
    assert load_sweep_ceiling("anything", str(tmp_path / "absent")) is None


def test_inflight_write_handle_per_buffer_reclaim(tmp_path):
    """swap_out returns a real in-flight handle: waiting ONE write
    reclaims only its buffer (no wait-at-use drain of the whole pool)."""
    h = AsyncIOHandle(thread_count=2)
    sw = AsyncTensorSwapper(h, buffer_bytes=64 * 1024, buffer_count=2)
    a = np.random.RandomState(0).randn(1000).astype(np.float32)
    b = np.random.RandomState(1).randn(1000).astype(np.float32)
    op_a = sw.swap_out(a, str(tmp_path / "a.bin"))
    op_b = sw.swap_out(b, str(tmp_path / "b.bin"))
    assert sw.pool.free_count == 0
    op_a.wait()
    assert op_a.done and not op_b.done
    assert sw.pool.free_count == 1   # only a's buffer came back
    sw.synchronize()
    assert sw.pool.free_count == 2
    h.close()


def test_sync_read_write_roundtrip(tmp_path):
    h = AsyncIOHandle(block_size=4096, thread_count=2)
    data = np.random.RandomState(0).randn(10000).astype(np.float32)
    path = str(tmp_path / "t.bin")
    h.pwrite(data, path, async_op=False)
    out = np.empty_like(data)
    h.pread(out, path, async_op=False)
    np.testing.assert_array_equal(data, out)
    h.close()


def test_async_batch(tmp_path):
    h = AsyncIOHandle(block_size=8192, queue_depth=4, thread_count=4)
    arrays = [np.random.RandomState(i).randn(5000 + i).astype(np.float32)
              for i in range(8)]
    for i, a in enumerate(arrays):
        h.pwrite(a, str(tmp_path / f"a{i}.bin"), async_op=True)
    completed = h.wait()
    assert completed == 8
    outs = [np.empty_like(a) for a in arrays]
    for i, o in enumerate(outs):
        h.pread(o, str(tmp_path / f"a{i}.bin"), async_op=True)
    h.wait()
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)
    h.close()


def test_aligned_buffers():
    buf = aligned_empty(1000)
    assert buf.ctypes.data % 4096 == 0
    pool = SwapBufferPool(4096, 3)
    b1, b2 = pool.allocate(), pool.allocate()
    assert pool.free_count == 1
    pool.release(b1)
    assert pool.free_count == 2
    with pytest.raises(RuntimeError):
        pool.release(b1)
    pool.release(b2)


def test_async_tensor_swapper(tmp_path):
    h = AsyncIOHandle(thread_count=2)
    sw = AsyncTensorSwapper(h, buffer_bytes=64 * 1024, buffer_count=2)
    arrays = [np.random.RandomState(i).randn(1000).astype(np.float32)
              for i in range(5)]
    for i, a in enumerate(arrays):
        sw.swap_out(a, str(tmp_path / f"g{i}.bin"))  # >2 forces sync cycles
    sw.synchronize()
    for i, a in enumerate(arrays):
        out = np.empty_like(a)
        h.pread(out, str(tmp_path / f"g{i}.bin"), async_op=False)
        np.testing.assert_array_equal(a, out)
    h.close()


def _params():
    rs = np.random.RandomState(0)
    return {"w1": rs.randn(32, 16).astype(np.float32),
            "w2": rs.randn(16, 8).astype(np.float32),
            "count": np.array(0, np.int32)}


def test_nvme_optimizer_matches_host_adam(tmp_path):
    from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam
    params = _params()
    nvme = NVMeOffloadOptimizer(params, str(tmp_path / "swap"),
                                optimizer_name="adamw",
                                optimizer_params={"lr": 1e-2,
                                                  "weight_decay": 0.01})
    ram = DeepSpeedCPUAdam({k: v for k, v in params.items()},
                           lr=1e-2, weight_decay=0.01, adamw_mode=True)
    for i in range(4):
        rs = np.random.RandomState(100 + i)
        grads = {"w1": rs.randn(32, 16).astype(np.float32),
                 "w2": rs.randn(16, 8).astype(np.float32),
                 "count": np.zeros((), np.int32)}
        out = nvme.apply(grads, scale_inv=1.0, lr=None,
                         store_dtype=jnp.float32)
        assert out is not None
        ram.step(grads)
    master = nvme.gather_master()
    for k in ("w1", "w2"):
        np.testing.assert_allclose(master[k], ram.params[k],
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(out[k], ram.params[k],
                                   rtol=1e-6, atol=1e-7)
    assert out["count"].dtype == np.int32


def test_nvme_overflow_skips(tmp_path):
    params = _params()
    nvme = NVMeOffloadOptimizer(params, str(tmp_path / "swap"))
    grads = {"w1": np.full((32, 16), np.inf, np.float32),
             "w2": np.zeros((16, 8), np.float32),
             "count": np.zeros((), np.int32)}
    assert nvme.apply(grads, 1.0, None, jnp.float32) is None
    assert nvme.step_count() == 0


def test_nvme_state_roundtrip(tmp_path):
    params = _params()
    a = NVMeOffloadOptimizer(params, str(tmp_path / "a"))
    rs = np.random.RandomState(3)
    g = {"w1": rs.randn(32, 16).astype(np.float32),
         "w2": rs.randn(16, 8).astype(np.float32),
         "count": np.zeros((), np.int32)}
    a.apply(g, 1.0, None, jnp.float32)
    sd = a.state_dict()
    b = NVMeOffloadOptimizer(params, str(tmp_path / "b"))
    b.load_state_dict(sd)
    assert b.step_count() == 1
    ga = a.apply(g, 1.0, None, jnp.float32)
    gb = b.apply(g, 1.0, None, jnp.float32)
    for k in ("w1", "w2"):
        np.testing.assert_allclose(ga[k], gb[k], rtol=1e-6)


def test_engine_nvme_offload(tmp_path):
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=-1)

    def model(params, rng, x, y):
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean(((h @ params["w2"]) - y) ** 2)

    rs = np.random.RandomState(0)
    params = {"w1": rs.randn(8, 16).astype(np.float32) * 0.3,
              "w2": rs.randn(16, 4).astype(np.float32) * 0.3}
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path)}},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg,
                                    model_parameters=params, mesh=mesh)
    x = rs.randn(16, 8).astype(np.float32)
    y = rs.randn(16, 4).astype(np.float32)
    losses = []
    for _ in range(6):
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert engine.global_steps == 6
    # states really live on disk
    import os
    files = os.listdir(str(tmp_path / "zero_stage_3" / "optimizer"))
    assert any("exp_avg" in f for f in files)
