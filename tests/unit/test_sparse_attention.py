"""Block-sparse attention tests vs dense reference (reference shape:
tests/unit/test_sparse_attention.py:352 — sparse ops checked against dense
matmul/softmax with the layout materialized as a mask)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.flash_attention import mha_reference
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, VariableSparsityConfig,
    layout_to_gather_indices, pad_to_block_size, unpad_sequence_output)

H, BLOCK, S, D = 2, 16, 128, 8


def _qkv(seed=0, h=H, s=S, d=D):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (2, h, s, d), jnp.float32) for k in ks)


def _dense_with_layout_mask(q, k, v, layout, block, causal):
    """Dense attention with the layout expanded to an additive mask — the
    ground truth the sparse kernel must match exactly."""
    mask = np.kron(layout, np.ones((block, block)))  # [H, S, S]
    bias = np.where(mask > 0, 0.0, -1e30).astype(np.float32)[None]
    return mha_reference(q, k, v, causal=causal, bias=jnp.asarray(bias))


ALL_CONFIGS = [
    DenseSparsityConfig(num_heads=H, block=BLOCK),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                        num_global_blocks=1),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                        num_global_blocks=1, attention="unidirectional"),
    VariableSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                           local_window_blocks=[2, 4],
                           global_block_indices=[0]),
    BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                               num_sliding_window_blocks=3,
                               global_block_indices=[0]),
]


@pytest.mark.parametrize("cfg", ALL_CONFIGS,
                         ids=lambda c: type(c).__name__)
def test_sparse_matches_dense_masked(cfg):
    q, k, v = _qkv()
    attn = SparseSelfAttention(cfg)
    layout = attn.layout_for(S)[0]
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    out = attn(q, k, v, causal=causal)
    ref = _dense_with_layout_mask(q, k, v, layout, BLOCK, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_layouts_are_actually_sparse():
    # density at long sequence is what the O(S·w) claim rests on
    for cfg in ALL_CONFIGS[1:]:
        attn = SparseSelfAttention(cfg)
        assert attn.density(512) < 0.4, type(cfg).__name__
    assert SparseSelfAttention(ALL_CONFIGS[0]).density(512) == 1.0


def test_gather_indices_roundtrip():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=BLOCK,
                                     num_sliding_window_blocks=3)
    layout = cfg.make_layout(S)
    idx, valid = layout_to_gather_indices(layout)
    nb = S // BLOCK
    rebuilt = np.zeros_like(layout)
    for i in range(nb):
        rebuilt[0, i, idx[0, i][valid[0, i]]] = True
    np.testing.assert_array_equal(rebuilt, layout)


def test_causal_grad_flows():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    attn = SparseSelfAttention(cfg)
    q, k, v = _qkv()

    g = jax.grad(lambda q: jnp.sum(attn(q, k, v, causal=True) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0


def test_transformer_layer_sparse_integration():
    from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                               DeepSpeedTransformerLayer)
    sparse = BSLongformerSparsityConfig(num_heads=4, block=16,
                                        num_sliding_window_blocks=3)
    cfg = DeepSpeedTransformerConfig(
        hidden_size=32, heads=4, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0, bf16=False, causal=False,
        sparsity_config=sparse)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 32))
    out = layer(params, x, deterministic=True)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_gpt2_with_sparse_attention_trains():
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    sparse = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                                 attention="unidirectional")
    cfg = GPT2Config(vocab_size=128, n_positions=64, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0,
                     sparse_attention=sparse)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    # input length must be block-divisible; loss() keeps the full length
    # through attention and shifts on logits instead of truncating inputs
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    loss, grads = jax.value_and_grad(
        lambda p: model.loss(p, None, ids))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))


def test_pad_unpad():
    ids = jnp.ones((2, 30), jnp.int32)
    mask = jnp.ones((2, 30), jnp.int32)
    pad, pids, pmask = pad_to_block_size(16, ids, pad_token_id=0,
                                         attention_mask=mask)
    assert pad == 2 and pids.shape == (2, 32) and pmask.shape == (2, 32)
    assert int(pids[0, -1]) == 0 and int(pmask[0, -1]) == 0
    out = unpad_sequence_output(pad, jnp.zeros((2, 32, 8)))
    assert out.shape == (2, 30, 8)


def test_rejects_bad_seq_len():
    cfg = FixedSparsityConfig(num_heads=1, block=16)
    with pytest.raises(ValueError, match="divisible"):
        cfg.make_layout(100)


# --------------------------------------------------------------------------- #
# Pallas block-sparse flash kernel (interpret mode) vs the gather impl
# --------------------------------------------------------------------------- #
FLASH_CONFIGS = [
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                        num_global_blocks=1),
    BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                               num_sliding_window_blocks=3,
                               global_block_indices=[0]),
]


@pytest.mark.parametrize("cfg", FLASH_CONFIGS, ids=lambda c: type(c).__name__)
@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_flash_matches_dense_masked(cfg, causal):
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        block_sparse_flash_attention, layout_gather)
    q, k, v = _qkv()
    layout = cfg.make_layout(S)
    fidx, fvalid = layout_gather(layout)
    tidx, tvalid = layout_gather(layout, transpose=True)
    out = block_sparse_flash_attention(q, k, v, fidx, fvalid, tidx, tvalid,
                                       cfg.block, causal=causal,
                                       interpret=True)
    ref = _dense_with_layout_mask(q, k, v, layout, cfg.block, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_flash_grads_match_gather_impl(causal):
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        block_sparse_flash_attention, layout_gather)
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              num_global_blocks=1)
    q, k, v = _qkv(seed=3)
    layout = cfg.make_layout(S)
    fidx, fvalid = layout_gather(layout)
    tidx, tvalid = layout_gather(layout, transpose=True)

    def loss_flash(q, k, v):
        o = block_sparse_flash_attention(q, k, v, fidx, fvalid, tidx, tvalid,
                                         cfg.block, causal=causal,
                                         interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = _dense_with_layout_mask(q, k, v, layout, cfg.block, causal)
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_layout_gather_pads_with_last_valid():
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        layout_gather)
    layout = np.zeros((1, 4, 4), bool)
    layout[0, 0, [0, 2]] = True
    layout[0, 1, 1] = True
    layout[0, 2, :] = True
    layout[0, 3, 3] = True
    idx, valid = layout_gather(layout)
    assert idx.shape == (1, 4, 4)
    assert list(idx[0, 0]) == [0, 2, 2, 2]       # padded with last valid
    assert list(valid[0, 0]) == [1, 1, 0, 0]
    assert list(idx[0, 1]) == [1, 1, 1, 1]
    # transpose direction: who attends k-block 3? rows 2 and 3
    tidx, tvalid = layout_gather(layout, transpose=True)
    assert list(tidx[0, 3][: int(tvalid[0, 3].sum())]) == [2, 3]


def test_sparse_self_attention_impl_dispatch():
    """impl='pallas' must raise when the block is not lane-aligned (16 on
    this CPU run) instead of silently running the gather path."""
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              num_global_blocks=1)
    attn = SparseSelfAttention(cfg, impl="pallas")
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="pallas"):
        attn(q, k, v)
    # gather impl always works
    attn2 = SparseSelfAttention(cfg, impl="gather")
    out = attn2(q, k, v)
    assert out.shape == q.shape


def test_extend_position_embedding():
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        extend_position_embedding)
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=64, n_positions=32, hidden_size=16,
                     num_layers=1, num_heads=2, bf16=False)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ext = extend_position_embedding(params, 128)
    assert ext["wpe"].shape == (128, 16)
    np.testing.assert_array_equal(np.asarray(ext["wpe"][:32]),
                                  np.asarray(params["wpe"]))
    np.testing.assert_array_equal(np.asarray(ext["wpe"][32:64]),
                                  np.asarray(params["wpe"]))
    # original untouched; non-multiple rejected
    assert params["wpe"].shape == (32, 16)
    with pytest.raises(ValueError, match="multiple"):
        extend_position_embedding(params, 100)
    # extended model actually runs at the longer length
    cfg_long = GPT2Config(vocab_size=64, n_positions=128, hidden_size=16,
                          num_layers=1, num_heads=2, bf16=False)
    model_long = GPT2Model(cfg_long)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
    out = model_long.loss(ext, None, ids)
    assert np.isfinite(float(out))


# ---------------------------------------------------------------------- #
# Reference-parity: masks + rpe on the block softmax path and the
# standalone SDD/DSD/DDS ops (reference test_sparse_attention.py:256
# test_softmax / :296 test_matmul coverage)
# ---------------------------------------------------------------------- #
def _dense_reference_masked(q, k, v, layout, block, rpe=None, kp=None,
                            attn=None, kp_mode="add", attn_mode="add"):
    """Dense attention applying the reference softmax order: scale + rpe
    + key-padding + attn-mask, with layout blocks outside the pattern
    removed (softmax_fwd.tr)."""
    b, h, s, d = q.shape
    scores = np.einsum("bhqd,bhkd->bhqk", np.asarray(q, np.float64),
                       np.asarray(k, np.float64)) / np.sqrt(d)
    if rpe is not None:
        r = np.asarray(rpe, np.float64)
        while r.ndim < 4:
            r = r[None]
        scores = scores + r
    if kp is not None:
        kpf = np.asarray(kp, np.float64)
        if kp_mode == "mul":
            kpf = np.where(kpf == 0, -np.inf, 0.0)
        scores = scores + kpf[:, None, None, :]
    if attn is not None:
        am = np.asarray(attn, np.float64)
        if attn_mode == "mul":
            am = np.where(am == 0, -np.inf, 0.0)
        scores = scores + am[None, None]
    lay = np.kron(layout, np.ones((block, block)))  # [H, S, S]
    scores = np.where(lay[None] > 0, scores, -np.inf)
    m = scores.max(-1, keepdims=True)
    p = np.exp(scores - np.where(np.isfinite(m), m, 0.0))
    p = np.where(np.isfinite(scores), p, 0.0)
    denom = np.maximum(p.sum(-1, keepdims=True), 1e-30)
    return np.einsum("bhqk,bhkd->bhqd", p / denom, np.asarray(v, np.float64))


@pytest.mark.parametrize("kp_mode,attn_mode", [("add", "add"),
                                               ("mul", "mul"),
                                               ("add", "mul")])
def test_sparse_attention_masks_and_rpe(kp_mode, attn_mode):
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              num_global_blocks=1)
    layout = cfg.make_layout(S)
    q, k, v = _qkv(3)
    rs = np.random.RandomState(0)
    rpe = (rs.randn(H, S, S) * 0.5).astype(np.float32)
    if kp_mode == "add":
        kp = np.where(rs.rand(2, S) < 0.2, -10000.0, 0.0).astype(np.float32)
    else:
        kp = (rs.rand(2, S) >= 0.2).astype(np.float32)
    if attn_mode == "add":
        attn = np.triu(np.full((S, S), -10000.0, np.float32), k=1)
    else:
        attn = np.tril(np.ones((S, S), np.float32))
    sa = SparseSelfAttention(cfg, key_padding_mask_mode=kp_mode,
                             attn_mask_mode=attn_mode)
    out = sa(q, k, v, rpe=jnp.asarray(rpe), key_padding_mask=jnp.asarray(kp),
             attn_mask=jnp.asarray(attn))
    ref = _dense_reference_masked(q, k, v, layout, BLOCK, rpe=rpe, kp=kp,
                                 attn=attn, kp_mode=kp_mode,
                                 attn_mode=attn_mode)
    # fp32 gather-softmax vs an fp64 dense reference.  atol covers the
    # near-fully-masked rows (-10000 additive masks): their softmax
    # weights sit at the fp32 rounding floor, where single elements
    # drift a few 1e-4 on the CPU backend (seed ledger,
    # docs/COVERAGE.md) — the structural agreement is what's asserted.
    np.testing.assert_allclose(np.asarray(out, np.float64), ref,
                               rtol=1e-3, atol=5e-4)


def test_sparse_attention_masks_grad_flows():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    q, k, v = _qkv(4)
    kp = (np.random.RandomState(1).rand(2, S) >= 0.25).astype(np.float32)
    sa = SparseSelfAttention(cfg, key_padding_mask_mode="mul")

    def loss(q, k, v):
        return jnp.sum(sa(q, k, v, key_padding_mask=jnp.asarray(kp)) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0


def test_block_sparse_matmul_modes():
    """SDD/DSD/DDS vs dense references (reference matmul.py:749 +
    test_sparse_attention.py:271 run_matmul_reference)."""
    from deepspeed_tpu.ops.sparse_attention import MatMul, block_coords

    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              num_global_blocks=1)
    layout = cfg.make_layout(S)
    hs, is_, js = block_coords(layout)
    rs = np.random.RandomState(2)
    a = jnp.asarray(rs.randn(2, H, S, D).astype(np.float32))
    b = jnp.asarray(rs.randn(2, H, S, D).astype(np.float32))

    # sdd nt: q @ k^T at the layout blocks
    sdd = MatMul(layout, BLOCK, "sdd", trans_a=False, trans_b=True)
    w = sdd(a, b)
    assert w.shape == (2, len(hs), BLOCK, BLOCK)
    dense = np.einsum("bhqd,bhkd->bhqk", np.asarray(a), np.asarray(b))
    for n in range(len(hs)):
        blockref = dense[:, hs[n], is_[n] * BLOCK:(is_[n] + 1) * BLOCK,
                         js[n] * BLOCK:(js[n] + 1) * BLOCK]
        np.testing.assert_allclose(np.asarray(w[:, n]), blockref,
                                   rtol=2e-4, atol=2e-4)

    # dsd nn: sparse @ dense -> dense
    dsd = MatMul(layout, BLOCK, "dsd", trans_a=False, trans_b=False)
    out = dsd(w, b)
    wd = np.zeros((2, H, S, S), np.float32)
    for n in range(len(hs)):
        wd[:, hs[n], is_[n] * BLOCK:(is_[n] + 1) * BLOCK,
           js[n] * BLOCK:(js[n] + 1) * BLOCK] = np.asarray(w[:, n])
    np.testing.assert_allclose(np.asarray(out),
                               np.einsum("bhqk,bhkd->bhqd", wd,
                                         np.asarray(b)),
                               rtol=2e-4, atol=2e-3)

    # dds nn: dense @ sparse -> dense
    dds = MatMul(layout, BLOCK, "dds", trans_a=False, trans_b=False)
    c = jnp.asarray(rs.randn(2, H, D, S).astype(np.float32))
    out2 = dds(c, w)
    np.testing.assert_allclose(np.asarray(out2),
                               np.einsum("bhmq,bhqk->bhmk", np.asarray(c),
                                         wd),
                               rtol=2e-4, atol=2e-3)

    # autodiff flows through all modes (the reference needs hand-written
    # backward kernels; gather/einsum transposes mechanically)
    def loss(a_, b_):
        return jnp.sum(dsd(sdd(a_, b_), b_) ** 2)

    ga, gb = jax.grad(loss, argnums=(0, 1))(a, b)
    assert np.isfinite(np.asarray(ga)).all()
    assert float(jnp.abs(gb).max()) > 0


@pytest.mark.parametrize("kp_mode,attn_mode", [("add", "add"),
                                               ("mul", "mul")])
def test_block_sparse_softmax_standalone(kp_mode, attn_mode):
    """The standalone Softmax op on the sparse format (reference
    softmax.py:315 + test_softmax:256)."""
    from deepspeed_tpu.ops.sparse_attention import (MatMul, Softmax,
                                                    block_coords)

    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    layout = cfg.make_layout(S)
    hs, is_, js = block_coords(layout)
    rs = np.random.RandomState(3)
    q, k, v = _qkv(5)
    sdd = MatMul(layout, BLOCK, "sdd", trans_a=False, trans_b=True)
    w = sdd(q, k)
    scale = 1.0 / np.sqrt(D)
    rpe = (rs.randn(H, S, S) * 0.3).astype(np.float32)
    if kp_mode == "add":
        kp = np.where(rs.rand(2, S) < 0.2, -10000.0, 0.0).astype(np.float32)
        attn = np.triu(np.full((S, S), -10000.0, np.float32), k=1)
    else:
        kp = (rs.rand(2, S) >= 0.2).astype(np.float32)
        attn = np.tril(np.ones((S, S), np.float32))
    sm = Softmax(layout, BLOCK)
    p = sm(w, scale=scale, rpe=jnp.asarray(rpe),
           key_padding_mask=jnp.asarray(kp), attn_mask=jnp.asarray(attn),
           key_padding_mask_mode=kp_mode, attn_mask_mode=attn_mode)
    dsd = MatMul(layout, BLOCK, "dsd", trans_a=False, trans_b=False)
    out = dsd(p, v)
    ref = _dense_reference_masked(q, k, v, layout, BLOCK, rpe=rpe, kp=kp,
                                 attn=attn, kp_mode=kp_mode,
                                 attn_mode=attn_mode)
    # fp32 gather-softmax vs an fp64 dense reference
    np.testing.assert_allclose(np.asarray(out, np.float64), ref,
                               rtol=1e-3, atol=1e-4)


def test_bert_sparse_self_attention():
    """BertSparseSelfAttention module (reference
    bert_sparse_self_attention.py:78): shapes, padding-mask effect, and
    equality with calling SparseSelfAttention directly."""
    from dataclasses import dataclass

    from deepspeed_tpu.ops.sparse_attention import BertSparseSelfAttention

    @dataclass
    class Cfg:
        hidden_size: int = H * D
        num_attention_heads: int = H

    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    mod = BertSparseSelfAttention(Cfg(), cfg, key_padding_mask_mode="mul")
    params = mod.init_params(jax.random.PRNGKey(0))
    rs = np.random.RandomState(4)
    hidden = jnp.asarray(rs.randn(2, S, H * D).astype(np.float32))
    mask = np.ones((2, S), np.float32)
    mask[:, S // 2:] = 0.0  # right half padded
    out = mod.apply(params, hidden, attention_mask=jnp.asarray(mask))
    assert out.shape == (2, S, H * D)
    out_nomask = mod.apply(params, hidden)
    # masking the right half must change the left half's context
    assert float(jnp.abs(out[:, :S // 2] -
                         out_nomask[:, :S // 2]).max()) > 1e-6
    # head-merge layout matches a manual SparseSelfAttention call
    q = hidden @ params["query"]["kernel"] + params["query"]["bias"]
    k = hidden @ params["key"]["kernel"] + params["key"]["bias"]
    v = hidden @ params["value"]["kernel"] + params["value"]["bias"]

    def split(t):
        return t.reshape(2, S, H, D).transpose(0, 2, 1, 3)

    direct = mod.sparse_self_attention(
        split(q), split(k), split(v), key_padding_mask=jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(direct.transpose(0, 2, 1, 3).reshape(2, S, H * D)),
        rtol=1e-6, atol=1e-6)


def test_bert_sparse_add_mode_default():
    """The DEFAULT key_padding_mask_mode='add' path (review r4): an
    additive HF-style mask (0 keep / -10000 pad) must actually mask."""
    from dataclasses import dataclass

    from deepspeed_tpu.ops.sparse_attention import BertSparseSelfAttention

    @dataclass
    class Cfg:
        hidden_size: int = H * D
        num_attention_heads: int = H

    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    mod = BertSparseSelfAttention(Cfg(), cfg)  # default 'add'
    params = mod.init_params(jax.random.PRNGKey(0))
    rs = np.random.RandomState(5)
    hidden = jnp.asarray(rs.randn(2, S, H * D).astype(np.float32))
    add_mask = np.zeros((2, S), np.float32)
    add_mask[:, S // 2:] = -10000.0
    out = mod.apply(params, hidden, attention_mask=jnp.asarray(add_mask))
    out_nomask = mod.apply(params, hidden)
    # additive -10000 on the right half must change the left half
    assert float(jnp.abs(out[:, :S // 2] -
                         out_nomask[:, :S // 2]).max()) > 1e-6
    # and match the 'mul' module given the equivalent 1/0 mask
    mul_mod = BertSparseSelfAttention(Cfg(), cfg,
                                      key_padding_mask_mode="mul")
    mul_mask = (add_mask == 0).astype(np.float32)
    out_mul = mul_mod.apply(params, hidden,
                            attention_mask=jnp.asarray(mul_mask))
    np.testing.assert_allclose(np.asarray(out[:, :S // 2]),
                               np.asarray(out_mul[:, :S // 2]),
                               rtol=1e-4, atol=1e-5)


def test_double_mul_mask_fully_masked_row_is_zero_not_nan():
    """Stacked mul-mode masks on a fully-masked row must produce 0, not
    NaN from -inf overflow (review r4)."""
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    q, k, v = _qkv(6)
    kp = np.ones((2, S), np.float32)
    kp[0] = 0.0  # batch row 0 fully padded
    attn = np.ones((S, S), np.float32)
    attn[:, :] = 0.0  # attn mask also zeroes everything
    sa = SparseSelfAttention(cfg, key_padding_mask_mode="mul",
                             attn_mask_mode="mul")
    out = np.asarray(sa(q, k, v, key_padding_mask=jnp.asarray(kp),
                        attn_mask=jnp.asarray(attn)))
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[0], 0.0)


def test_transformer_layer_sparse_mask_routing():
    """The fused layer routes its additive mask into the sparse path
    (review r4): [B,1,1,S] -> key padding; bad shapes raise."""
    from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                               DeepSpeedTransformerLayer)

    cfg = DeepSpeedTransformerConfig(
        hidden_size=H * D, heads=H, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0, bf16=False,
        sparsity_config=FixedSparsityConfig(num_heads=H, block=BLOCK,
                                            num_local_blocks=4))
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(7).randn(2, S, H * D)
                    .astype(np.float32))
    kp4 = np.zeros((2, 1, 1, S), np.float32)
    kp4[:, :, :, S // 2:] = -10000.0
    out_masked = layer(params, x, attn_mask=jnp.asarray(kp4),
                       deterministic=True)
    out_plain = layer(params, x, deterministic=True)
    assert float(jnp.abs(out_masked[:, :S // 2] -
                         out_plain[:, :S // 2]).max()) > 1e-6
    with pytest.raises(NotImplementedError, match="2D"):
        layer(params, x, attn_mask=jnp.zeros((2, 1, S, S), jnp.float32),
              deterministic=True)


def test_compressed_int8_wire_guards():
    from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
    from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce

    reset_mesh_context()
    mesh = initialize_mesh(data=-1)
    x = jnp.zeros((mesh.data_parallel_world_size, 8), jnp.float32)
    with pytest.raises(ValueError, match="wire"):
        compressed_allreduce(x, x, mesh_ctx=mesh, wire="int4")
    reset_mesh_context()
