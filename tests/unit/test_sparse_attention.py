"""Block-sparse attention tests vs dense reference (reference shape:
tests/unit/test_sparse_attention.py:352 — sparse ops checked against dense
matmul/softmax with the layout materialized as a mask)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.flash_attention import mha_reference
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, VariableSparsityConfig,
    layout_to_gather_indices, pad_to_block_size, unpad_sequence_output)

H, BLOCK, S, D = 2, 16, 128, 8


def _qkv(seed=0, h=H, s=S, d=D):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (2, h, s, d), jnp.float32) for k in ks)


def _dense_with_layout_mask(q, k, v, layout, block, causal):
    """Dense attention with the layout expanded to an additive mask — the
    ground truth the sparse kernel must match exactly."""
    mask = np.kron(layout, np.ones((block, block)))  # [H, S, S]
    bias = np.where(mask > 0, 0.0, -1e30).astype(np.float32)[None]
    return mha_reference(q, k, v, causal=causal, bias=jnp.asarray(bias))


ALL_CONFIGS = [
    DenseSparsityConfig(num_heads=H, block=BLOCK),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                        num_global_blocks=1),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                        num_global_blocks=1, attention="unidirectional"),
    VariableSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                           local_window_blocks=[2, 4],
                           global_block_indices=[0]),
    BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                               num_sliding_window_blocks=3,
                               global_block_indices=[0]),
]


@pytest.mark.parametrize("cfg", ALL_CONFIGS,
                         ids=lambda c: type(c).__name__)
def test_sparse_matches_dense_masked(cfg):
    q, k, v = _qkv()
    attn = SparseSelfAttention(cfg)
    layout = attn.layout_for(S)[0]
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    out = attn(q, k, v, causal=causal)
    ref = _dense_with_layout_mask(q, k, v, layout, BLOCK, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_layouts_are_actually_sparse():
    # density at long sequence is what the O(S·w) claim rests on
    for cfg in ALL_CONFIGS[1:]:
        attn = SparseSelfAttention(cfg)
        assert attn.density(512) < 0.4, type(cfg).__name__
    assert SparseSelfAttention(ALL_CONFIGS[0]).density(512) == 1.0


def test_gather_indices_roundtrip():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=BLOCK,
                                     num_sliding_window_blocks=3)
    layout = cfg.make_layout(S)
    idx, valid = layout_to_gather_indices(layout)
    nb = S // BLOCK
    rebuilt = np.zeros_like(layout)
    for i in range(nb):
        rebuilt[0, i, idx[0, i][valid[0, i]]] = True
    np.testing.assert_array_equal(rebuilt, layout)


def test_causal_grad_flows():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    attn = SparseSelfAttention(cfg)
    q, k, v = _qkv()

    g = jax.grad(lambda q: jnp.sum(attn(q, k, v, causal=True) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0


def test_transformer_layer_sparse_integration():
    from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                               DeepSpeedTransformerLayer)
    sparse = BSLongformerSparsityConfig(num_heads=4, block=16,
                                        num_sliding_window_blocks=3)
    cfg = DeepSpeedTransformerConfig(
        hidden_size=32, heads=4, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0, bf16=False, causal=False,
        sparsity_config=sparse)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 32))
    out = layer(params, x, deterministic=True)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_gpt2_with_sparse_attention_trains():
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    sparse = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                                 attention="unidirectional")
    cfg = GPT2Config(vocab_size=128, n_positions=64, hidden_size=32,
                     num_layers=2, num_heads=4, bf16=False, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0,
                     sparse_attention=sparse)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    # input length must be block-divisible; loss() keeps the full length
    # through attention and shifts on logits instead of truncating inputs
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    loss, grads = jax.value_and_grad(
        lambda p: model.loss(p, None, ids))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))


def test_pad_unpad():
    ids = jnp.ones((2, 30), jnp.int32)
    mask = jnp.ones((2, 30), jnp.int32)
    pad, pids, pmask = pad_to_block_size(16, ids, pad_token_id=0,
                                         attention_mask=mask)
    assert pad == 2 and pids.shape == (2, 32) and pmask.shape == (2, 32)
    assert int(pids[0, -1]) == 0 and int(pmask[0, -1]) == 0
    out = unpad_sequence_output(pad, jnp.zeros((2, 32, 8)))
    assert out.shape == (2, 30, 8)


def test_rejects_bad_seq_len():
    cfg = FixedSparsityConfig(num_heads=1, block=16)
    with pytest.raises(ValueError, match="divisible"):
        cfg.make_layout(100)


# --------------------------------------------------------------------------- #
# Pallas block-sparse flash kernel (interpret mode) vs the gather impl
# --------------------------------------------------------------------------- #
FLASH_CONFIGS = [
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                        num_global_blocks=1),
    BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                               num_sliding_window_blocks=3,
                               global_block_indices=[0]),
]


@pytest.mark.parametrize("cfg", FLASH_CONFIGS, ids=lambda c: type(c).__name__)
@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_flash_matches_dense_masked(cfg, causal):
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        block_sparse_flash_attention, layout_gather)
    q, k, v = _qkv()
    layout = cfg.make_layout(S)
    fidx, fvalid = layout_gather(layout)
    tidx, tvalid = layout_gather(layout, transpose=True)
    out = block_sparse_flash_attention(q, k, v, fidx, fvalid, tidx, tvalid,
                                       cfg.block, causal=causal,
                                       interpret=True)
    ref = _dense_with_layout_mask(q, k, v, layout, cfg.block, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_flash_grads_match_gather_impl(causal):
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        block_sparse_flash_attention, layout_gather)
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              num_global_blocks=1)
    q, k, v = _qkv(seed=3)
    layout = cfg.make_layout(S)
    fidx, fvalid = layout_gather(layout)
    tidx, tvalid = layout_gather(layout, transpose=True)

    def loss_flash(q, k, v):
        o = block_sparse_flash_attention(q, k, v, fidx, fvalid, tidx, tvalid,
                                         cfg.block, causal=causal,
                                         interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = _dense_with_layout_mask(q, k, v, layout, cfg.block, causal)
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_layout_gather_pads_with_last_valid():
    from deepspeed_tpu.ops.sparse_attention.block_sparse_flash import (
        layout_gather)
    layout = np.zeros((1, 4, 4), bool)
    layout[0, 0, [0, 2]] = True
    layout[0, 1, 1] = True
    layout[0, 2, :] = True
    layout[0, 3, 3] = True
    idx, valid = layout_gather(layout)
    assert idx.shape == (1, 4, 4)
    assert list(idx[0, 0]) == [0, 2, 2, 2]       # padded with last valid
    assert list(valid[0, 0]) == [1, 1, 0, 0]
    assert list(idx[0, 1]) == [1, 1, 1, 1]
    # transpose direction: who attends k-block 3? rows 2 and 3
    tidx, tvalid = layout_gather(layout, transpose=True)
    assert list(tidx[0, 3][: int(tvalid[0, 3].sum())]) == [2, 3]


def test_sparse_self_attention_impl_dispatch():
    """impl='pallas' must raise when the block is not lane-aligned (16 on
    this CPU run) instead of silently running the gather path."""
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              num_global_blocks=1)
    attn = SparseSelfAttention(cfg, impl="pallas")
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="pallas"):
        attn(q, k, v)
    # gather impl always works
    attn2 = SparseSelfAttention(cfg, impl="gather")
    out = attn2(q, k, v)
    assert out.shape == q.shape


def test_extend_position_embedding():
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        extend_position_embedding)
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=64, n_positions=32, hidden_size=16,
                     num_layers=1, num_heads=2, bf16=False)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ext = extend_position_embedding(params, 128)
    assert ext["wpe"].shape == (128, 16)
    np.testing.assert_array_equal(np.asarray(ext["wpe"][:32]),
                                  np.asarray(params["wpe"]))
    np.testing.assert_array_equal(np.asarray(ext["wpe"][32:64]),
                                  np.asarray(params["wpe"]))
    # original untouched; non-multiple rejected
    assert params["wpe"].shape == (32, 16)
    with pytest.raises(ValueError, match="multiple"):
        extend_position_embedding(params, 100)
    # extended model actually runs at the longer length
    cfg_long = GPT2Config(vocab_size=64, n_positions=128, hidden_size=16,
                          num_layers=1, num_heads=2, bf16=False)
    model_long = GPT2Model(cfg_long)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
    out = model_long.loss(ext, None, ids)
    assert np.isfinite(float(out))
