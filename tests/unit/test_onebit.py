"""1-bit optimizer wire tier (zero_optimization.low_bandwidth.onebit;
docs/onebit.md).

Covers the round-20 acceptance surface:
  - warmup identity: with the tier armed, every pre-freeze step is
    byte-identical to the same OneBit optimizer without the tier (the
    dense program IS the warmup program), and tracks a dense Adam twin;
  - the freeze-boundary phase switch is exactly ONE planned retrace
    (RecompileGuard.planned_retraces) and flips the engine's phase;
  - compression numerics: exact fp32 error-feedback round-trip on
    dyadic-rational inputs, packed-wire consensus + mean preservation
    under shard_map (flat and hierarchical), LAMB trust ratio computed
    on the raw (lr-normalised) step;
  - static pricing: the per-leaf wire-cost gate, the onebit_bytes
    breakout in collective_wire_bytes, and the >=4x jaxpr+HLO wire
    reduction of the compressed program vs its dense twin;
  - e2e: 6-step parity across the switch, fp16 forced-overflow skip
    leaves params/momentum/wire-error untouched, checkpoint/resume on
    both sides of freeze_step restores the phase as program identity,
    fused-vs-modular parity through the switch;
  - config conflicts (config.py _validate_onebit).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from tests.unit.simple_model import (base_engine_config, simple_model_apply,
                                     simple_model_params)

HIDDEN = 16
MICRO = 8


# --------------------------------------------------------------------- #
# helpers
# --------------------------------------------------------------------- #
def make_engine(tier=True, optimizer="OneBitAdam", freeze=3, lr=1e-3,
                stage=2, hidden=HIDDEN, gas=1, analysis=None, fused=False,
                extra=None, opt_params=None):
    ds.reset_mesh_context()
    cfg = base_engine_config(micro_batch=MICRO, gas=gas)
    params = {"lr": lr}
    if optimizer.lower().startswith("onebit"):
        params["freeze_step"] = freeze
    if opt_params:
        params.update(opt_params)
    cfg["optimizer"] = {"type": optimizer, "params": params}
    if stage:
        cfg["zero_optimization"] = {"stage": stage}
    if tier:
        cfg.setdefault("zero_optimization", {})
        cfg["zero_optimization"]["low_bandwidth"] = {"onebit": True}
    if analysis:
        cfg["analysis"] = analysis
    if fused:
        cfg["fused_step"] = {"enabled": True}
    if extra:
        cfg.update(extra)
    engine, _, _, _ = ds.initialize(model=simple_model_apply, config=cfg,
                                    model_parameters=simple_model_params(
                                        hidden))
    return engine


def batches(n, hidden=HIDDEN, seed=7):
    rng = np.random.RandomState(seed)
    return [(rng.normal(0, 1, (MICRO, hidden)).astype(np.float32),
             rng.normal(0, 1, (MICRO,)).astype(np.float32))
            for _ in range(n)]


def run_steps(engine, data):
    losses = []
    for x, y in data:
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(np.asarray(loss).item())
    return losses


def assert_tree_equal(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


def assert_tree_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


# --------------------------------------------------------------------- #
# warmup identity + phase switch
# --------------------------------------------------------------------- #
def test_warmup_bitwise_vs_numerics_only():
    """Before freeze_step the tier must be INERT: byte-identical params
    and optimizer state vs the same OneBitAdam without the wire tier."""
    data = batches(3)
    e_tier = make_engine(tier=True, freeze=4)
    run_steps(e_tier, data)
    e_plain = make_engine(tier=False, freeze=4)
    run_steps(e_plain, data)
    assert e_tier._onebit_phase == "warmup"
    assert_tree_equal(e_tier.params, e_plain.params)
    assert_tree_equal(e_tier.opt_state, e_plain.opt_state)


def test_warmup_tracks_dense_adam():
    data = batches(3)
    e_tier = make_engine(tier=True, freeze=4)
    l1 = run_steps(e_tier, data)
    e_adam = make_engine(tier=False, optimizer="Adam")
    l2 = run_steps(e_adam, data)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert_tree_close(e_tier.params, e_adam.params, rtol=1e-4, atol=1e-6)


def test_phase_switch_single_planned_retrace():
    """Crossing freeze_step re-derives the step programs exactly once,
    announced to the RecompileGuard as a PLANNED retrace — lockstep
    stays clean and max_retraces absorbs the switch."""
    e = make_engine(freeze=2, analysis={"mode": "warn"})
    assert e._onebit_phase == "warmup"
    run_steps(e, batches(4))
    assert e._onebit_phase == "compressed"
    c = e._recompile_guard.counters()
    assert c["planned_retraces"] == 1, c
    assert c["retraces_seen"] == 1, c


# --------------------------------------------------------------------- #
# compression numerics
# --------------------------------------------------------------------- #
def test_sign_compress_exact_fp32_roundtrip():
    """cm + residual must reconstruct the compensated momentum EXACTLY
    (bitwise) on dyadic-rational inputs — the error feedback loses
    nothing to the wire, it only defers it."""
    from deepspeed_tpu.runtime.comm.onebit import _sign_compress

    rs = np.random.RandomState(3)
    m = jnp.asarray(rs.randint(-8, 9, 256) * 0.25, jnp.float32)
    err = jnp.asarray(rs.randint(-8, 9, 256) * 0.25, jnp.float32)
    cm, resid = _sign_compress(m, err)
    # scale = mean|comp| of 256 dyadic values: exact in fp32, so the
    # round-trip is exact too
    np.testing.assert_array_equal(np.asarray(cm + resid),
                                  np.asarray(m + err))
    # the wire tensor really is 1-bit + scale: one magnitude everywhere
    mags = np.unique(np.abs(np.asarray(cm)))
    assert len(mags[mags > 0]) == 1


def test_packed_wire_consensus_and_mean_preservation():
    """wire="packed" (the int8-lane sign pack): every worker decodes the
    identical reduced tensor, and error feedback preserves the mean over
    rounds; group_size == world degenerates to the exact dense mean."""
    from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
    from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce

    reset_mesh_context()
    mesh = initialize_mesh(data=-1)
    w = mesh.data_parallel_world_size
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(w, 64), jnp.float32)
    true_mean = np.asarray(x).mean(axis=0)

    red, err = compressed_allreduce(x, jnp.zeros_like(x), mesh_ctx=mesh,
                                    wire="packed", block=8)
    red = np.asarray(red)
    np.testing.assert_array_equal(red[0], red[-1])

    def avg_err(n, group_size=0):
        f = jax.jit(lambda a, e: compressed_allreduce(
            a, e, mesh_ctx=mesh, wire="packed", block=8,
            group_size=group_size))
        acc = np.zeros(64)
        e = jnp.zeros_like(x)
        for _ in range(n):
            red, e = f(x, e)
            acc += np.asarray(red)[0]
        return np.abs(acc / n - true_mean).max()

    # the two-stage scheme compensates the server-side residual only at
    # the owning worker, so per-round it is NOT conservative — but the
    # accumulated average still closes on the true mean, and beats a
    # single uncompensated round
    single = np.abs(red[0] - true_mean).max()
    e8, e128 = avg_err(8), avg_err(128)
    assert e128 < 0.75 * e8, (e8, e128)
    assert e128 < 0.35, e128
    assert e128 < single, (e128, single)
    # hierarchical (Frontier-style): intra-group dense, cross-group 1-bit
    assert avg_err(64, group_size=2) < 0.35
    # group covering the whole world -> pure dense mean, exact
    red, _ = compressed_allreduce(x, jnp.zeros_like(x), mesh_ctx=mesh,
                                  wire="packed", block=8, group_size=w)
    np.testing.assert_allclose(np.asarray(red)[0], true_mean, rtol=1e-6)
    reset_mesh_context()


def test_lamb_trust_on_raw_step():
    """The trust ratio is computed on the lr-NORMALISED step (the raw
    Adam direction), so scaling lr scales the update linearly instead of
    feeding back into the ratio; out-of-range ratios clip."""
    from deepspeed_tpu.runtime.comm.onebit import lamb_trust_math

    rs = np.random.RandomState(4)
    d = jnp.asarray(rs.randn(32), jnp.float32)
    p = jnp.asarray(rs.randn(32), jnp.float32)
    out_hi = np.asarray(lamb_trust_math(0.1 * d, p, 0.1, 0.01, 10.0))
    out_lo = np.asarray(lamb_trust_math(0.001 * d, p, 0.001, 0.01, 10.0))
    np.testing.assert_allclose(out_hi, 100.0 * out_lo, rtol=1e-4)

    # clip: a huge parameter norm vs a tiny step norm -> max_trust
    big_p = jnp.full((32,), 1e6, jnp.float32)
    out = np.asarray(lamb_trust_math(0.1 * d, big_p, 0.1, 0.01, 10.0))
    np.testing.assert_allclose(out, 10.0 * 0.1 * np.asarray(d), rtol=1e-5)
    # zero parameter norm -> ratio 1 (no trust scaling)
    out = np.asarray(lamb_trust_math(0.1 * d, jnp.zeros((32,)), 0.1,
                                     0.01, 10.0))
    np.testing.assert_allclose(out, 0.1 * np.asarray(d), rtol=1e-6)


def test_onebit_leaf_saves_bytes_gate():
    """Skinny leaves stay on the dense wire: chunk padding makes the
    packed transport COST bytes below ~world*block elements."""
    from deepspeed_tpu.runtime.comm.onebit import onebit_leaf_saves_bytes

    assert not onebit_leaf_saves_bytes((16,), jnp.float32, 8)
    assert not onebit_leaf_saves_bytes((64,), jnp.float32, 8)
    assert onebit_leaf_saves_bytes((64, 64), jnp.float32, 8)
    assert onebit_leaf_saves_bytes((1 << 20,), jnp.float32, 8)


def test_collective_wire_onebit_breakout():
    """collective_wire_bytes prices the packed sync under its own
    onebit_bytes attribution key (named_scope onebit_packed)."""
    from deepspeed_tpu.parallel import initialize_mesh, reset_mesh_context
    from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce
    from deepspeed_tpu.runtime.comm.low_bandwidth import \
        collective_wire_bytes

    reset_mesh_context()
    mesh = initialize_mesh(data=-1)
    w = mesh.data_parallel_world_size
    x = jnp.zeros((w, 64), jnp.float32)

    def wire(kind):
        jaxpr = jax.make_jaxpr(
            lambda a, e: compressed_allreduce(a, e, mesh_ctx=mesh,
                                              wire=kind, block=8))(
            x, jnp.zeros_like(x))
        return collective_wire_bytes(jaxpr.jaxpr)

    packed = wire("packed")
    assert packed["onebit_bytes"] > 0, packed
    full = wire("full")
    assert full["onebit_bytes"] == 0, full
    reset_mesh_context()


# --------------------------------------------------------------------- #
# static pricing: the compressed program's wire vs its dense twin
# --------------------------------------------------------------------- #
def test_compressed_wire_4x_reduction():
    """Round-20 acceptance: at hidden=64 the compressed-phase program
    moves <= 1/4 the bytes of the dense twin at BOTH the jaxpr and the
    compiled-HLO level, the two levels reconcile within
    spmd_match_tolerance, and the warmup program prices identically to
    the dense twin."""
    from deepspeed_tpu.analysis.auditor import audit_engine

    e = make_engine(freeze=1, hidden=64)
    run_steps(e, batches(3, hidden=64))
    assert e._onebit_phase == "compressed"
    warm = audit_engine(e, multihost=False, phase="warmup", hlo=True)
    comp = audit_engine(e, multihost=False, phase="compressed", hlo=True)

    e_dense = make_engine(tier=False, optimizer="Adam", hidden=64)
    run_steps(e_dense, batches(1, hidden=64))
    dense = audit_engine(e_dense, multihost=False, hlo=True)

    # warmup == dense twin on the wire (the tier is pure bookkeeping
    # until freeze_step).  Both dense programs have their grad reduction
    # GSPMD-inserted (jaxpr-invisible), so the dense side is priced at
    # the compiled-HLO level; the onebit optimizer adds a few scalar
    # collectives (count/freeze bookkeeping), hence the 1% band.
    assert warm.wire_bytes_per_step == dense.wire_bytes_per_step == 0
    assert dense.hlo_wire_bytes_per_step > 0
    assert abs(warm.hlo_wire_bytes_per_step -
               dense.hlo_wire_bytes_per_step) <= \
        0.01 * dense.hlo_wire_bytes_per_step
    # compressed phase: >= 4x reduction — the explicit (jaxpr-counted)
    # compressed wire AND its compiled-HLO twin against the dense
    # program's compiled wire
    assert comp.wire_bytes_per_step > 0
    assert comp.wire_bytes_per_step * 4 <= dense.hlo_wire_bytes_per_step, (
        comp.wire_bytes_per_step, dense.hlo_wire_bytes_per_step)
    assert comp.hlo_wire_bytes_per_step * 4 <= \
        dense.hlo_wire_bytes_per_step, (
        comp.hlo_wire_bytes_per_step, dense.hlo_wire_bytes_per_step)
    assert comp.hlo_wire_bytes_per_step * 4 <= \
        warm.hlo_wire_bytes_per_step
    # the jaxpr accounting and the compiled program agree
    assert abs(comp.hlo_divergence_ratio - 1.0) <= 0.05, \
        comp.hlo_divergence_ratio
    assert comp.hlo["n_silent_reshards"] == 0
    # phase is program identity: distinct lockstep signatures
    assert e.lockstep_signature("warmup") != \
        e.lockstep_signature("compressed")


# --------------------------------------------------------------------- #
# e2e parity, overflow-skip, checkpoint, fused
# --------------------------------------------------------------------- #
def test_e2e_six_step_parity():
    """6 steps across freeze=3: the warmup half is bitwise vs the
    numerics-only twin; the compressed half stays inside the loss band
    of the dense Adam twin."""
    data = batches(6, seed=11)
    e = make_engine(freeze=3)
    l_tier = run_steps(e, data)
    assert e._onebit_phase == "compressed"

    e_plain = make_engine(tier=False, freeze=3)
    l_plain = run_steps(e_plain, data)
    np.testing.assert_array_equal(l_tier[:3], l_plain[:3])

    e_adam = make_engine(tier=False, optimizer="Adam")
    l_adam = run_steps(e_adam, data)
    for a, b in zip(l_tier, l_adam):
        assert abs(a - b) <= 0.10 * max(1.0, abs(b)), (l_tier, l_adam)


def test_fp16_overflow_skip_preserves_error_feedback():
    """A post-freeze overflow-skipped step must leave params, momentum
    AND the wire-error carry untouched — otherwise the compensation
    stream drifts on every skip."""
    fp16 = {"fp16": {"enabled": True, "initial_scale_power": 4,
                     "loss_scale_window": 100, "hysteresis": 1}}
    e = make_engine(freeze=2, extra=fp16)
    data = batches(3, seed=13)
    run_steps(e, data)
    assert e._onebit_phase == "compressed"
    assert e.skipped_steps == 0

    p0 = jax.tree.map(np.asarray, e.params)
    s0 = jax.tree.map(np.asarray, e.opt_state)
    w0 = jax.tree.map(np.asarray, e._onebit_wire_error)
    scale0 = e.loss_scale
    x, y = data[0]
    loss = e.forward(x * 1e30, y)
    e.backward(loss)
    e.step()
    assert e.skipped_steps == 1
    assert e.loss_scale < scale0
    assert_tree_equal(e.params, p0)
    assert_tree_equal(e.opt_state, s0)
    assert_tree_equal(e._onebit_wire_error, w0)
    # the next clean step proceeds normally
    run_steps(e, data[1:2])
    assert e.skipped_steps == 1
    assert any(np.any(np.asarray(a) != b) for a, b in
               zip(jax.tree.leaves(e.params), jax.tree.leaves(p0)))


def test_checkpoint_across_freeze_boundary(tmp_path):
    """Phase is program identity: a pre-freeze checkpoint resumes in
    warmup and replays bitwise; a post-freeze checkpoint resumes
    directly in the compressed phase (no spurious warmup program)."""
    data = batches(6, seed=17)
    e = make_engine(freeze=3)
    run_steps(e, data[:2])
    e.save_checkpoint(str(tmp_path), tag="pre")

    e2 = make_engine(freeze=3)
    e2.load_checkpoint(str(tmp_path), tag="pre")
    assert e2._onebit_phase == "warmup"
    run_steps(e, data[2:])       # crosses freeze at step 4
    run_steps(e2, data[2:])
    assert e._onebit_phase == e2._onebit_phase == "compressed"
    assert_tree_equal(e.params, e2.params)
    assert_tree_equal(e._onebit_wire_error, e2._onebit_wire_error)

    e.save_checkpoint(str(tmp_path), tag="post")
    e3 = make_engine(freeze=3)
    assert e3._onebit_phase == "warmup"
    e3.load_checkpoint(str(tmp_path), tag="post")
    assert e3._onebit_phase == "compressed"
    extra = batches(1, seed=18)
    run_steps(e, extra)
    run_steps(e3, extra)
    assert_tree_equal(e.params, e3.params)


def test_fused_modular_parity_through_switch():
    """The fused gas-scan step must track the modular loop through the
    phase switch — same freeze boundary, same compressed numerics."""
    gas = 2
    rng = np.random.RandomState(19)
    micro_batches = [(rng.normal(0, 1, (MICRO, HIDDEN)).astype(np.float32),
                      rng.normal(0, 1, (MICRO,)).astype(np.float32))
                     for _ in range(5 * gas)]

    e_mod = make_engine(freeze=2, gas=gas)
    it = iter(micro_batches)
    for _ in range(5):
        for _ in range(gas):
            x, y = next(it)
            loss = e_mod.forward(x, y)
            e_mod.backward(loss)
            e_mod.step()

    e_fus = make_engine(freeze=2, gas=gas, fused=True)
    assert e_fus._fused_step_fn is not None, e_fus.fused_step_reason
    it = iter(micro_batches)
    for _ in range(5):
        e_fus.train_batch(it)

    assert e_mod._onebit_phase == e_fus._onebit_phase == "compressed"
    assert_tree_close(e_mod.params, e_fus.params, rtol=1e-5, atol=1e-6)
    assert_tree_close(e_mod._onebit_wire_error, e_fus._onebit_wire_error,
                      rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------- #
# config conflicts
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("cfg_patch, msg", [
    ({"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
     "requires a OneBitAdam or OneBitLamb"),
    ({"zero_optimization": {"stage": 3, "low_bandwidth": {"onebit": True}}},
     "stage"),
    ({"zero_optimization": {"stage": 2, "low_bandwidth": {"onebit": True},
                            "offload_optimizer": {"device": "cpu"}}},
     "offload"),
    ({"gradient_clipping": 1.0}, "gradient_clipping"),
    ({"sparse_gradients": True}, "sparse_gradients"),
    ({"optimizer": {"type": "OneBitAdam",
                    "params": {"lr": 1e-3, "freeze_step": 0}}},
     "freeze_step"),
    ({"optimizer": {"type": "OneBitAdam",
                    "params": {"lr": 1e-3, "freeze_step": 2,
                               "betas": [0.9, 1.5]}}},
     "betas"),
])
def test_onebit_config_conflicts(cfg_patch, msg):
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": 2}},
        "zero_optimization": {"stage": 2, "low_bandwidth": {"onebit": True}},
    }
    for k, v in cfg_patch.items():
        cfg[k] = v
    with pytest.raises(DeepSpeedConfigError, match=msg):
        DeepSpeedConfig(cfg)
