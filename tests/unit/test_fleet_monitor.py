"""Fleet observability (monitor/{fleet,health,heartbeat,capture}.py,
docs/telemetry.md "Fleet observability").

Covers the ISSUE-10 acceptance surface with a CPU "fake fleet": the
aggregation/straggler/divergence paths driven by synthetic multi-host
window matrices through an injected gather_fn (no distributed world
needed), the end-to-end chain injected-slow-host -> straggler event with
lane attribution -> sentinel health event -> profiler capture armed and
disarmed after K steps (profiler mocked), heartbeat stale detection and
the --watch table, the boundary-only aggregation guarantee (gather count
== full windows, never on close), the host-sync audit regression
extended to the fleet path, and the schema-v2 satellites (identity
fields, host-gap, trace schema_version, launcher prefixes).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.config import DeepSpeedConfigError, MonitorConfig
from deepspeed_tpu.monitor import (ATTR_COMPUTE, ATTR_EXPERT_HOTSPOT,
    ATTR_HOST_GAP,
    ATTR_SWAP, EVENT_DEAD_EXPERT, EVENT_DIVERGENCE, EVENT_EP_IMBALANCE,
    EVENT_ROUTER_COLLAPSE,
    EVENT_STRAGGLER, KIND_FLEET, KIND_FLEET_HOST, KIND_HEALTH, KIND_RECONCILE,
    KIND_STEP, SCHEMA_VERSION, STEP_RECORD_FIELDS, FleetAggregator,
    FleetHealth, HeartbeatWriter, ProfileCapture, TrainingMonitor,
    annotate_stale, format_watch_table, read_heartbeats, straggler_verdict,
    validate_trace_events)
from deepspeed_tpu.monitor import record as R
from deepspeed_tpu.monitor.fleet import (VEC_LEN, _encode_host,
                                         decode_window_vector,
                                         encode_window_vector)
from deepspeed_tpu.runtime.resilience.sentinel import TrainingSentinel


# --------------------------------------------------------------------- #
# fake-fleet plumbing
# --------------------------------------------------------------------- #
def _summary(t, loss=2.0, gap=0.0, swap_exp=0.0, step=10, gbps=None,
             **moe):
    """Window summary; the moe_* slots default ABSENT (NaN on the wire)
    exactly like a dense config — pass e.g. moe_local_load=2.0 to rig
    an expert-parallel fleet."""
    d = {"last_step": step, "steps": 5, "step_time_mean_s": t,
         "step_time_max_s": t, "loss_mean": loss,
         "host_gap_mean_s": gap, "swap_read_gbps": gbps,
         "swap_exposed_mean_s": swap_exp}
    d.update(moe)
    return d


def _matrix(rows):
    return np.stack([encode_window_vector(r) for r in rows])


class RiggedGather:
    """Injected gather_fn: serves the one-time hostname exchange, then
    returns the scripted window matrices in order (repeating the last).
    Counts window exchanges — the boundary-only acceptance check."""

    def __init__(self, hosts, matrices):
        self.hosts = hosts
        self.matrices = list(matrices)
        self.window_calls = 0

    def __call__(self, arr):
        arr = np.asarray(arr)
        if arr.dtype == np.uint8:  # hostname side-channel (init-time)
            return np.stack([_encode_host(h) for h in self.hosts])
        self.window_calls += 1
        idx = min(self.window_calls - 1, len(self.matrices) - 1)
        return self.matrices[idx]


class MockProfiler:
    def __init__(self, fail=False):
        self.fail = fail
        self.started = []
        self.stopped = 0
        self.active = False

    def start_trace(self, log_dir):
        if self.fail:
            raise RuntimeError("no profiler on this host")
        assert not self.active, "start_trace while active"
        self.active = True
        self.started.append(log_dir)

    def stop_trace(self):
        assert self.active, "stop_trace while idle"
        self.active = False
        self.stopped += 1


# --------------------------------------------------------------------- #
# window-vector codec + aggregation records
# --------------------------------------------------------------------- #
def test_window_vector_roundtrip():
    s = _summary(0.01, loss=2.5, gap=0.001, swap_exp=0.002, step=7,
                 gbps=12.5)
    vec = encode_window_vector(s)
    assert vec.shape == (VEC_LEN,) and vec.dtype == np.float64
    d = decode_window_vector(vec)
    assert d["step_time_mean_s"] == pytest.approx(0.01)
    assert d["loss_mean"] == pytest.approx(2.5)
    assert d["swap_read_gbps"] == pytest.approx(12.5)
    # absent fields ride as NaN and decode back to None
    d2 = decode_window_vector(encode_window_vector({"last_step": 3}))
    assert d2["last_step"] == 3.0
    assert d2["loss_mean"] is None and d2["swap_read_gbps"] is None


def test_fake_fleet_aggregate_records():
    hosts = ["h0", "h1", "h2", "h3"]
    rows = [_summary(0.010), _summary(0.012), _summary(0.020, gbps=4.0),
            _summary(0.011)]
    rig = RiggedGather(hosts, [_matrix(rows)])
    agg = FleetAggregator(process_index=0, process_count=4, host="h0",
                          gather_fn=rig)
    mat = agg.exchange(_summary(0.010))
    assert rig.window_calls == 1
    per_host = agg.per_host_records(mat)
    assert [r[R.F_HOST] for r in per_host] == hosts
    assert all(r[R.F_KIND] == KIND_FLEET_HOST for r in per_host)
    assert per_host[2][R.FL_SWAP_READ_GBPS] == pytest.approx(4.0)
    fleet = agg.fleet_record(mat)
    assert fleet[R.F_KIND] == KIND_FLEET
    assert fleet[R.FL_HOSTS] == 4
    assert fleet[R.FL_STEP_TIME_MIN_S] == pytest.approx(0.010)
    assert fleet[R.FL_STEP_TIME_MAX_S] == pytest.approx(0.020)
    assert fleet[R.FL_STEP_TIME_MEDIAN_S] == pytest.approx(0.0115)
    assert fleet[R.FL_STEP_TIME_P99_S] <= fleet[R.FL_STEP_TIME_MAX_S] + 1e-9
    assert fleet[R.FL_PER_HOST]["host"] == hosts
    assert fleet[R.FL_PER_HOST]["step_time_s"][2] == pytest.approx(0.020)


def test_single_host_degenerate_summary():
    agg = FleetAggregator(process_index=0, process_count=1, host="solo")
    mat = agg.exchange(_summary(0.01, step=5))
    assert mat.shape == (1, VEC_LEN)
    fleet = agg.fleet_record(mat)
    assert fleet[R.FL_HOSTS] == 1
    assert fleet[R.FL_STEP_TIME_MEDIAN_S] == pytest.approx(0.01)
    v = straggler_verdict(mat, agg.host_names())
    assert v["straggler"] is False and v["ratio"] == pytest.approx(1.0)


def test_fleet_gather_shape_mismatch_is_loud():
    rig = RiggedGather(["a", "b"], [np.zeros((3, VEC_LEN + 1))])
    agg = FleetAggregator(0, 2, host="a", gather_fn=rig)
    with pytest.raises(ValueError, match="mixed monitor schema"):
        agg.exchange(_summary(0.01))


# --------------------------------------------------------------------- #
# straggler / divergence detection
# --------------------------------------------------------------------- #
def _warm(health, hosts, windows=3, t=0.010):
    for w in range(windows):
        assert health.observe(_matrix([_summary(t, step=10 * (w + 1))
                                       for _ in hosts]), hosts) == []


def test_straggler_lane_attribution_swap_and_hostgap():
    hosts = ["h0", "h1", "h2", "h3"]
    health = FleetHealth(warmup_windows=2)
    _warm(health, hosts)
    # host 2 slow, the excess dominated by exposed swap reads
    rows = [_summary(0.010, step=40), _summary(0.010, step=40),
            _summary(0.030, gap=0.001, swap_exp=0.018, step=40),
            _summary(0.010, step=40)]
    evs = health.observe(_matrix(rows), hosts)
    assert len(evs) == 1
    ev = evs[0]
    assert ev[R.F_KIND] == KIND_HEALTH
    assert ev[R.H_EVENT] == EVENT_STRAGGLER
    assert ev[R.F_HOST] == "h2" and ev[R.F_PROCESS_INDEX] == 2
    assert ev[R.H_LANE] == ATTR_SWAP
    assert ev[R.H_RATIO] == pytest.approx(3.0)
    assert ev[R.H_STEP] == 40
    # host-gap dominated excess names the host-gap lane
    health2 = FleetHealth(warmup_windows=2)
    _warm(health2, hosts)
    rows = [_summary(0.010, step=40), _summary(0.010, step=40),
            _summary(0.010, step=40),
            _summary(0.025, gap=0.014, step=40)]
    evs = health2.observe(_matrix(rows), hosts)
    assert len(evs) == 1 and evs[0][R.H_LANE] == ATTR_HOST_GAP
    assert evs[0][R.F_HOST] == "h3"


def test_straggler_needs_warmup_and_ratio():
    hosts = ["h0", "h1"]
    health = FleetHealth(warmup_windows=3, straggler_min_ratio=1.5)
    # a slow host inside the warmup window is NOT flagged
    rows = [_summary(0.010), _summary(0.030)]
    assert health.observe(_matrix(rows), hosts) == []
    _warm(health, hosts, windows=3)
    # past warmup but under the ratio gate: still quiet
    rows = [_summary(0.010, step=40), _summary(0.0125, step=40)]
    assert [e for e in health.observe(_matrix(rows), hosts)
            if e[R.H_EVENT] == EVENT_STRAGGLER] == []


def test_straggler_does_not_drag_baseline():
    """Flagged hosts' samples must not update the EWMA — a persistent
    straggler keeps being flagged instead of becoming the new normal."""
    hosts = ["h0", "h1", "h2", "h3"]
    health = FleetHealth(warmup_windows=1)
    _warm(health, hosts, windows=2)
    for w in range(5):
        rows = [_summary(0.010, step=30 + w)] * 3 + \
            [_summary(0.030, step=30 + w)]
        evs = [e for e in health.observe(_matrix(rows), hosts)
               if e[R.H_EVENT] == EVENT_STRAGGLER]
        assert len(evs) == 1, f"window {w}: straggler went quiet"
        assert evs[0][R.F_HOST] == "h3"


def test_straggler_slow_from_first_window_is_flagged():
    """Review regression: a host that is slow from the job's FIRST
    window (cold NVMe, sick host from boot) must still be flagged —
    its warmup samples must not pollute the EWMA baseline into masking
    it (the ratio gate, which needs no history, keeps it out of the
    baseline)."""
    hosts = ["h0", "h1", "h2", "h3"]
    health = FleetHealth(warmup_windows=2)
    flagged_windows = 0
    for w in range(10):
        rows = [_summary(0.010, step=10 * (w + 1))] * 3 + \
            [_summary(0.020, step=10 * (w + 1))]   # 2x slow from w=0
        evs = [e for e in health.observe(_matrix(rows), hosts)
               if e[R.H_EVENT] == EVENT_STRAGGLER]
        if w >= health.warmup_windows:
            assert len(evs) == 1 and evs[0][R.F_HOST] == "h3", \
                f"window {w}: boot-time straggler masked"
            flagged_windows += 1
    assert flagged_windows == 8


def test_grad_norm_divergence_detected():
    """ISSUE-10 tentpole: divergence watches loss AND grad-norm spread
    — corrupt optimizer state moves the norm windows before the loss."""
    hosts = ["h0", "h1", "h2"]
    health = FleetHealth(warmup_windows=0, divergence_rel_spread=1e-3)
    rows = [dict(_summary(0.01, loss=2.0), grad_norm_mean=1.0),
            dict(_summary(0.01, loss=2.0), grad_norm_mean=1.0),
            dict(_summary(0.01, loss=2.0), grad_norm_mean=5.0)]
    evs = [e for e in health.observe(_matrix(rows), hosts)
           if e[R.H_EVENT] == EVENT_DIVERGENCE]
    assert len(evs) == 1
    assert evs[0][R.H_METRIC] == "grad_norm"
    assert evs[0][R.F_HOST] == "h2"
    # the spread rides the metric-neutral key; a grad-norm magnitude
    # never lands under the loss-labeled field
    assert evs[0][R.H_SPREAD] == pytest.approx(4.0)
    assert R.FL_LOSS_SPREAD not in evs[0]
    # identical norms (and losses): quiet
    rows = [dict(_summary(0.01, loss=2.0), grad_norm_mean=1.0)] * 3
    assert [e for e in health.observe(_matrix(rows), hosts)
            if e[R.H_EVENT] == EVENT_DIVERGENCE] == []


def test_divergence_detection_flags_outlier_replica():
    hosts = ["h0", "h1", "h2"]
    health = FleetHealth(warmup_windows=0, divergence_rel_spread=1e-3)
    rows = [_summary(0.01, loss=2.0), _summary(0.01, loss=2.0),
            _summary(0.01, loss=2.4)]
    evs = [e for e in health.observe(_matrix(rows), hosts)
           if e[R.H_EVENT] == EVENT_DIVERGENCE]
    assert len(evs) == 1
    assert evs[0][R.F_HOST] == "h2"
    assert evs[0][R.FL_LOSS_SPREAD] == pytest.approx(0.4)
    # identical (globally-reduced) losses: quiet
    rows = [_summary(0.01, loss=2.0)] * 3
    assert [e for e in health.observe(_matrix(rows), hosts)
            if e[R.H_EVENT] == EVENT_DIVERGENCE] == []


def test_two_host_straggler_not_masked_by_midpoint_median():
    """Review regression: the ratio gate divides by the PEER median
    (leave-one-out).  An all-host median on P=2 is the midpoint of the
    pair, so a 30% straggler read as only ~1.13x 'the fleet' and
    slipped a 1.15 gate — while its samples kept feeding the EWMA
    baseline."""
    hosts = ["h0", "h1"]
    health = FleetHealth(warmup_windows=1, straggler_min_ratio=1.15)
    _warm(health, hosts, windows=2, t=0.100)
    rows = [_summary(0.100, step=30), _summary(0.130, step=30)]
    evs = [e for e in health.observe(_matrix(rows), hosts)
           if e[R.H_EVENT] == EVENT_STRAGGLER]
    assert len(evs) == 1 and evs[0][R.F_HOST] == "h1"
    assert evs[0][R.H_RATIO] == pytest.approx(1.3)
    # one-shot verdict (the bench-row form) uses the same peer median
    v = straggler_verdict(_matrix(rows), hosts, min_ratio=1.15)
    assert v["straggler"] is True and v["host"] == "h1"
    assert v["ratio"] == pytest.approx(1.3)


def test_two_host_divergence_is_ambiguous_not_blamed_on_p0():
    """Review regression: with P=2 both hosts are equidistant from the
    midpoint median — argmax's tie-break blamed the HEALTHY process 0
    (which then armed ITS capture).  The event must mark the
    attribution ambiguous and carry no process_index."""
    hosts = ["h0", "h1"]
    health = FleetHealth(warmup_windows=0, divergence_rel_spread=1e-3)
    rows = [_summary(0.01, loss=1.0), _summary(0.01, loss=2.0)]
    evs = [e for e in health.observe(_matrix(rows), hosts)
           if e[R.H_EVENT] == EVENT_DIVERGENCE]
    assert len(evs) == 1
    ev = evs[0]
    assert ev[R.F_PROCESS_INDEX] is None
    assert ev[R.F_HOST].startswith("ambiguous:")
    assert "h0" in ev[R.F_HOST] and "h1" in ev[R.F_HOST]
    assert ev[R.F_WORLD_SIZE] == 2


def test_straggler_verdict_one_shot():
    hosts = ["h0", "h1", "h2"]
    mat = _matrix([_summary(0.010), _summary(0.010),
                   _summary(0.030, swap_exp=0.015)])
    v = straggler_verdict(mat, hosts)
    assert v["straggler"] is True and v["host"] == "h2"
    assert v["ratio"] == pytest.approx(3.0)
    assert v["lane"] == ATTR_SWAP
    mat = _matrix([_summary(0.010)] * 3)
    assert straggler_verdict(mat, hosts)["straggler"] is False


# --------------------------------------------------------------------- #
# capture: rate limit, K-step disarm, failure path (profiler mocked)
# --------------------------------------------------------------------- #
def test_capture_arm_disarm_and_rate_limit(tmp_path):
    prof = MockProfiler()
    cap = ProfileCapture(str(tmp_path), steps=3, max_captures=2,
                         cooldown_steps=10, profiler=prof)
    assert cap.arm("step_time_above_band", step=5) is True
    assert prof.active and cap.armed
    assert cap.arm("again", step=5) is False      # already armed
    for s in (6, 7):
        cap.observe_step_end(s)
        assert cap.armed
    cap.observe_step_end(8)                        # K-th step: disarm
    assert not cap.armed and prof.stopped == 1
    assert cap.captures[0]["steps"] == 3
    assert os.path.isdir(cap.captures[0]["dir"])
    assert cap.arm("too-soon", step=12) is False   # inside cooldown
    assert cap.arm("ok", step=18) is True          # past cooldown
    cap.observe_step_end(19)
    cap.close(20)                                  # close stops an armed one
    assert prof.stopped == 2 and not prof.active
    assert cap.arm("third", step=100) is False     # max_captures reached
    assert cap.counters() == {"captures": 2, "capture_armed": 0}


def test_capture_trigger_flags_and_failure(tmp_path):
    prof = MockProfiler()
    cap = ProfileCapture(str(tmp_path), steps=1, profiler=prof)
    assert cap.maybe_arm_for_flags(["model_violation"], 1) is False
    assert cap.maybe_arm_for_flags(["swap_below_ceiling_band"], 1) is True
    cap.observe_step_end(2)
    assert prof.stopped == 1
    # a dead profiler disables capture for the run, loudly not fatally
    bad = ProfileCapture(str(tmp_path / "bad"), profiler=MockProfiler(
        fail=True))
    assert bad.arm("x", 1) is False
    assert bad.exhausted
    assert bad.arm("y", 500) is False


# --------------------------------------------------------------------- #
# heartbeat protocol: stale detection + --watch table
# --------------------------------------------------------------------- #
def test_heartbeat_roundtrip_and_stale(tmp_path):
    d = str(tmp_path / "hb")
    for p in range(3):
        HeartbeatWriter(d, process_index=p, world_size=3,
                        host=f"host{p}").beat(step=40 + p)
    beats = read_heartbeats(d)
    assert [b["process_index"] for b in beats] == [0, 1, 2]
    assert [b["step"] for b in beats] == [40, 41, 42]
    assert all(b["age_s"] < 30 for b in beats)
    # age one host artificially: stale only past the threshold
    beats = read_heartbeats(d, now=time.time() + 120)
    annotate_stale(beats, stale_after_s=60)
    assert all(b["stale"] for b in beats)
    table = format_watch_table(read_heartbeats(d), stale_after_s=1e9)
    assert "host0" in table and "running" in table and "STALE" not in table
    table = format_watch_table(read_heartbeats(d, now=time.time() + 120),
                               stale_after_s=60)
    assert "STALE" in table
    # a stopped host is not stale no matter how old its file is
    HeartbeatWriter(d, process_index=1, world_size=3,
                    host="host1").close(step=43)
    beats = annotate_stale(read_heartbeats(d, now=time.time() + 120), 60)
    assert beats[1]["status"] == "stopped" and not beats[1]["stale"]


def test_heartbeat_adaptive_staleness_long_windows(tmp_path):
    """Review regression: a long-step job beats once per ~100 s; the
    staleness threshold must scale to 3x the host's OWN reported beat
    interval instead of crying STALE against a 60 s wall constant."""
    now = time.time()
    beats = [{"host": "big", "process_index": 0, "status": "running",
              "step": 40, "time": now - 150, "age_s": 150.0,
              "interval_s": 100.0}]
    annotate_stale(beats, stale_after_s=60)
    assert beats[0]["stale"] is False          # 150 < 3*100
    beats[0]["age_s"] = 350.0
    annotate_stale(beats, stale_after_s=60)
    assert beats[0]["stale"] is True           # 350 > 3*100
    # a fast-beating host keeps the wall-clock floor
    quick = [{"host": "q", "process_index": 1, "status": "running",
              "age_s": 70.0, "interval_s": 2.0}]
    annotate_stale(quick, stale_after_s=60)
    assert quick[0]["stale"] is True
    # the FIRST beat already reports an interval (monitor build ->
    # first flush, seeded at construction) so a long first window
    # cannot render a transient false STALE before the second beat
    w = HeartbeatWriter(str(tmp_path / "hb1"), 0, 1, host="h")
    w._t_last -= 100.0                 # pretend construction was 100s ago
    w.beat(step=1)
    first = read_heartbeats(str(tmp_path / "hb1"))[0]
    assert first["interval_s"] == pytest.approx(100.0, abs=1.0)
    first["age_s"] = 150.0             # < 3x first interval
    annotate_stale([first], stale_after_s=60)
    assert first["stale"] is False


def test_watch_table_renders_missing_workers(tmp_path):
    """Review regression: a worker that died before its FIRST beat must
    show as MISSING, not be silently absent from the table."""
    d = str(tmp_path / "hb")
    HeartbeatWriter(d, 0, 3, host="alive0").beat(step=5)
    HeartbeatWriter(d, 2, 3, host="alive2").beat(step=5)
    table = format_watch_table(read_heartbeats(d), expected_procs=3)
    assert "alive0" in table and "alive2" in table
    assert "MISSING" in table
    lines = [ln for ln in table.splitlines() if "MISSING" in ln]
    assert len(lines) == 1 and lines[0].lstrip().startswith("1")


def test_heartbeat_corrupt_file_surfaces(tmp_path):
    d = str(tmp_path / "hb")
    HeartbeatWriter(d, 0, 1, host="ok").beat(step=1)
    with open(os.path.join(d, "hb_9.json"), "w") as f:
        f.write("{torn")
    beats = read_heartbeats(d)
    corrupt = [b for b in beats if b["status"] == "corrupt"]
    # the process index is recovered from the filename, so the watch
    # table shows ONE corrupt row — never an extra MISSING row too
    assert len(corrupt) == 1 and corrupt[0]["process_index"] == 9
    table = format_watch_table(beats, expected_procs=10)
    assert "corrupt" in table
    rows_for_9 = [ln for ln in table.splitlines()
                  if ln.lstrip().startswith("9")]
    assert len(rows_for_9) == 1 and "MISSING" not in rows_for_9[0]


def test_resolve_heartbeat_dir_handles_job_name(tmp_path):
    """--watch is pointed at monitor.output_path; the beats live under
    output_path/<job_name>/heartbeat when job_name is set."""
    from deepspeed_tpu.monitor.heartbeat import resolve_heartbeat_dir
    root = str(tmp_path)
    # nothing yet: default guess (may appear later)
    assert resolve_heartbeat_dir(root) == os.path.join(root, "heartbeat")
    # job_name layout
    HeartbeatWriter(os.path.join(root, "run1", "heartbeat"),
                    0, 2, host="w0").beat(step=3)
    assert resolve_heartbeat_dir(root) == os.path.join(
        root, "run1", "heartbeat")
    # empty-job_name layout wins once present
    HeartbeatWriter(os.path.join(root, "heartbeat"),
                    0, 2, host="w0").beat(step=3)
    assert resolve_heartbeat_dir(root) == os.path.join(root, "heartbeat")
    # pointing directly AT the heartbeat dir also works
    assert resolve_heartbeat_dir(
        os.path.join(root, "heartbeat")) == os.path.join(root, "heartbeat")


# --------------------------------------------------------------------- #
# the acceptance chain: slow host -> straggler event -> sentinel ->
# capture armed on the flagged host and disarmed after K steps
# --------------------------------------------------------------------- #
def _fleet_cfg(tmp_path, **kw):
    d = {"enabled": True, "output_path": str(tmp_path),
         "writers": ["jsonl"], "write_interval": 2, "fleet": True,
         "health_warmup_windows": 1, "heartbeat": True}
    d.update(kw)
    return MonitorConfig.from_dict(d)


def _rigged_windows(slow_from=2, windows=6, slow_idx=2):
    """Scripted fleet windows: healthy, then host `slow_idx` 3x slow
    with swap-exposed excess."""
    hosts = [f"host{i}" for i in range(4)]
    mats = []
    for w in range(windows):
        rows = []
        for p in range(4):
            if w >= slow_from and p == slow_idx:
                rows.append(_summary(0.030, gap=0.001, swap_exp=0.018,
                                     step=2 * (w + 1)))
            else:
                rows.append(_summary(0.010, step=2 * (w + 1)))
        mats.append(_matrix(rows))
    return hosts, mats


def test_e2e_slow_host_event_sentinel_capture(tmp_path):
    """ISSUE-10 acceptance: injected slow host -> straggler event with
    correct lane attribution -> sentinel health event recorded ->
    capture armed on the flagged host and disarmed after K steps."""
    hosts, mats = _rigged_windows()
    rig = RiggedGather(hosts, mats)
    prof = MockProfiler()
    sentinel = TrainingSentinel()
    mon = TrainingMonitor(
        _fleet_cfg(tmp_path, capture={"enabled": True, "steps": 2,
                                      "max_captures": 1}),
        process_index=2, world_size=4, host="host2",
        gather_fn=rig, profiler=prof,
        health_sink=sentinel.record_health_event)
    assert not mon.is_emitter  # non-zero rank: no file writers
    assert mon.jsonl_path is None
    step = 0
    for _ in range(2):  # two healthy windows (warmup=1 + baseline)
        for _ in range(2):
            step += 1
            mon.mark_step_start()
            mon.end_step(step, loss=2.0)
    assert rig.window_calls == 2 and not prof.active
    # window 3: the rigged matrix turns host2 (me) into the straggler
    for _ in range(2):
        step += 1
        mon.mark_step_start()
        mon.end_step(step, loss=2.0)
    assert rig.window_calls == 3
    evs = mon.last_health_events
    assert [e[R.H_EVENT] for e in evs] == [EVENT_STRAGGLER]
    assert evs[0][R.F_HOST] == "host2" and evs[0][R.H_LANE] == ATTR_SWAP
    # schema-v2 identity triple rides health events too
    assert evs[0][R.F_WORLD_SIZE] == 4
    # sentinel got the structured event
    assert sentinel.health_events_seen == 1
    assert sentinel.counters()["health_events"] == 1
    assert sentinel.health_events[0][R.H_EVENT] == EVENT_STRAGGLER
    diag = sentinel.diagnostic(step)
    assert diag["recent_health_events"][0][R.F_HOST] == "host2"
    # capture armed on the FLAGGED host (us), and disarms after K=2.
    # A sentinel-rewound step (discard_step) still ran a full
    # forward/backward on device under the live profiler, so it counts
    # toward the K-step bound — a rewind streak must not let the
    # capture outlive its window
    assert prof.active and mon.capture.armed
    mon.mark_step_start()
    mon.discard_step()
    assert mon.capture.armed          # 1 of 2 captured steps (rewound)
    mon.mark_step_start()
    mon.end_step(step + 1, loss=2.0)
    assert not mon.capture.armed      # K-step disarm
    assert prof.stopped == 1
    assert "straggler" in prof.started[0]
    mon.close()
    # heartbeat was written by the non-emitter rank too
    beats = read_heartbeats(os.path.join(mon.out_dir, "heartbeat"))
    assert [b["process_index"] for b in beats] == [2]
    assert beats[0]["status"] == "stopped"


def test_e2e_rank0_emits_fleet_and_health_records(tmp_path):
    """Rank 0 of the same fake fleet: per-host + fleet-aggregate +
    health records ride the JSONL stream; capture is NOT armed (the
    straggler is host2, not us)."""
    hosts, mats = _rigged_windows()
    rig = RiggedGather(hosts, mats)
    prof = MockProfiler()
    mon = TrainingMonitor(
        _fleet_cfg(tmp_path, capture={"enabled": True}),
        process_index=0, world_size=4, host="host0",
        gather_fn=rig, profiler=prof)
    assert mon.is_emitter
    for step in range(1, 7):
        mon.mark_step_start()
        mon.end_step(step, loss=2.0)
    mon.close()
    assert not prof.started  # the anomaly is on host2, not on rank 0
    recs = [json.loads(line) for line in open(mon.jsonl_path)]
    kinds = [r.get(R.F_KIND) for r in recs]
    assert kinds.count(KIND_FLEET) == 3      # one per FULL window
    assert kinds.count(KIND_FLEET_HOST) == 12
    health = [r for r in recs if r.get(R.F_KIND) == KIND_HEALTH]
    assert len(health) == 1 and health[0][R.F_HOST] == "host2"
    fleet = [r for r in recs if r.get(R.F_KIND) == KIND_FLEET][-1]
    assert fleet[R.FL_HOSTS] == 4
    assert fleet[R.FL_STEP_TIME_MAX_S] == pytest.approx(0.030)
    assert fleet[R.FL_STEP_TIME_MEDIAN_S] == pytest.approx(0.010)
    assert fleet[R.FL_PER_HOST]["host"] == hosts
    # every step/reconcile record carries the v2 identity triple
    for r in recs:
        if r.get(R.F_KIND) in (KIND_STEP, KIND_RECONCILE):
            assert r[R.F_HOST] == "host0"
            assert r[R.F_PROCESS_INDEX] == 0
            assert r[R.F_WORLD_SIZE] == 4


def test_aggregation_traffic_boundary_only(tmp_path):
    """Acceptance: cross-host traffic at FULL flush-window boundaries
    only — N steps at window W = N//W exchanges, and close() (a partial
    window may remain, hosts may exit at different times) never adds
    one."""
    hosts = [f"host{i}" for i in range(2)]
    rig = RiggedGather(hosts, [_matrix([_summary(0.01)] * 2)])
    mon = TrainingMonitor(_fleet_cfg(tmp_path, write_interval=3),
                          process_index=0, world_size=2, host="host0",
                          gather_fn=rig)
    for step in range(1, 8):  # 7 steps, window 3 -> 2 full windows
        mon.mark_step_start()
        mon.end_step(step, loss=1.0)
    assert rig.window_calls == 2
    # explicit mid-run flush() with fleet live: no collective AND the
    # partial window stays buffered — flushing it on one host would
    # shift that host's future boundaries off its peers' (window
    # cadence is collective state); close()'s final flush still lands
    # the buffered steps on disk below
    mon.flush()
    assert rig.window_calls == 2
    assert len(mon.stream._pending) == 1
    mon.close()               # final flush: no collective
    assert rig.window_calls == 2
    recs = [json.loads(line) for line in open(mon.jsonl_path)]
    # the partial window's STEP records still made it to disk
    steps = [r[R.F_STEP] for r in recs if r.get(R.F_KIND) == KIND_STEP]
    assert steps == [1, 2, 3, 4, 5, 6, 7]


def test_post_exchange_local_failure_keeps_exchange_alive(tmp_path):
    """Review regression: only a failed EXCHANGE disables the hook.  A
    local bug in record/health processing on one host must not stop
    that host from joining future allgathers — the other hosts would
    block forever on the missing participant."""
    hosts = ["h0", "h1"]
    rig = RiggedGather(hosts, [_matrix([_summary(0.01)] * 2)])
    mon = TrainingMonitor(_fleet_cfg(tmp_path), process_index=0,
                          world_size=2, host="h0", gather_fn=rig)

    def boom(matrix):
        raise RuntimeError("local record bug")

    mon.fleet.per_host_records = boom
    for step in range(1, 7):  # 3 full windows
        mon.mark_step_start()
        mon.end_step(step, loss=1.0)
    mon.close()
    # the collective kept running despite the per-window local failure
    assert rig.window_calls == 3


def test_non_emitter_skips_record_assembly(tmp_path):
    """Review regression: fleet non-emitter ranks have no writers — the
    flush must not pay the records-only boundary reads (lr/loss-scale)
    or assemble step records nobody consumes."""
    hosts = ["h0", "h1"]
    rig = RiggedGather(hosts, [_matrix([_summary(0.01)] * 2)])
    reads = {"n": 0}

    def boundary():
        reads["n"] += 1
        return {"lr": 1e-3}

    mon = TrainingMonitor(_fleet_cfg(tmp_path), process_index=1,
                          world_size=2, host="h1", gather_fn=rig,
                          boundary_fn=boundary)
    for step in range(1, 5):
        mon.mark_step_start()
        mon.end_step(step, loss=1.0)
    mon.close()
    assert reads["n"] == 0
    assert mon.stream.records_emitted == 0
    assert rig.window_calls == 2  # the fleet path still ran


def test_fleet_exchange_failure_degrades_loudly(tmp_path, caplog):
    calls = {"n": 0}

    def broken(arr):
        arr = np.asarray(arr)
        if arr.dtype == np.uint8:
            return np.stack([_encode_host("h0"), _encode_host("h1")])
        calls["n"] += 1
        raise RuntimeError("collective timeout")

    mon = TrainingMonitor(_fleet_cfg(tmp_path), process_index=0,
                          world_size=2, host="h0", gather_fn=broken)
    for step in range(1, 7):
        mon.mark_step_start()
        mon.end_step(step, loss=1.0)
    mon.close()
    assert calls["n"] == 1  # hook disabled after the first failure
    recs = [json.loads(line) for line in open(mon.jsonl_path)]
    # step records keep flowing; no fleet records after the failure
    assert [r[R.F_STEP] for r in recs
            if r.get(R.F_KIND) == KIND_STEP] == [1, 2, 3, 4, 5, 6]
    assert [r for r in recs if r.get(R.F_KIND) == KIND_FLEET] == []
    # the degradation is marked IN the stream, not just this host's log
    degraded = [r for r in recs if r.get("fleet_disabled")]
    assert len(degraded) == 1
    assert "collective timeout" in degraded[0]["fleet_disabled"]


# --------------------------------------------------------------------- #
# host-sync audit regression extended to the fleet path (acceptance)
# --------------------------------------------------------------------- #
def _engine(tmp_path, monitor=None):
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    ds.reset_mesh_context()
    cfg = GPT2Config(vocab_size=64, n_positions=16, hidden_size=32,
                     num_layers=2, num_heads=4, embd_dropout=0.0,
                     attn_dropout=0.0, hidden_dropout=0.0)
    model = GPT2Model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10 ** 9,
    }
    if monitor is not None:
        monitor = dict(monitor)
        monitor.setdefault("enabled", True)
        monitor.setdefault("output_path", str(tmp_path))
        config["monitor"] = monitor
    engine, _, _, _ = ds.initialize(
        model=model, config=config,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    return engine


def test_fleet_monitor_on_adds_zero_host_sync_findings(tmp_path):
    """Acceptance: host-sync audit stays clean with FLEET monitoring
    enabled — zero new auditor findings, unchanged lockstep signature
    and wire bytes vs monitor-off (the fleet exchange is host-side at
    flush boundaries; the traced step programs are identical)."""
    from deepspeed_tpu.analysis import RULE_HOST_SYNC, audit_engine
    plain = _engine(tmp_path)
    plain_report = audit_engine(plain, multihost=False)
    fleet = _engine(tmp_path, monitor={"writers": ["jsonl"],
                                       "write_interval": 2,
                                       "fleet": True, "heartbeat": True})
    assert fleet.monitor is not None and fleet.monitor.fleet is not None
    ids = np.random.RandomState(0).randint(
        0, 64, size=(2, 16)).astype(np.int32)
    for _ in range(4):
        loss = fleet.forward(ids)
        fleet.backward(loss)
        fleet.step()
    report = audit_engine(fleet, multihost=False)
    assert fleet.monitor.fleet.exchanges == 2  # the fleet path RAN
    fleet.monitor.close()
    host_sync = [f for f in report.findings if f.rule == RULE_HOST_SYNC]
    assert host_sync == [], [f.format() for f in host_sync]
    assert report.signature == plain_report.signature
    assert report.wire_bytes_per_step == plain_report.wire_bytes_per_step
    # degenerate single-host fleet records landed
    recs = [json.loads(line) for line in open(fleet.monitor.jsonl_path)]
    fleet_recs = [r for r in recs if r.get(R.F_KIND) == KIND_FLEET]
    assert fleet_recs and fleet_recs[0][R.FL_HOSTS] == 1
    steps = [r for r in recs if r.get(R.F_KIND) == KIND_STEP]
    # schema v2: identity populated on a single-host run too
    assert all(r[R.F_WORLD_SIZE] == 1 and r[R.F_PROCESS_INDEX] == 0
               and r[R.F_HOST] for r in steps)
    # host-gap measured from step 2 on (needs a previous end_step)
    assert all(r[R.F_HOST_GAP_S] is not None for r in steps[1:])


# --------------------------------------------------------------------- #
# schema v2 satellites
# --------------------------------------------------------------------- #
def test_step_record_fields_carry_identity_and_gap():
    for f in (R.F_HOST, R.F_PROCESS_INDEX, R.F_WORLD_SIZE, R.F_HOST_GAP_S):
        assert f in STEP_RECORD_FIELDS
    ident = R.identity()
    assert ident[R.F_PROCESS_INDEX] == 0 and ident[R.F_WORLD_SIZE] >= 1
    assert ident[R.F_HOST]


def test_trace_schema_version_validated():
    from deepspeed_tpu.monitor import TraceEventBuffer
    buf = TraceEventBuffer()
    buf.add_span("x", 1.0, 2.0)
    payload = buf.to_json()
    assert payload["otherData"]["schema_version"] == SCHEMA_VERSION
    assert validate_trace_events(payload) == []
    payload["otherData"]["schema_version"] = SCHEMA_VERSION + 1
    assert any("newer than this validator" in p
               for p in validate_trace_events(payload))
    payload["otherData"]["schema_version"] = "two"
    assert any("not an int" in p for p in validate_trace_events(payload))
    # v1-era traces (no version key) still validate
    del payload["otherData"]["schema_version"]
    assert validate_trace_events(payload) == []


def test_monitor_fleet_config_validation():
    with pytest.raises(DeepSpeedConfigError, match="straggler_min_ratio"):
        MonitorConfig.from_dict({"straggler_min_ratio": 0.9})
    with pytest.raises(DeepSpeedConfigError, match="straggler_zscore"):
        MonitorConfig.from_dict({"straggler_zscore": 0})
    with pytest.raises(DeepSpeedConfigError, match="divergence_rel_spread"):
        MonitorConfig.from_dict({"divergence_rel_spread": -1})
    with pytest.raises(DeepSpeedConfigError, match="capture.steps"):
        MonitorConfig.from_dict({"capture": {"steps": 0}})
    with pytest.raises(DeepSpeedConfigError, match="max_captures"):
        MonitorConfig.from_dict({"capture": {"max_captures": 0}})
    cfg = MonitorConfig.from_dict({"fleet": True, "heartbeat": True,
                                   "capture": {"enabled": True,
                                               "steps": 4}})
    assert cfg.fleet and cfg.heartbeat
    assert cfg.capture.enabled and cfg.capture.steps == 4
    assert MonitorConfig.from_dict(None).fleet is False
    assert MonitorConfig.from_dict(None).capture.enabled is False
    # "capture": true is the turn-it-on shorthand; a non-object value
    # that is not a bool is a config error, not an AttributeError
    assert MonitorConfig.from_dict({"capture": True}).capture.enabled
    assert not MonitorConfig.from_dict({"capture": False}).capture.enabled
    with pytest.raises(DeepSpeedConfigError, match="monitor.capture"):
        MonitorConfig.from_dict({"capture": "yes"})


def test_sentinel_health_event_state_roundtrip():
    s = TrainingSentinel()
    s.record_health_event({R.H_EVENT: EVENT_DIVERGENCE, R.F_HOST: "h1",
                           R.H_STEP: 9})
    sd = s.state_dict()
    s2 = TrainingSentinel()
    s2.load_state_dict(sd)
    assert s2.health_events_seen == 1
    # the bounded ring never grows past its cap
    for i in range(100):
        s.record_health_event({R.H_EVENT: EVENT_STRAGGLER, R.H_STEP: i})
    assert len(s.health_events) == s._HEALTH_EVENTS_KEPT
    assert s.health_events_seen == 101


# --------------------------------------------------------------------- #
# launcher satellites: [host:rank] prefixes + failure naming + --watch
# --------------------------------------------------------------------- #
def test_launcher_prefixes_and_names_failing_host(capsys, caplog):
    from deepspeed_tpu.launcher.runner import launch_and_wait
    from deepspeed_tpu.utils.logging import logger as ds_logger
    ds_logger.addHandler(caplog.handler)  # the DS logger is non-propagating
    try:
        rc = launch_and_wait(
            [[sys.executable, "-c",
              "print('alpha line'); import sys; "
              "print('alpha err', file=sys.stderr)"],
             [sys.executable, "-c", "print('beta line'); import sys; "
              "sys.exit(7)"]],
            ["nodeA", "nodeB"])
    finally:
        ds_logger.removeHandler(caplog.handler)
    assert rc == 7
    out = capsys.readouterr()
    assert "[nodeA:0] alpha line" in out.out
    assert "[nodeB:1] beta line" in out.out
    assert "[nodeA:0] alpha err" in out.err
    messages = " ".join(r.getMessage() for r in caplog.records)
    assert "'nodeB'" in messages and "rc=7" in messages
    assert "nodeA" in messages  # the clean host is named too


def test_launcher_watch_renders_heartbeat_table(tmp_path, capsys):
    from deepspeed_tpu.launcher.runner import launch_and_wait
    from deepspeed_tpu.monitor.heartbeat import HEARTBEAT_DIR
    hb_dir = os.path.join(str(tmp_path), HEARTBEAT_DIR)
    HeartbeatWriter(hb_dir, 0, 2, host="podhost0").beat(step=12)
    HeartbeatWriter(hb_dir, 1, 2, host="podhost1").beat(step=12)
    rc = launch_and_wait(
        [[sys.executable, "-c", "import time; time.sleep(1.2)"],
         [sys.executable, "-c", "import time; time.sleep(1.2)"]],
        ["h0", "h1"], watch_dir=str(tmp_path), watch_interval=0.5)
    assert rc == 0
    out = capsys.readouterr().out
    assert "dslaunch --watch" in out
    assert "podhost0" in out and "podhost1" in out


def test_tpu_pod_labels():
    from deepspeed_tpu.launcher.tpu_discovery import PodInfo
    pod = PodInfo(workers=["10.0.0.5", "10.0.0.6"], my_index=0)
    assert pod.labels() == {"10.0.0.5": "w0", "10.0.0.6": "w1"}


# --------------------------------------------------------------------- #
# bench satellite: fleet summary fields
# --------------------------------------------------------------------- #
def test_bench_fleet_summary_degenerate_single_host():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    import bench
    out = bench._fleet_summary_fields(0.012, final_loss=3.3)
    fl = out["fleet"]
    assert fl[R.FL_HOSTS] == 1
    assert fl[R.FL_STEP_TIME_MEDIAN_S] == pytest.approx(0.012)
    assert fl["straggler"]["straggler"] is False
    assert len(fl["host_names"]) == 1
    assert "error" not in fl


# --------------------------------------------------------------------- #
# MoE health rules (ISSUE 15): dead expert, router collapse, EP load
# imbalance — rigged fleet matrices through the full sentinel ->
# capture-arming path
# --------------------------------------------------------------------- #
def _moe_summary(t=0.010, step=10, load=1.0, min_frac=0.9, entropy=0.8,
                 drop=0.01, imb=1.1, cold=2):
    return _summary(t, step=step, moe_drop_frac=drop, moe_entropy=entropy,
                    moe_imbalance=imb, moe_min_count_frac=min_frac,
                    moe_coldest_expert=cold, moe_local_load=load)


def test_dead_expert_rule_needs_consecutive_windows():
    health = FleetHealth(dead_expert_threshold=0.02,
                         dead_expert_windows=3)
    hosts = ["a", "b"]
    sick = _matrix([_moe_summary(min_frac=0.001)] * 2)
    healthy = _matrix([_moe_summary(min_frac=0.5)] * 2)
    assert health.observe(sick, hosts) == []
    assert health.observe(sick, hosts) == []
    # a healthy window resets the streak
    assert health.observe(healthy, hosts) == []
    assert health.observe(sick, hosts) == []
    assert health.observe(sick, hosts) == []
    evs = health.observe(sick, hosts)
    assert [e[R.H_EVENT] for e in evs] == [EVENT_DEAD_EXPERT]
    ev = evs[0]
    # model-level pathology: no process identity, nobody self-arms
    assert ev[R.F_PROCESS_INDEX] is None and ev[R.F_HOST] == "fleet"
    assert ev["expert"] == 2                 # the rigged coldest expert
    assert "dead expert" in ev[R.H_DETAIL] or "fair token share" in \
        ev[R.H_DETAIL]
    assert health.counters()["moe_events_flagged"] == 1


def test_router_collapse_rule_fires_at_entropy_floor():
    health = FleetHealth(entropy_floor=0.05, collapse_windows=2)
    hosts = ["a", "b"]
    collapsed = _matrix([_moe_summary(entropy=0.01)] * 2)
    assert health.observe(collapsed, hosts) == []
    evs = health.observe(collapsed, hosts)
    assert [e[R.H_EVENT] for e in evs] == [EVENT_ROUTER_COLLAPSE]
    assert "entropy" in evs[0][R.H_DETAIL]
    assert evs[0][R.F_PROCESS_INDEX] is None
    # dense fleets (NaN slots) never trip any moe rule
    dense = FleetHealth(entropy_floor=0.5, collapse_windows=1)
    for _ in range(3):
        assert dense.observe(_matrix([_summary(0.01)] * 2),
                             hosts) == []


def test_ep_imbalance_rule_leave_one_out_and_lane():
    health = FleetHealth(ep_imbalance_ratio=1.5, ep_imbalance_windows=2)
    hosts = [f"w{i}" for i in range(4)]
    rows = [_moe_summary(load=2.4 if p == 2 else 0.8)
            for p in range(4)]
    mat = _matrix(rows)
    assert health.observe(mat, hosts) == []  # window 1 of 2
    evs = health.observe(mat, hosts)
    assert [e[R.H_EVENT] for e in evs] == [EVENT_EP_IMBALANCE]
    ev = evs[0]
    assert ev[R.F_HOST] == "w2" and ev[R.F_PROCESS_INDEX] == 2
    assert ev[R.H_LANE] == ATTR_EXPERT_HOTSPOT
    assert ev[R.H_RATIO] == pytest.approx(3.0)  # 2.4 / peer-median 0.8
    assert "expert hot-spot on host w2" in ev[R.H_DETAIL]
    # balanced window resets the streak
    balanced = _matrix([_moe_summary(load=1.0)] * 4)
    assert health.observe(balanced, hosts) == []
    assert health.observe(mat, hosts) == []


def test_straggler_lane_names_expert_hotspot():
    """A straggler whose excess is explained by neither host-gap nor
    swap, but whose local experts carry past the EP gate, attributes as
    expert-hotspot instead of generic compute — the ISSUE 15 verdict
    upgrade."""
    health = FleetHealth(straggler_zscore=2.0, straggler_min_ratio=1.3,
                         warmup_windows=1, ep_imbalance_ratio=1.5)
    hosts = [f"w{i}" for i in range(4)]
    for _ in range(3):
        health.observe(_matrix([_moe_summary(0.010)] * 4), hosts)
    rows = [_moe_summary(0.010, load=0.8) for _ in range(4)]
    rows[2] = _moe_summary(0.030, load=2.4)   # slow AND expert-hot
    evs = health.observe(_matrix(rows), hosts)
    stragglers = [e for e in evs if e[R.H_EVENT] == EVENT_STRAGGLER]
    assert len(stragglers) == 1
    assert stragglers[0][R.H_LANE] == ATTR_EXPERT_HOTSPOT
    # straggler_verdict (the bench-row form) agrees
    verdict = straggler_verdict(_matrix(rows), hosts, min_ratio=1.3)
    assert verdict["straggler"] and verdict["host"] == "w2"
    assert verdict["lane"] == ATTR_EXPERT_HOTSPOT
    # and it honors a CONFIGURED ep gate exactly like the live
    # detector: a stricter ratio demotes the same matrix to compute
    strict = straggler_verdict(_matrix(rows), hosts, min_ratio=1.3,
                               ep_imbalance_ratio=4.0)
    assert strict["lane"] == ATTR_COMPUTE


def test_e2e_ep_imbalance_sentinel_and_capture(tmp_path):
    """ISSUE-15 acceptance: rigged EP-imbalance fleet matrix -> health
    event on the hot host -> sentinel health ring fed (abort budget
    untouched) -> capture armed on the flagged host, K-step disarm."""
    hosts = [f"w{i}" for i in range(4)]
    mats = []
    for w in range(4):
        rows = [_moe_summary(step=2 * (w + 1),
                             load=(2.4 if p == 2 and w >= 1 else 0.8))
                for p in range(4)]
        mats.append(_matrix(rows))
    rig = RiggedGather(hosts, mats)
    prof = MockProfiler()
    sentinel = TrainingSentinel()
    mon = TrainingMonitor(
        _fleet_cfg(tmp_path, capture={"enabled": True, "steps": 2,
                                      "max_captures": 1},
                   moe={"enabled": True, "ep_imbalance_ratio": 1.5,
                        "ep_imbalance_windows": 2}),
        process_index=2, world_size=4, host="w2",
        gather_fn=rig, profiler=prof,
        health_sink=sentinel.record_health_event)
    step = 0
    for _ in range(2):                       # windows 1-2: arming run-up
        for _ in range(2):
            step += 1
            mon.mark_step_start()
            mon.end_step(step, loss=2.0)
    assert not prof.active                   # streak 1 of 2: no event
    for _ in range(2):                       # window 3: streak reaches 2
        step += 1
        mon.mark_step_start()
        mon.end_step(step, loss=2.0)
    evs = mon.last_health_events
    assert [e[R.H_EVENT] for e in evs] == [EVENT_EP_IMBALANCE]
    assert evs[0][R.F_HOST] == "w2" and evs[0][R.F_PROCESS_INDEX] == 2
    # sentinel ring got the structured event; the ABORT budget did not
    assert sentinel.health_events_seen == 1
    assert sentinel.health_events[0][R.H_EVENT] == EVENT_EP_IMBALANCE
    assert sentinel.consecutive_anomalies == 0
    assert not sentinel.over_budget
    # flagged host (us) armed its own capture; K=2 steps then disarm
    assert prof.active and mon.capture.armed
    mon.mark_step_start()
    mon.end_step(step + 1, loss=2.0)
    mon.mark_step_start()
    mon.end_step(step + 2, loss=2.0)
    assert not mon.capture.armed
    assert prof.stopped == 1
    assert "ep_imbalance" in prof.started[0]
    mon.close()


def test_e2e_dead_expert_rank0_record_no_capture(tmp_path):
    """Dead-expert events carry no process identity: rank 0 writes the
    record + feeds its sentinel, and NO host self-arms a capture."""
    hosts = ["w0", "w1"]
    mats = [_matrix([_moe_summary(step=2 * (w + 1),
                                  min_frac=0.001)] * 2)
            for w in range(4)]
    rig = RiggedGather(hosts, mats)
    prof = MockProfiler()
    sentinel = TrainingSentinel()
    mon = TrainingMonitor(
        _fleet_cfg(tmp_path, capture={"enabled": True},
                   moe={"enabled": True, "dead_expert_windows": 2,
                        "dead_expert_threshold": 0.02}),
        process_index=0, world_size=2, host="w0",
        gather_fn=rig, profiler=prof,
        health_sink=sentinel.record_health_event)
    for step in range(1, 9):
        mon.mark_step_start()
        mon.end_step(step, loss=2.0)
    mon.close()
    assert not prof.started                  # nobody self-armed
    recs = [json.loads(line) for line in open(mon.jsonl_path)]
    dead = [r for r in recs if r.get(R.F_KIND) == KIND_HEALTH
            and r.get(R.H_EVENT) == EVENT_DEAD_EXPERT]
    assert len(dead) >= 1
    assert dead[0][R.F_HOST] == "fleet"
    assert sentinel.health_events_seen == len(dead)
    # the rigged fleet records also carry the per-host moe load column
    fleet = [r for r in recs if r.get(R.F_KIND) == KIND_FLEET]
    assert fleet and fleet[0][R.FL_PER_HOST]["moe_local_load"] == [
        1.0, 1.0]


def test_e2e_router_collapse_sentinel_ring_budget_untouched(tmp_path):
    """Router-collapse through the full path: rigged entropy floor ->
    health event -> sentinel ring fed, abort budget untouched, no
    capture (fleet-global event carries no process identity)."""
    hosts = ["w0", "w1"]
    mats = [_matrix([_moe_summary(step=2 * (w + 1),
                                  entropy=0.01)] * 2)
            for w in range(3)]
    rig = RiggedGather(hosts, mats)
    prof = MockProfiler()
    sentinel = TrainingSentinel(anomaly_budget=1)
    mon = TrainingMonitor(
        _fleet_cfg(tmp_path, capture={"enabled": True},
                   moe={"enabled": True, "entropy_floor": 0.05,
                        "collapse_windows": 2}),
        process_index=0, world_size=2, host="w0",
        gather_fn=rig, profiler=prof,
        health_sink=sentinel.record_health_event)
    for step in range(1, 7):
        mon.mark_step_start()
        mon.end_step(step, loss=2.0)
    mon.close()
    assert not prof.started
    recs = [json.loads(line) for line in open(mon.jsonl_path)]
    collapse = [r for r in recs if r.get(R.F_KIND) == KIND_HEALTH
                and r.get(R.H_EVENT) == EVENT_ROUTER_COLLAPSE]
    assert len(collapse) >= 1 and "entropy" in collapse[0][R.H_DETAIL]
    assert sentinel.health_events_seen == len(collapse)
    # a tight abort budget survives: health events never count toward it
    assert sentinel.consecutive_anomalies == 0
    assert not sentinel.over_budget
