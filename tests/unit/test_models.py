"""Model-family tests — tiny GPT-2/BERT configs trained through the engine
(the analog of the reference's simple_model.py fixtures + model-level
convergence checks, tests/model/run_func_test.py)."""

import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import BertConfig, BertModel, GPT2Config, GPT2Model


def tiny_gpt2(**kw):
    defaults = dict(vocab_size=256, n_positions=32, hidden_size=32,
                    num_layers=2, num_heads=2, bf16=False,
                    embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    defaults.update(kw)
    return GPT2Config(**defaults)


def test_gpt2_loss_shape_and_initial_value():
    cfg = tiny_gpt2()
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    loss = model.loss(params, None, ids)
    assert loss.shape == ()
    # ~uniform prediction at init => loss ~ log(vocab)
    assert abs(float(loss) - np.log(256)) < 1.0


def test_gpt2_partition_specs_match_param_tree():
    cfg = tiny_gpt2()
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    specs = model.param_partition_specs()
    # identical tree structure (specs are leaves)
    from jax.sharding import PartitionSpec
    jax.tree.map(lambda p, s: None, params,
                 jax.tree.map(lambda s: s, specs,
                              is_leaf=lambda x: isinstance(x, PartitionSpec)))


def test_gpt2_num_params_matches_tree():
    cfg = tiny_gpt2()
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    actual = sum(int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(params))
    assert cfg.num_params() == actual


def test_gpt2_trains_through_engine():
    cfg = tiny_gpt2()
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config,
                                    model_parameters=params)
    ids = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 256))
    losses = []
    for _ in range(8):
        loss = engine.forward(ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt2_activation_checkpointing_same_loss():
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    losses = {}
    for ckpt in (False, True):
        cfg = tiny_gpt2(activation_checkpointing=ckpt)
        model = GPT2Model(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        grads = jax.grad(lambda p: model.loss(p, None, ids))(params)
        losses[ckpt] = (float(model.loss(params, None, ids)),
                        float(jnp.mean(jnp.abs(grads["wte"]))))
    assert np.allclose(losses[False], losses[True], rtol=1e-5)


def test_bert_mlm_loss_ignores_unmasked_positions():
    cfg = BertConfig(vocab_size=128, max_position_embeddings=32,
                     hidden_size=32, num_layers=1, num_heads=2, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = BertModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 128)
    labels_all_ignored = jnp.full((2, 8), -100)
    loss = model.mlm_loss(params, None, ids, labels_all_ignored)
    assert float(loss) == 0.0

    labels = labels_all_ignored.at[:, 0].set(5)
    loss2 = model.mlm_loss(params, None, ids, labels)
    assert float(loss2) > 0.0


def test_bert_attention_mask_changes_output():
    cfg = BertConfig(vocab_size=128, max_position_embeddings=32,
                     hidden_size=32, num_layers=1, num_heads=2, bf16=False,
                     embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    model = BertModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 128)
    full = np.asarray(model.hidden_states(params, ids))
    masked = np.asarray(model.hidden_states(
        params, ids, attention_mask=jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]])))
    assert not np.allclose(full[:, 0], masked[:, 0])


def test_gpt2_tensor_parallel_training_on_mesh():
    """TP x DP: hidden sharded over model axis, batch over data axis."""
    ds.reset_mesh_context()
    mesh = ds.initialize_mesh(data=2, model=4)
    cfg = tiny_gpt2(hidden_size=64, num_heads=4, vocab_size=256)
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config,
                                    model_parameters=params, mesh=mesh)
    # TP specs picked up from the model automatically
    assert engine.param_specs is not None
    ids = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 256))
    loss0 = engine.forward(ids)
    engine.backward(loss0)
    engine.step()
    loss1 = engine.forward(ids)
    engine.backward(loss1)
    engine.step()
    assert float(loss1) < float(loss0)


def test_bert_activation_checkpointing_same_loss_and_grads():
    """BertConfig.activation_checkpointing must be a pure memory knob —
    identical loss and gradients (it is what lets bert_s512 fit 24 layers
    of seq-512 activations in HBM; bench.py r4)."""
    cfg_kw = dict(vocab_size=128, max_position_embeddings=32,
                  hidden_size=32, num_layers=2, num_heads=2, bf16=False,
                  embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    out = {}
    for ckpt in (False, True):
        model = BertModel(BertConfig(activation_checkpointing=ckpt,
                                     **cfg_kw))
        params = model.init_params(jax.random.PRNGKey(0))
        loss, grads = jax.value_and_grad(
            lambda p: model.mlm_loss(p, None, ids, ids))(params)
        out[ckpt] = (float(loss),
                     float(jnp.mean(jnp.abs(jax.tree.leaves(grads)[0]))))
    assert np.allclose(out[False], out[True], rtol=1e-5)
