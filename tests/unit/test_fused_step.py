"""Fused whole-step train program (runtime/fused_step.py; docs/fused_step.md).

Covers the PR-3 acceptance surface:
  - numerical parity with the modular forward/backward/step loop over >=5
    optimizer steps at gas=4 for fp32, bf16, and fp16 dynamic scaling with
    a forced overflow (the skipped step must match on both paths);
  - a fused-path ZeRO-3 streaming case (scan-in-scan);
  - the dispatch-count regression: the fused path issues exactly ONE
    compiled-program invocation per optimizer step, the modular path 2N
    (N grad programs + N-1 accumulation adds + 1 apply);
  - the automatic-fallback matrix for host-interactive features;
  - in-program loss-only sentinel monitoring (skip policy rides the
    per-leaf select predicate);
  - the coalesced host reads of the async host loop (summary writer /
    get_lr only at boundaries).
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.dataloader import stack_microbatches
from tests.unit.simple_model import (base_engine_config, simple_model_apply,
                                     simple_model_params)

HIDDEN = 16
MICRO = 8
GAS = 4


def make_engine(fused, gas=GAS, micro=MICRO, extra=None, model=None,
                params=None):
    ds.reset_mesh_context()
    cfg = base_engine_config(micro_batch=micro, gas=gas)
    cfg["fused_step"] = {"enabled": bool(fused)}
    if extra:
        cfg.update(extra)
    engine, _, _, _ = ds.initialize(
        model=model or simple_model_apply, config=cfg,
        model_parameters=params if params is not None
        else simple_model_params(HIDDEN))
    return engine


def data_stream(n_steps, gas=GAS, micro=MICRO, seed=3, poison=None,
                scale=1.0):
    """[(x, y)] covering n_steps optimizer steps; poison=(step, factor)
    multiplies ONE microbatch's inputs at that step."""
    rng = np.random.RandomState(seed)
    out = []
    for s in range(n_steps):
        for m in range(gas):
            x = rng.normal(0, 1, (micro, HIDDEN)).astype(np.float32) * scale
            y = rng.normal(0, 1, (micro,)).astype(np.float32)
            if poison is not None and s == poison[0] and m == 1:
                x = x * poison[1]
            out.append((x, y))
    return out


def run_modular(engine, batches, gas=GAS):
    it = iter(batches)
    losses = []
    for _ in range(len(batches) // gas):
        micro_losses = []
        for _ in range(gas):
            x, y = next(it)
            loss = engine.forward(x, y)
            engine.backward(loss)
            engine.step()
            micro_losses.append(np.asarray(loss).item())
        losses.append(float(np.mean(micro_losses)))
    return losses


def run_fused(engine, batches, gas=GAS):
    it = iter(batches)
    return [np.asarray(engine.train_batch(it)).item()
            for _ in range(len(batches) // gas)]


def assert_tree_close(a, b, atol):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                                atol=atol), a, b)


# --------------------------------------------------------------------- #
# parity: fused vs modular
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("dtype_cfg, atol", [
    ({}, 1e-5),
    ({"bf16": {"enabled": True}}, 1e-2),
])
def test_fused_matches_modular(dtype_cfg, atol):
    batches = data_stream(6)
    e_mod = make_engine(False, extra=dtype_cfg)
    l_mod = run_modular(e_mod, batches)
    e_fus = make_engine(True, extra=dtype_cfg)
    assert e_fus._fused_step_fn is not None, e_fus.fused_step_reason
    l_fus = run_fused(e_fus, batches)
    np.testing.assert_allclose(l_mod, l_fus, atol=atol, rtol=1e-4)
    assert_tree_close(e_mod.params, e_fus.params, atol)
    assert_tree_close(e_mod.opt_state, e_fus.opt_state, atol)
    assert e_mod.global_steps == e_fus.global_steps == 6
    assert e_mod.micro_steps == e_fus.micro_steps == 6 * GAS


def test_fused_matches_modular_fp16_overflow_skip():
    """fp16 dynamic scaling with one poisoned microbatch: the overflow
    must skip the step (per-leaf selects) IDENTICALLY on both paths —
    same skipped_steps, same post-run loss scale, same params/opt
    trajectory through the skip."""
    fp16 = {"fp16": {"enabled": True, "initial_scale_power": 4,
                     "loss_scale_window": 100, "hysteresis": 1}}
    # 1e30 saturates the f16 cast -> inf activations -> NaN grads
    batches = data_stream(6, poison=(2, 1e30))
    e_mod = make_engine(False, extra=fp16)
    l_mod = run_modular(e_mod, batches)
    e_fus = make_engine(True, extra=fp16)
    assert e_fus._fused_step_fn is not None, e_fus.fused_step_reason
    l_fus = run_fused(e_fus, batches)
    assert e_mod.skipped_steps == e_fus.skipped_steps == 1
    assert e_mod.loss_scale == e_fus.loss_scale < 2.0 ** 4
    # the poisoned step's loss is NaN on both paths; compare the rest
    np.testing.assert_allclose(np.delete(l_mod, 2), np.delete(l_fus, 2),
                               atol=1e-3, rtol=1e-3)
    assert np.isnan(l_mod[2]) and np.isnan(l_fus[2])
    assert_tree_close(e_mod.params, e_fus.params, 1e-4)
    assert_tree_close(e_mod.opt_state, e_fus.opt_state, 1e-4)


@pytest.mark.parametrize("stream_cfg", [
    pytest.param({"stage3_max_live_parameters": 10_000,
                  "stage3_prefetch_bucket_size": 0}, id="at_use"),
    # carried double-buffer prefetch nested INSIDE the fused gas scan
    # (scan-in-scan-in-scan): the hand-written VJP's residuals are the
    # group-boundary carries, so the outer scan never stacks gathered
    # groups across microbatches (ISSUE 7)
    pytest.param({"stage3_max_live_parameters": 100_000,
                  "stage3_prefetch_bucket_size": 100_000,
                  "stage3_prefetch_mode": "carried"}, id="carried"),
])
def test_fused_zero3_streaming_parity(stream_cfg):
    """Scan-in-scan: the fused program's microbatch scan wraps the ZeRO-3
    streamed layer scan (at-use or carried prefetch) without changes."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model

    batch, seq, gas, steps = 8, 16, 2, 2
    zero3 = {"zero_optimization": dict({"stage": 3}, **stream_cfg)}

    def build(fused):
        ds.reset_mesh_context()
        mesh = ds.initialize_mesh(data=-1)
        cfg = GPT2Config(vocab_size=64, n_positions=seq, hidden_size=32,
                         num_layers=2, num_heads=2, bf16=False,
                         embd_dropout=0.0, attn_dropout=0.0,
                         hidden_dropout=0.0)
        model = GPT2Model(cfg)
        conf = {"train_micro_batch_size_per_gpu": batch,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9,
                "fused_step": {"enabled": fused}}
        conf.update(zero3)
        engine, _, _, _ = ds.initialize(
            model=model, config=conf,
            model_parameters=model.init_params(jax.random.PRNGKey(0)),
            mesh=mesh, rng=jax.random.PRNGKey(7))
        return engine

    rng = np.random.RandomState(0)
    batches = [(rng.randint(0, 64, size=(batch, seq)).astype(np.int32),)
               for _ in range(gas * steps)]
    e_mod = build(False)
    it = iter(batches)
    l_mod = []
    for _ in range(steps):
        micro = []
        for _ in range(gas):
            (ids,) = next(it)
            loss = e_mod.forward(ids)
            e_mod.backward(loss)
            e_mod.step()
            micro.append(np.asarray(loss).item())
        l_mod.append(float(np.mean(micro)))
    e_fus = build(True)
    assert e_fus._fused_step_fn is not None, e_fus.fused_step_reason
    l_fus = run_fused(e_fus, batches, gas=gas)
    if stream_cfg.get("stage3_prefetch_mode") == "carried":
        # the plan is recorded when the fused program traces the scan
        assert e_fus._zero3_stream.last_plan.mode == "carried"
        assert e_fus._zero3_stream.last_plan.prefetch
    np.testing.assert_allclose(l_mod, l_fus, rtol=2e-4)
    assert_tree_close(e_mod.params, e_fus.params, 2e-5)


# --------------------------------------------------------------------- #
# dispatch-count regression
# --------------------------------------------------------------------- #
class _CountCalls:
    def __init__(self, fn):
        self.fn = fn
        self.calls = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        return self.fn(*args, **kwargs)


def _wrap_programs(engine):
    counters = {}
    for name in ("_grad_fn", "_acc_fn", "_apply_fn", "_fused_step_fn"):
        fn = getattr(engine, name, None)
        if fn is not None:
            counters[name] = _CountCalls(fn)
            setattr(engine, name, counters[name])
    return counters


def test_dispatch_count_fused_is_one_modular_is_2n():
    """The whole point of the fused path: 1 compiled-program invocation
    per optimizer step, vs the modular loop's 2N (N grad programs, N-1
    accumulation adds — the first microbatch adopts the grad buffer
    directly — and 1 apply).  Wrapping the engine's compiled callables
    counts every dispatch the step loop can issue, so the win cannot
    silently regress."""
    steps = 3
    batches = data_stream(steps)

    e_fus = make_engine(True)
    assert e_fus._fused_step_fn is not None, e_fus.fused_step_reason
    c_fus = _wrap_programs(e_fus)
    run_fused(e_fus, batches)
    assert c_fus["_fused_step_fn"].calls == steps          # exactly 1/step
    assert c_fus["_grad_fn"].calls == 0
    assert c_fus["_acc_fn"].calls == 0
    assert c_fus["_apply_fn"].calls == 0

    e_mod = make_engine(False)
    c_mod = _wrap_programs(e_mod)
    run_modular(e_mod, batches)
    assert c_mod["_grad_fn"].calls == steps * GAS
    assert c_mod["_acc_fn"].calls == steps * (GAS - 1)
    assert c_mod["_apply_fn"].calls == steps
    total = sum(c.calls for c in c_mod.values())
    assert total == steps * 2 * GAS                         # 2N per step


# --------------------------------------------------------------------- #
# config gating + fallback matrix
# --------------------------------------------------------------------- #
def test_fused_off_by_default():
    ds.reset_mesh_context()
    engine, _, _, _ = ds.initialize(
        model=simple_model_apply, config=base_engine_config(micro_batch=MICRO),
        model_parameters=simple_model_params(HIDDEN))
    assert engine._fused_step_fn is None
    assert engine.fused_step_reason is None  # off, not fallen back


@pytest.mark.parametrize("extra, marker", [
    ({"zero_optimization": {"stage": 2,
                            "offload_optimizer": {"device": "cpu"}}},
     "offload_optimizer"),
    ({"quantize_training": {"enabled": True, "quantize_groups": 1}},
     "quantize-training"),
    ({"progressive_layer_drop": {"enabled": True}}, "progressive_layer_drop"),
    ({"curriculum_learning": {"enabled": True,
                              "curriculum_type": "fixed_linear",
                              "min_difficulty": 4, "max_difficulty": 16,
                              "schedule_config": {"total_curriculum_step": 10,
                                                  "difficulty_step": 8}}},
     "curriculum_learning"),
    ({"resilience": {"enabled": True,
                     "sentinel": {"enabled": True, "policy": "rewind",
                                  "monitor_grad_norm": False}}},
     "rewind"),
    ({"resilience": {"enabled": True,
                     "sentinel": {"enabled": True, "policy": "skip_step",
                                  "monitor_grad_norm": True}}},
     "grad-norm"),
])
def test_fused_falls_back_for_host_interactive_features(extra, marker):
    def pld_model(params, rng, x, y, pld_theta=None):
        return simple_model_apply(params, rng, x, y)

    engine = make_engine(True, extra=extra, model=pld_model)
    assert engine._fused_step_fn is None
    assert engine.fused_step_reason is not None
    assert marker in engine.fused_step_reason


def test_fused_fallback_offload_still_trains():
    """The offload fallback must run the modular loop through the same
    train_batch API — and at gas>1 this exercises the host optimizer's
    grad scaling on read-only device-array views (fixed in this PR)."""
    extra = {"zero_optimization": {"stage": 2,
                                   "offload_optimizer": {"device": "cpu"}}}
    engine = make_engine(True, extra=extra)
    assert engine._fused_step_fn is None
    assert "offload_optimizer" in engine.fused_step_reason
    losses = [engine.train_batch(iter(data_stream(1, seed=40 + i)))
              for i in range(2)]
    assert all(np.isfinite(loss) for loss in losses)


# --------------------------------------------------------------------- #
# in-program loss-only sentinel
# --------------------------------------------------------------------- #
def test_fused_sentinel_skip_policy_skips_in_program():
    """A k-sigma loss anomaly with FINITE gradients must zero the update
    INSIDE the fused program (healthy rides the same per-leaf select as
    the overflow skip — the apply's own finite check would not fire).
    The EWMA state is rigged to a warmed, far-off baseline so the verdict
    is deterministic regardless of training noise."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.fused_step import FusedSentinelState

    sent = {"resilience": {"enabled": True,
                           "sentinel": {"enabled": True,
                                        "policy": "skip_step",
                                        "monitor_grad_norm": False,
                                        "warmup_steps": 2, "k_sigma": 6.0,
                                        "anomaly_budget": 50}}}
    engine = make_engine(True, extra=sent)
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    run_fused(engine, data_stream(2, seed=11))
    engine._drain_fused_sentinel()

    def rig(mean, var, count):
        engine._fused_sent_state = jax.device_put(
            FusedSentinelState(mean=jnp.asarray(mean, jnp.float32),
                               var=jnp.asarray(var, jnp.float32),
                               count=jnp.asarray(count, jnp.int32)),
            engine.mesh_ctx.replicated())

    pre_skipped = engine.skipped_steps
    rig(mean=1e6, var=1e-6, count=100)  # any real loss is >>6 sigma away
    before = jax.tree.map(np.asarray, engine.params)
    spike_loss = run_fused(engine, data_stream(1, seed=12))[0]
    assert np.isfinite(spike_loss)  # grads were finite — only the
    after = jax.tree.map(np.asarray, engine.params)  # sentinel skipped
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 before, after)
    engine._drain_fused_sentinel()
    assert engine.skipped_steps == pre_skipped + 1
    assert engine.sentinel.counters()["steps_skipped"] >= 1
    # a rigged-clean baseline lets training continue
    rig(mean=spike_loss, var=1e6, count=100)
    run_fused(engine, data_stream(1, seed=13))
    final = jax.tree.map(np.asarray, engine.params)
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(after), jax.tree.leaves(final)))


def test_fused_sentinel_skip_freezes_lr_scheduler_and_counts_once():
    """Parity with step()'s skip chain: a sentinel-skipped step must not
    advance the host lr scheduler, and a step that is BOTH an fp16
    overflow and a sentinel flag counts toward skipped_steps exactly
    once (the sentinel branch wins, like the modular if/elif)."""
    import jax.numpy as jnp
    from deepspeed_tpu.runtime.fused_step import FusedSentinelState

    extra = {"fp16": {"enabled": True, "initial_scale_power": 4,
                      "loss_scale_window": 100, "hysteresis": 2},
             "scheduler": {"type": "WarmupLR",
                           "params": {"warmup_min_lr": 0.0,
                                      "warmup_max_lr": 1e-2,
                                      "warmup_num_steps": 100}},
             "resilience": {"enabled": True,
                            "sentinel": {"enabled": True,
                                         "policy": "skip_step",
                                         "monitor_grad_norm": False,
                                         "warmup_steps": 2,
                                         "anomaly_budget": 50}}}
    engine = make_engine(True, extra=extra)
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    run_fused(engine, data_stream(2, seed=50))
    sched_before = engine.lr_scheduler.last_batch_iteration
    # NaN loss: overflow AND nonfinite sentinel flag on the same step
    run_fused(engine, data_stream(1, seed=51, poison=(0, np.inf)))
    engine._drain_fused_sentinel()
    assert engine.skipped_steps == 1  # once, not twice
    assert engine.lr_scheduler.last_batch_iteration == sched_before
    # rigged finite k-sigma skip: scheduler still frozen
    engine._fused_sent_state = jax.device_put(
        FusedSentinelState(mean=jnp.asarray(1e6, jnp.float32),
                           var=jnp.asarray(1e-6, jnp.float32),
                           count=jnp.asarray(100, jnp.int32)),
        engine.mesh_ctx.replicated())
    run_fused(engine, data_stream(1, seed=52))
    assert engine.skipped_steps == 2
    assert engine.lr_scheduler.last_batch_iteration == sched_before


def test_fused_sentinel_warmup_zero_never_flags_first_step():
    """warmup_steps=0 must not flag the very first observation (the
    device EWMA mean is a placeholder until something is observed) —
    mirrors the host sentinel's mean-is-None guard."""
    sent = {"resilience": {"enabled": True,
                           "sentinel": {"enabled": True,
                                        "policy": "skip_step",
                                        "monitor_grad_norm": False,
                                        "warmup_steps": 0,
                                        "anomaly_budget": 50}}}
    engine = make_engine(True, extra=sent)
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    before = jax.tree.map(np.asarray, engine.params)
    run_fused(engine, data_stream(1, seed=60))
    engine._drain_fused_sentinel()
    assert engine.skipped_steps == 0
    assert engine.sentinel.counters()["anomalies_seen"] == 0
    after = jax.tree.map(np.asarray, engine.params)
    assert any(not np.array_equal(a, b) for a, b in
               zip(jax.tree.leaves(before), jax.tree.leaves(after)))


def test_fused_sentinel_state_survives_checkpoint(tmp_path):
    """save_checkpoint folds the in-program EWMA back into the host
    sentinel; load re-seeds the device state."""
    sent = {"resilience": {"enabled": True,
                           "sentinel": {"enabled": True, "policy": "warn",
                                        "monitor_grad_norm": False,
                                        "warmup_steps": 2}}}
    engine = make_engine(True, extra=sent)
    assert engine._fused_step_fn is not None, engine.fused_step_reason
    run_fused(engine, data_stream(4, seed=21))
    engine.save_checkpoint(str(tmp_path), tag="t4")
    assert engine.sentinel.loss_stat.count == 4
    assert engine.sentinel.loss_stat.mean is not None
    engine2 = make_engine(True, extra=sent)
    engine2.load_checkpoint(str(tmp_path), tag="t4")
    assert int(np.asarray(engine2._fused_sent_state.count)) == 4
    np.testing.assert_allclose(np.asarray(engine2._fused_sent_state.mean),
                               engine.sentinel.loss_stat.mean, rtol=1e-6)


# --------------------------------------------------------------------- #
# microbatch stacking
# --------------------------------------------------------------------- #
def test_stack_microbatches():
    b = [(np.ones((2, 3)), {"y": np.zeros((2,))}) for _ in range(4)]
    stacked = stack_microbatches(b)
    assert stacked[0].shape == (4, 2, 3)
    assert stacked[1]["y"].shape == (4, 2)
    with pytest.raises(ValueError, match="tree structure"):
        stack_microbatches([(np.ones(2),), (np.ones(2), np.ones(2))])
    with pytest.raises(ValueError, match="at least one"):
        stack_microbatches([])


# --------------------------------------------------------------------- #
# async host loop: coalesced boundary reads (modular path satellite)
# --------------------------------------------------------------------- #
class _RecordingWriter:
    def __init__(self):
        self.scalars = []

    def add_scalar(self, tag, value, step):
        self.scalars.append((tag, value, step))


def test_summary_writer_and_lr_reads_only_at_boundaries():
    """step() used to call float(self._last_loss) + get_lr() for the
    writer on EVERY step, forcing a device sync each step; both must now
    run only at steps_per_print / tensorboard.write_interval boundaries."""
    engine = make_engine(False, extra={"steps_per_print": 3})
    writer = _RecordingWriter()
    engine._summary_writer = writer
    engine._tb_write_interval = 3
    lr_calls = []
    orig_get_lr = engine.get_lr
    engine.get_lr = lambda: (lr_calls.append(engine.global_steps)
                             or orig_get_lr())
    run_modular(engine, data_stream(7, seed=31))
    written_steps = sorted({s for (tag, _, s) in writer.scalars
                            if tag == "Train/Samples/lr"})
    assert written_steps == [3, 6]
    assert sorted(set(lr_calls)) == [3, 6]


def test_tb_write_interval_config():
    engine = make_engine(False, extra={"steps_per_print": 100,
                                       "tensorboard": {"enabled": False,
                                                       "write_interval": 7}})
    assert engine._tb_write_interval == 7
    engine = make_engine(False, extra={"steps_per_print": 100})
    assert engine._tb_write_interval == 100
