"""BERT family — encoder LM (the role bing_bert plays in the reference's
headline benchmarks: BERT-large pretraining, docs/_tutorials/bert-pretraining.md
and the fused-kernel tests tests/unit/modeling.py:1597).

Same TPU structure as GPT-2: stacked layers + lax.scan, fused transformer
body, declarative TP specs.  Loss = masked-LM cross entropy (positions with
label == ignore_index contribute nothing), matching the reference pretraining
objective minus NSP (which modern recipes drop).
"""

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)
from ..ops.normalize import fused_layer_norm
from ..ops.activations import dropout
from ..parallel.mesh import MODEL_AXIS


@dataclass
class BertConfig:
    vocab_size: int = 30592          # 30522 padded to a 128 multiple
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 1024          # BERT-large defaults
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: Optional[int] = None
    embd_dropout: float = 0.1
    attn_dropout: float = 0.1
    hidden_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    hidden_act: str = "gelu"         # HF BERT default: exact erf gelu
    initializer_range: float = 0.02
    bf16: bool = True
    # attention kernel layout: "bhsd" (classic) or "bshd" (API
    # convenience; converts at the kernel boundary — a native bshd
    # BlockSpec is Mosaic-illegal, measured round 3)
    attn_layout: str = "bhsd"
    attn_dropout_impl: str = "kernel"  # "kernel" (reference semantics) | "ctx" (cheaper)
    pre_layer_norm: bool = True      # reference supports both (preln/postln)
    activation_checkpointing: bool = False
    sparse_attention: Optional[object] = None  # a SparsityConfig
    ignore_index: int = -100
    # layer-stack execution, same semantics as GPT2Config.scan_layers
    scan_layers: Optional[bool] = None
    # chunked LM-head + CE (ops/fused_cross_entropy.py) — never SAVES the
    # [B, S, V] fp32 logits; None = auto chunk from the transient budget
    fused_loss: bool = True
    fused_loss_chunk: Optional[int] = None

    @property
    def use_scan(self) -> bool:
        from .layer_stack import resolve_use_scan
        return resolve_use_scan(self.scan_layers, self.num_layers)

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def dtype(self):
        return jnp.bfloat16 if self.bf16 else jnp.float32

    def layer_config(self) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_heads,
            attn_dropout_ratio=self.attn_dropout,
            hidden_dropout_ratio=self.hidden_dropout,
            num_hidden_layers=self.num_layers,
            initializer_range=self.initializer_range,
            layer_norm_eps=self.layer_norm_eps,
            bf16=self.bf16,
            pre_layer_norm=self.pre_layer_norm,
            causal=False,
            activation=self.hidden_act,
            sparsity_config=self.sparse_attention,
            attn_layout=self.attn_layout,
            attn_dropout_impl=self.attn_dropout_impl,
        )

    def num_params(self, include_embeddings: bool = True) -> int:
        layer = DeepSpeedTransformerLayer(self.layer_config())
        n = self.num_layers * layer.num_params() + 2 * self.hidden_size
        if include_embeddings:
            n += (self.vocab_size + self.max_position_embeddings +
                  self.type_vocab_size) * self.hidden_size
        return n

    def flops_per_token(self, seq_len: Optional[int] = None) -> int:
        """Training FLOPs/token (fwd+bwd ≈ 6N + attention + MLM head), the
        Megatron-style accounting used for MFU (matches GPT2Config: the
        vocab projection is a real MXU matmul and belongs in the count)."""
        n = self.num_params(include_embeddings=False)
        s = seq_len if seq_len is not None else self.max_position_embeddings
        attn = 12 * self.num_layers * self.hidden_size * s
        head = 6 * self.hidden_size * self.vocab_size
        return 6 * n + attn + head


class BertModel:
    """Encoder LM over stacked DeepSpeedTransformerLayers (MLM objective)."""

    def __init__(self, config: BertConfig):
        self.config = config
        self.layer = DeepSpeedTransformerLayer(config.layer_config())

    def init_params(self, rng):
        cfg = self.config
        k_wte, k_wpe, k_tte, k_layers = jax.random.split(rng, 4)
        init = jax.nn.initializers.normal(cfg.initializer_range)
        layer_keys = jax.random.split(k_layers, cfg.num_layers)
        stacked = jax.vmap(self.layer.init_params)(layer_keys)
        return {
            "wte": init(k_wte, (cfg.vocab_size, cfg.hidden_size), jnp.float32),
            "wpe": init(k_wpe, (cfg.max_position_embeddings, cfg.hidden_size),
                        jnp.float32),
            "tte": init(k_tte, (cfg.type_vocab_size, cfg.hidden_size),
                        jnp.float32),
            "emb_ln": {"w": jnp.ones((cfg.hidden_size,), jnp.float32),
                       "b": jnp.zeros((cfg.hidden_size,), jnp.float32)},
            "h": stacked,
        }

    def param_partition_specs(self):
        layer_specs = DeepSpeedTransformerLayer.param_partition_specs()
        stacked_specs = {k: P(None, *list(s)) for k, s in layer_specs.items()}
        return {
            "wte": P(MODEL_AXIS, None),
            "wpe": P(),
            "tte": P(),
            "emb_ln": {"w": P(), "b": P()},
            "h": stacked_specs,
        }

    def hidden_states(self, params, input_ids, attention_mask=None,
                      token_type_ids=None, rng=None,
                      deterministic: bool = False):
        cfg = self.config
        b, s = input_ids.shape
        if rng is None:
            deterministic = True
            rng = jax.random.PRNGKey(0)
        r_embd, r_layers = jax.random.split(rng)

        h = (params["wte"].astype(cfg.dtype)[input_ids] +
             params["wpe"].astype(cfg.dtype)[jnp.arange(s)])
        if token_type_ids is not None:
            h = h + params["tte"].astype(cfg.dtype)[token_type_ids]
        h = fused_layer_norm(h, params["emb_ln"]["w"], params["emb_ln"]["b"],
                             cfg.layer_norm_eps)
        h = dropout(h, cfg.embd_dropout, r_embd, deterministic)

        bias = None
        if attention_mask is not None:
            # [B, S] 1/0 mask -> additive [B, 1, 1, S]
            bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                             -1e9).astype(jnp.float32)

        layer_fn = self.layer

        def body(carry, xs):
            layer_params, layer_rng = xs
            out = layer_fn(layer_params, carry, attn_mask=bias, rng=layer_rng,
                           deterministic=deterministic)
            return out, None

        if cfg.activation_checkpointing:
            body = jax.checkpoint(body)
        layer_rngs = jax.random.split(r_layers, cfg.num_layers)
        from .layer_stack import run_layer_stack
        return run_layer_stack(body, h, (params["h"], layer_rngs),
                               cfg.use_scan)

    def mlm_loss(self, params, rng, input_ids, labels,
                 attention_mask=None, token_type_ids=None):
        """Masked-LM loss; positions with labels == ignore_index are
        excluded (reference objective, bing_bert pretraining)."""
        cfg = self.config
        h = self.hidden_states(params, input_ids, attention_mask,
                               token_type_ids, rng)
        if cfg.fused_loss:
            from ..ops.fused_cross_entropy import fused_linear_cross_entropy
            return fused_linear_cross_entropy(
                h.reshape(-1, cfg.hidden_size),
                params["wte"].astype(h.dtype).T,
                labels.reshape(-1).astype(jnp.int32),
                cfg.fused_loss_chunk, cfg.ignore_index)
        logits = (h @ params["wte"].astype(h.dtype).T).astype(jnp.float32)
        valid = labels != cfg.ignore_index
        safe_labels = jnp.where(valid, labels, 0)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, safe_labels)
        denom = jnp.maximum(jnp.sum(valid), 1)
        return jnp.sum(per_tok * valid) / denom

    def __call__(self, params, rng, input_ids, labels,
                 attention_mask=None, token_type_ids=None):
        return self.mlm_loss(params, rng, input_ids, labels,
                             attention_mask, token_type_ids)
