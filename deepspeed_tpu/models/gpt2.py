"""GPT-2 family — the flagship decoder LM (the role Megatron-GPT2 plays for
the reference's headline ZeRO benchmarks, docs/_tutorials/megatron.md).

TPU-native structure:
  - all transformer layers stored STACKED (leading layer axis) and executed
    with `lax.scan` — one compiled layer body regardless of depth, the
    XLA-friendly analog of the reference's per-layer module list;
  - per-layer activation checkpointing = `jax.checkpoint` around the scanned
    body (reference: runtime/activation_checkpointing/checkpointing.py);
  - tensor parallelism is declarative: `param_partition_specs` emits
    Megatron-style column/row specs over the "model" mesh axis, vocab-sharded
    embedding included (the role of Megatron's VocabParallelEmbedding).
"""

from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)
from ..ops.normalize import fused_layer_norm
from ..ops.activations import dropout
from ..parallel.mesh import MODEL_AXIS


@dataclass
class GPT2Config:
    vocab_size: int = 50304          # 50257 padded to a 128 multiple (MXU)
    n_positions: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    embd_dropout: float = 0.1
    attn_dropout: float = 0.1
    hidden_dropout: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    bf16: bool = True
    # attention kernel layout: "bhsd" (classic) or "bshd" (API
    # convenience; converts at the kernel boundary — a native bshd
    # BlockSpec is Mosaic-illegal, measured round 3)
    attn_layout: str = "bhsd"
    attn_dropout_impl: str = "kernel"  # "kernel" (reference semantics) | "ctx" (cheaper)
    activation_checkpointing: bool = False
    sparse_attention: Optional[object] = None  # a SparsityConfig
    tie_word_embeddings: bool = True
    # chunked LM-head + cross-entropy: never SAVES the [B,S,V] fp32 logits
    # (ops/fused_cross_entropy.py); None = auto chunk from the transient
    # budget (largest chunk wins on speed — profile_ce_sweep.py)
    fused_loss: bool = True
    fused_loss_chunk: Optional[int] = None
    # layer-stack execution: None = auto (unrolled up to the measured
    # threshold, scan beyond — see models/layer_stack.py).  ZeRO-3
    # streaming always uses its gather-scan.
    scan_layers: Optional[bool] = None

    @property
    def use_scan(self) -> bool:
        from .layer_stack import resolve_use_scan
        return resolve_use_scan(self.scan_layers, self.num_layers)

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def dtype(self):
        return jnp.bfloat16 if self.bf16 else jnp.float32

    def layer_config(self) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_heads,
            attn_dropout_ratio=self.attn_dropout,
            hidden_dropout_ratio=self.hidden_dropout,
            num_hidden_layers=self.num_layers,
            initializer_range=self.initializer_range,
            layer_norm_eps=self.layer_norm_eps,
            bf16=self.bf16,
            pre_layer_norm=True,
            causal=True,
            sparsity_config=self.sparse_attention,
            attn_layout=self.attn_layout,
            attn_dropout_impl=self.attn_dropout_impl,
        )

    def num_params(self, include_embeddings: bool = True) -> int:
        layer = DeepSpeedTransformerLayer(self.layer_config())
        n = self.num_layers * layer.num_params() + 2 * self.hidden_size
        if include_embeddings:
            n += (self.vocab_size + self.n_positions) * self.hidden_size
        return n

    def flops_per_token(self) -> int:
        """Training FLOPs/token (fwd+bwd ≈ 6N + attention + LM head), the
        Megatron-style accounting used for MFU: the vocab projection is a
        real [*, H]x[H, V] matmul on the MXU and belongs in the count
        (the embedding LOOKUP does not)."""
        n = self.num_params(include_embeddings=False)
        attn = 12 * self.num_layers * self.hidden_size * self.n_positions
        head = 6 * self.hidden_size * self.vocab_size
        return 6 * n + attn + head


class GPT2Model:
    """Decoder-only LM over stacked DeepSpeedTransformerLayers."""

    @property
    def sparse_grad_paths(self):
        """engine "sparse_gradients" consumers: row-sparse embedding grads
        are reduced as (indices, values) instead of a dense allreduce
        (reference: engine.py:1729-1792 sparse_allreduce — which applies to
        sparse nn.Embedding grads).  Only valid UNTIED: a tied LM head adds
        a dense d loss/d wte contribution over every vocab row."""
        if self.config.tie_word_embeddings:
            return ()
        return ("wte",)

    def __init__(self, config: GPT2Config):
        self.config = config
        self.layer = DeepSpeedTransformerLayer(config.layer_config())
        self._zero3_stream = None

    def install_zero3_streaming(self, stream_ctx) -> None:
        """Engine hook: route the layer-stack scan through the explicit
        ZeRO-3 gather/prefetch executor (runtime/zero/stage3_streaming.py —
        the stage3_max_live_parameters / stage3_prefetch_bucket_size
        consumer; reference stage3.py:294 PartitionedParameterCoordinator)."""
        self._zero3_stream = stream_ctx

    # -- parameters ---------------------------------------------------- #
    def init_params(self, rng):
        cfg = self.config
        k_wte, k_wpe, k_layers = jax.random.split(rng, 3)
        init = jax.nn.initializers.normal(cfg.initializer_range)
        layer_keys = jax.random.split(k_layers, cfg.num_layers)
        stacked = jax.vmap(self.layer.init_params)(layer_keys)
        params = {
            "wte": init(k_wte, (cfg.vocab_size, cfg.hidden_size), jnp.float32),
            "wpe": init(k_wpe, (cfg.n_positions, cfg.hidden_size),
                        jnp.float32),
            "h": stacked,
            "ln_f": {"w": jnp.ones((cfg.hidden_size,), jnp.float32),
                     "b": jnp.zeros((cfg.hidden_size,), jnp.float32)},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = init(
                jax.random.fold_in(k_wte, 1),
                (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return params

    def param_partition_specs(self):
        """TP specs: vocab-sharded embeddings + Megatron column/row layer
        splits over the "model" axis."""
        layer_specs = DeepSpeedTransformerLayer.param_partition_specs()
        stacked_specs = {k: P(None, *list(s)) for k, s in layer_specs.items()}
        specs = {
            "wte": P(MODEL_AXIS, None),
            "wpe": P(),
            "h": stacked_specs,
            "ln_f": {"w": P(), "b": P()},
        }
        if not self.config.tie_word_embeddings:
            specs["lm_head"] = P(None, MODEL_AXIS)
        return specs

    # -- forward ------------------------------------------------------- #
    def embed(self, params, input_ids, position_offset=0):
        """Token + position embedding; position_offset supports KV-cache
        decode (inference engine feeds one token at position `pos`)."""
        cfg = self.config
        wte = params["wte"].astype(cfg.dtype)
        wpe = params["wpe"].astype(cfg.dtype)
        pos = position_offset + jnp.arange(input_ids.shape[1])
        return wte[input_ids] + wpe[pos]

    def _head_matrix(self, params, dtype):
        """[H, V] LM projection — tied wte.T or the independent lm_head.
        (The layer-streaming path re-derives the tie from its own group
        split — layerwise_api head_loss_fn.)"""
        if self.config.tie_word_embeddings:
            return params["wte"].astype(dtype).T
        return params["lm_head"].astype(dtype)

    def _final_hidden(self, params, h):
        """Final layer norm shared by head_logits and the fused-loss path."""
        return fused_layer_norm(h, params["ln_f"]["w"], params["ln_f"]["b"],
                                self.config.layer_norm_eps)

    @staticmethod
    def _shift_for_next_token(h, input_ids, labels):
        """Next-token convention: when labels is None, input_ids[:, 1:] are
        the targets and the last hidden column is dropped (keeps the
        attention length unchanged, e.g. divisible by a sparse-attention
        block)."""
        if labels is None:
            return h[:, :-1], input_ids[:, 1:]
        return h, labels

    def head_logits(self, params, h):
        """Final LN + (tied) LM head, fp32 logits."""
        with jax.named_scope("head"):
            h = self._final_hidden(params, h)
            return (h @ self._head_matrix(params, h.dtype)).astype(
                jnp.float32)

    def hidden_states(self, params, input_ids, rng=None,
                      deterministic: bool = False, pld_theta=None):
        """input_ids [B, S] -> pre-head hidden states [B, S, H] (the final
        LN lives in head_logits so the KV-cache decode path shares it).

        pld_theta: progressive-layer-drop keep probability theta(t)
        (reference: runtime/progressive_layer_drop.py injected via
        engine.py:1236).  Layer i keeps its residual branch with
        p_i = 1 - (i/L)(1 - theta) — deeper layers drop more (PLD paper's
        depth schedule) — gated per step inside the scan."""
        cfg = self.config
        if rng is None:
            deterministic = True
            rng = jax.random.PRNGKey(0)
        r_embd, r_layers, r_pld = jax.random.split(rng, 3)

        with jax.named_scope("embed"):
            h = self.embed(params, input_ids)
            h = dropout(h, cfg.embd_dropout, r_embd, deterministic)

        layer_fn = self.layer
        use_pld = pld_theta is not None and not deterministic
        n = cfg.num_layers
        if use_pld:
            keep_probs = 1.0 - (jnp.arange(n, dtype=jnp.float32) / n) * \
                (1.0 - jnp.float32(pld_theta))
            pld_keys = jax.random.split(r_pld, n)

        stream = self._zero3_stream
        # usable() also covers the post-engine life of the model object
        # (stale mesh, batch-1 decode); it is the same predicate scan gates
        # on internally, so the fold below only runs inside the manual
        # region.
        streaming = stream is not None and stream.usable(
            h, params=params["h"])

        def body(carry, xs):
            if use_pld:
                layer_params, layer_rng, keep_p, pld_key = xs
            else:
                layer_params, layer_rng = xs
            if streaming and not deterministic:
                # Inside the manual ZeRO region every shard sees the same
                # layer rng; fold in the shard index so dropout masks stay
                # independent across the batch shards.
                layer_rng = stream.fold_shard_index(layer_rng)
            with jax.named_scope("layer"):
                out = layer_fn(layer_params, carry, rng=layer_rng,
                               deterministic=deterministic)
            if use_pld:
                keep = jax.random.bernoulli(pld_key, keep_p)
                out = jnp.where(keep, out, carry)
            return out, None

        if cfg.activation_checkpointing:
            body = jax.checkpoint(body)

        layer_rngs = jax.random.split(r_layers, n)
        extras = ((layer_rngs, keep_probs, pld_keys) if use_pld
                  else (layer_rngs,))
        if streaming:
            h = stream.scan(body, h, params["h"], extras,
                            param_tp_specs=self.param_partition_specs()["h"])
        else:
            from .layer_stack import run_layer_stack
            h = run_layer_stack(body, h, (params["h"],) + extras,
                                cfg.use_scan)
        return h

    # -- layer-streaming protocol (ZeRO-Infinity param offload) --------- #
    def layerwise_api(self):
        """Split the model into streaming groups for the layer-streaming
        engine (runtime/zero/infinity.py): embed / one group per layer /
        head.  The reference's analog is the per-submodule fetch units of
        stage3.py:397 fetch_sub_module.

        Tied embeddings: the head group reads `wte` from the EMBED group, so
        wte gradients accumulate from both the embedding lookup and the LM
        head matmul (the reference ties them through the shared Parameter).
        """
        cfg = self.config
        layer = self.layer
        n = cfg.num_layers

        def split(params):
            groups = {"embed": {"wte": params["wte"], "wpe": params["wpe"]}}
            for i in range(n):
                groups[f"layer{i}"] = jax.tree.map(lambda a: a[i],
                                                   params["h"])
            head = {"ln_f": params["ln_f"]}
            if not cfg.tie_word_embeddings:
                head["lm_head"] = params["lm_head"]
            groups["head"] = head
            return groups

        def join(groups):
            params = {
                "wte": groups["embed"]["wte"],
                "wpe": groups["embed"]["wpe"],
                "h": jax.tree.map(
                    lambda *ls: np.stack(ls) if isinstance(
                        ls[0], np.ndarray) else jnp.stack(ls),
                    *[groups[f"layer{i}"] for i in range(n)]),
                "ln_f": groups["head"]["ln_f"],
            }
            if not cfg.tie_word_embeddings:
                params["lm_head"] = groups["head"]["lm_head"]
            return params

        def join_consuming(groups):
            """join, but each numpy layer-group leaf is FREED right after
            its row is copied into the stacked array — the transient is
            one stacked leaf instead of a full second copy of all layer
            tensors.  The streaming engine's optimizer boundary calls
            this on the accumulated grad tier, where the naive join's
            extra full-model copy OOMed a 125 GB host at 4.2B (r4)."""
            layer_groups = [groups[f"layer{i}"] for i in range(n)]
            treedef = jax.tree.structure(layer_groups[0])
            flats = [treedef.flatten_up_to(g) for g in layer_groups]
            out_leaves = []
            for li in range(treedef.num_leaves):
                rows = [flats[i][li] for i in range(n)]
                if isinstance(rows[0], np.ndarray):
                    out = np.empty((n,) + rows[0].shape, rows[0].dtype)
                    for i in range(n):
                        out[i] = rows[i]
                        flats[i][li] = None
                        rows[i] = None
                else:
                    out = jnp.stack(rows)
                out_leaves.append(out)
            for i in range(n):
                groups[f"layer{i}"] = None
            params = {
                "wte": groups["embed"]["wte"],
                "wpe": groups["embed"]["wpe"],
                "h": jax.tree_util.tree_unflatten(treedef, out_leaves),
                "ln_f": groups["head"]["ln_f"],
            }
            if not cfg.tie_word_embeddings:
                params["lm_head"] = groups["head"]["lm_head"]
            return params

        def embed_fn(embed_g, input_ids, rng):
            wte = embed_g["wte"].astype(cfg.dtype)
            wpe = embed_g["wpe"].astype(cfg.dtype)
            h = wte[input_ids] + wpe[jnp.arange(input_ids.shape[1])]
            deterministic = rng is None
            r = rng if rng is not None else jax.random.PRNGKey(0)
            return dropout(h, cfg.embd_dropout, r, deterministic)

        def layer_fn(layer_g, h, rng, layer_idx):
            r = (jax.random.fold_in(rng, layer_idx)
                 if rng is not None else None)
            return layer(layer_g, h, rng=r,
                         deterministic=rng is None)

        def head_loss_fn(head_g, embed_g, h, input_ids, labels):
            hs = fused_layer_norm(h, head_g["ln_f"]["w"],
                                  head_g["ln_f"]["b"], cfg.layer_norm_eps)
            if cfg.tie_word_embeddings:
                head = embed_g["wte"].astype(hs.dtype).T
            else:
                head = head_g["lm_head"].astype(hs.dtype)
            hs, labels = GPT2Model._shift_for_next_token(
                hs, input_ids, labels)
            if cfg.fused_loss:
                from ..ops.fused_cross_entropy import (
                    fused_linear_cross_entropy)
                return fused_linear_cross_entropy(
                    hs.reshape(-1, cfg.hidden_size), head,
                    labels.reshape(-1).astype(jnp.int32),
                    cfg.fused_loss_chunk)
            logits = (hs @ head).astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        return {"split": split, "join": join,
                "join_consuming": join_consuming, "embed_fn": embed_fn,
                "layer_fn": layer_fn, "head_loss_fn": head_loss_fn,
                "num_layers": n}

    def logits(self, params, input_ids, rng=None, deterministic=False,
               pld_theta=None):
        h = self.hidden_states(params, input_ids, rng, deterministic,
                               pld_theta)
        return self.head_logits(params, h)

    def loss(self, params, rng, input_ids, labels=None, pld_theta=None):
        """Next-token cross entropy (fp32 softmax).  When labels is None,
        input_ids[:, 1:] serve as targets; the model runs on the FULL
        sequence and the last logit column is dropped (keeps the attention
        length unchanged, e.g. divisible by a sparse-attention block).

        With cfg.fused_loss (default) the head projection and the CE fuse
        into a vocab-chunked streaming pass that never materializes the
        [B, S, V] fp32 logits — the LM-head HBM fix."""
        cfg = self.config
        if cfg.fused_loss:
            from ..ops.fused_cross_entropy import fused_linear_cross_entropy
            h = self.hidden_states(params, input_ids, rng,
                                   deterministic=rng is None,
                                   pld_theta=pld_theta)
            with jax.named_scope("head"):
                h = self._final_hidden(params, h)
                h, labels2 = self._shift_for_next_token(h, input_ids,
                                                        labels)
                return fused_linear_cross_entropy(
                    h.reshape(-1, cfg.hidden_size),
                    self._head_matrix(params, h.dtype),
                    labels2.reshape(-1).astype(jnp.int32),
                    cfg.fused_loss_chunk)
        logits = self.logits(params, input_ids, rng,
                             deterministic=rng is None,
                             pld_theta=pld_theta).astype(jnp.float32)
        with jax.named_scope("head"):
            logits, labels = self._shift_for_next_token(logits, input_ids,
                                                        labels)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

    # engine entry point: model(params, rng, batch...) -> loss
    def __call__(self, params, rng, input_ids, labels=None, pld_theta=None):
        return self.loss(params, rng, input_ids, labels, pld_theta)
