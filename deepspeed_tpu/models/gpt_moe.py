"""GPT-MoE — decoder LM with gated expert FFNs on alternating layers.

Reference pattern: Megatron-MoE / GShard place a `MoE` layer in the FFN
position of every other transformer layer (deepspeed/moe/layer.py:18 MoE
wraps gate+experts; the 0.5.2-era examples interleave dense and expert
layers).  Here the composition is explicit: dense layers are full
DeepSpeedTransformerLayers; MoE layers are an attention-only layer
(ffn="none") followed by [pre-LN -> top-k gated experts -> dropout ->
residual], with the GShard load-balancing loss summed across MoE layers
and added to the LM loss.

Layers are stored per-layer (a tuple under "h") and executed unrolled —
dense and MoE layers have different param trees, so the homogeneous-stack
scan machinery (layer_stack.py) does not apply.  Expert parallelism rides
the mesh's "expert" axis; everything else composes exactly as GPT2Model
(ZeRO 0-2, TP on the attention/dense layers, dp).
"""

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..moe import MoE
from ..ops.activations import dropout
from ..ops.normalize import fused_layer_norm
from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MODEL_AXIS


@dataclass
class GPTMoEConfig:
    vocab_size: int = 50257
    n_positions: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    # --- MoE ---
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    moe_every: int = 2            # layer i is MoE when i % moe_every == 1
    moe_aux_loss_coef: float = 0.01
    # --- shared with GPT2Config ---
    embd_dropout: float = 0.1
    attn_dropout: float = 0.1
    hidden_dropout: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    bf16: bool = True
    attn_layout: str = "bhsd"
    tie_word_embeddings: bool = True
    # chunked fused linear+CE (the LM-head HBM fix — same knobs as
    # GPT2Config): never materializes the [B, S, V] fp32 logits
    fused_loss: bool = True
    fused_loss_chunk: int = 8192

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def dtype(self):
        return jnp.bfloat16 if self.bf16 else jnp.float32

    def is_moe_layer(self, i: int) -> bool:
        """Layer i carries the expert FFN when i % moe_every is the LAST
        slot of its group — moe_every=2 gives layers 1,3,5,... (the GShard
        interleave); moe_every=1 makes EVERY layer MoE."""
        return (self.moe_every > 0 and
                i % self.moe_every == self.moe_every - 1)

    def layer_config(self, ffn: str) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_heads,
            attn_dropout_ratio=self.attn_dropout,
            hidden_dropout_ratio=self.hidden_dropout,
            num_hidden_layers=self.num_layers,
            initializer_range=self.initializer_range,
            layer_norm_eps=self.layer_norm_eps,
            bf16=self.bf16, pre_layer_norm=True, causal=True,
            attn_layout=self.attn_layout, ffn=ffn)

    def flops_per_token(self) -> int:
        """ACTIVE training FLOPs/token (fwd+bwd = 6N_active + attention +
        LM head) — the MoE analog of GPT2Config.flops_per_token: only the
        top_k routed experts' FFN parameters count per token (each routed
        token does 6 x its expert-FFN params of work; the gate matmul is
        included, the dispatch scatter/gather is not — it moves bytes,
        not MACs).  This makes the MoE bench rows' TFLOPS/MFU comparable
        with the dense ladder on the same accounting (VERDICT r4 weak #4:
        'MoE rows have no comparator')."""
        h, inter = self.hidden_size, self.intermediate_size
        dense_layer = DeepSpeedTransformerLayer(self.layer_config("dense"))
        attn_only = DeepSpeedTransformerLayer(self.layer_config("none"))
        expert_ffn_active = self.top_k * (2 * h * inter + h + inter)
        gate = h * self.num_experts
        n_active = 0
        for i in range(self.num_layers):
            if self.is_moe_layer(i):
                n_active += (attn_only.num_params() + 2 * h +
                             expert_ffn_active + gate)
            else:
                n_active += dense_layer.num_params()
        n_active += 2 * h  # ln_f
        attn = 12 * self.num_layers * h * self.n_positions
        head = 6 * h * self.vocab_size
        return 6 * n_active + attn + head

    def num_params(self) -> int:
        dense = DeepSpeedTransformerLayer(self.layer_config("dense"))
        attn_only = DeepSpeedTransformerLayer(self.layer_config("none"))
        h, inter = self.hidden_size, self.intermediate_size
        expert_ffn = self.num_experts * (2 * h * inter + h + inter)
        gate = h * self.num_experts
        n = 0
        for i in range(self.num_layers):
            if self.is_moe_layer(i):
                n += attn_only.num_params() + 2 * h + expert_ffn + gate
            else:
                n += dense.num_params()
        n += 2 * self.hidden_size  # ln_f
        n += (self.vocab_size + self.n_positions) * self.hidden_size
        if not self.tie_word_embeddings:
            n += self.hidden_size * self.vocab_size
        return n


class GPTMoEModel:
    """Decoder LM with expert FFNs on alternating layers."""

    def __init__(self, config: GPTMoEConfig):
        self.config = config
        self.dense_layer = DeepSpeedTransformerLayer(
            config.layer_config("dense"))
        self.attn_layer = DeepSpeedTransformerLayer(
            config.layer_config("none"))
        self.moe = MoE(hidden_size=config.hidden_size,
                       num_experts=config.num_experts, k=config.top_k,
                       capacity_factor=config.capacity_factor,
                       min_capacity=config.min_capacity)

    # -- parameters ---------------------------------------------------- #
    def init_params(self, rng):
        cfg = self.config
        k_wte, k_wpe, k_layers = jax.random.split(rng, 3)
        init = jax.nn.initializers.normal(cfg.initializer_range)
        layer_keys = jax.random.split(k_layers, cfg.num_layers)
        layers = []
        probe = jnp.zeros((1, cfg.hidden_size), jnp.float32)
        for i in range(cfg.num_layers):
            if cfg.is_moe_layer(i):
                ka, km = jax.random.split(layer_keys[i])
                layers.append({
                    "attn": self.attn_layer.init_params(ka),
                    "moe_nw": jnp.ones((cfg.hidden_size,), jnp.float32),
                    "moe_nb": jnp.zeros((cfg.hidden_size,), jnp.float32),
                    "moe": self.moe.init_params(km, probe),
                })
            else:
                layers.append(self.dense_layer.init_params(layer_keys[i]))
        params = {
            "wte": init(k_wte, (cfg.vocab_size, cfg.hidden_size),
                        jnp.float32),
            "wpe": init(k_wpe, (cfg.n_positions, cfg.hidden_size),
                        jnp.float32),
            "h": tuple(layers),
            "ln_f": {"w": jnp.ones((cfg.hidden_size,), jnp.float32),
                     "b": jnp.zeros((cfg.hidden_size,), jnp.float32)},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = init(
                jax.random.fold_in(k_wte, 1),
                (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return params

    def param_partition_specs(self):
        cfg = self.config
        dense_specs = DeepSpeedTransformerLayer.param_partition_specs(
            "dense")
        attn_specs = DeepSpeedTransformerLayer.param_partition_specs("none")
        layers = []
        for i in range(cfg.num_layers):
            if cfg.is_moe_layer(i):
                layers.append({
                    "attn": attn_specs,
                    "moe_nw": P(), "moe_nb": P(),
                    "moe": self.moe.param_partition_specs(),
                })
            else:
                layers.append(dense_specs)
        specs = {
            "wte": P(MODEL_AXIS, None),
            "wpe": P(),
            "h": tuple(layers),
            "ln_f": {"w": P(), "b": P()},
        }
        if not cfg.tie_word_embeddings:
            specs["lm_head"] = P(None, MODEL_AXIS)
        return specs

    # -- forward ------------------------------------------------------- #
    def hidden_states(self, params, input_ids, rng=None,
                      deterministic: bool = False
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (h [B, S, H], l_aux_sum) — the summed GShard
        load-balancing loss of every MoE layer (reference: sharded_moe
        l_aux, consumed at moe_aux_loss_coef in loss())."""
        cfg = self.config
        if rng is None:
            deterministic = True
            rng = jax.random.PRNGKey(0)
        r_embd, r_layers = jax.random.split(rng)

        wte = params["wte"].astype(cfg.dtype)
        wpe = params["wpe"].astype(cfg.dtype)
        h = wte[input_ids] + wpe[jnp.arange(input_ids.shape[1])]
        h = dropout(h, cfg.embd_dropout, r_embd, deterministic)

        b, s, hid = h.shape
        l_aux_sum = jnp.float32(0.0)
        layer_rngs = jax.random.split(r_layers, cfg.num_layers)
        for i, lp in enumerate(params["h"]):
            r = None if deterministic else layer_rngs[i]
            if cfg.is_moe_layer(i):
                h = self.attn_layer(lp["attn"], h, rng=r,
                                    deterministic=deterministic)
                moe_in = fused_layer_norm(h, lp["moe_nw"], lp["moe_nb"],
                                          cfg.layer_norm_eps)
                flat = moe_in.reshape(b * s, hid)
                # distinct key: r's children feed the attention dropouts,
                # so the gate's rsample noise gets its own fold
                r_moe = (jax.random.fold_in(r, 13)
                         if r is not None else None)
                out, l_aux, _ = self.moe.apply(
                    lp["moe"], flat, rng=r_moe, train=not deterministic)
                out = out.reshape(b, s, hid).astype(h.dtype)
                out = dropout(out, cfg.hidden_dropout,
                              (jax.random.fold_in(r, 7)
                               if r is not None else jax.random.PRNGKey(0)),
                              deterministic or r is None)
                h = h + out
                l_aux_sum = l_aux_sum + l_aux.astype(jnp.float32)
            else:
                h = self.dense_layer(lp, h, rng=r,
                                     deterministic=deterministic)
        return h, l_aux_sum

    # -- head (shared by logits and loss) ------------------------------ #
    def _final_hidden_and_head(self, params, h):
        h = fused_layer_norm(h, params["ln_f"]["w"], params["ln_f"]["b"],
                             self.config.layer_norm_eps)
        if self.config.tie_word_embeddings:
            head = params["wte"].astype(h.dtype).T
        else:
            head = params["lm_head"].astype(h.dtype)
        return h, head

    def logits(self, params, input_ids, rng=None, deterministic=False):
        h, _ = self.hidden_states(params, input_ids, rng, deterministic)
        h, head = self._final_hidden_and_head(params, h)
        return (h @ head).astype(jnp.float32)

    def loss(self, params, rng, input_ids, labels=None):
        """Next-token CE + moe_aux_loss_coef * summed l_aux (the GShard
        auxiliary loss placement, reference sharded_moe.py top2gating).
        With cfg.fused_loss the head projection and CE fuse into the
        vocab-chunked streaming pass (no [B, S, V] fp32 logits — the same
        LM-head HBM fix as GPT2Model.loss)."""
        cfg = self.config
        h, l_aux = self.hidden_states(params, input_ids, rng,
                                      deterministic=rng is None)
        h, head = self._final_hidden_and_head(params, h)
        if labels is None:
            h, labels = h[:, :-1], input_ids[:, 1:]
        if cfg.fused_loss:
            from ..ops.fused_cross_entropy import fused_linear_cross_entropy
            ce = fused_linear_cross_entropy(
                h.reshape(-1, cfg.hidden_size), head,
                labels.reshape(-1).astype(jnp.int32), cfg.fused_loss_chunk)
        else:
            logits = (h @ head).astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        return ce + cfg.moe_aux_loss_coef * l_aux

    def __call__(self, params, rng, input_ids, labels=None):
        """Engine entry: loss(params, rng, batch...) like GPT2Model."""
        return self.loss(params, rng, input_ids, labels)
