"""deepspeed_tpu.models — model families built on the fused ops layer.

The reference ships models through DeepSpeedExamples (Megatron-GPT2,
bing_bert) and fuses them via module injection; here the flagship
transformer-LM families are first-class so the framework is usable
standalone.
"""

from .gpt2 import GPT2Config, GPT2Model
from .bert import BertConfig, BertModel
from .gpt_moe import GPTMoEConfig, GPTMoEModel

__all__ = ["GPT2Config", "GPT2Model", "BertConfig", "BertModel",
           "GPTMoEConfig", "GPTMoEModel"]
