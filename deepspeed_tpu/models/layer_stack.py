"""Shared layer-stack executor for the model families.

Models keep their transformer layers STACKED (leading layer axis) and run
one compiled body over them.  Two execution modes:

- scan: `lax.scan` — one traced body regardless of depth, fastest compile;
- unrolled: Python loop over the same body — XLA sees the whole depth and
  fuses across layer boundaries (measured ~18 ms/step faster than scan on
  the GPT-2 flagship bench, benchmarks/profile_ablations.py), at the cost
  of compile time linear in depth.

The auto policy (`scan_layers=None` in the model configs) unrolls up to
SCAN_LAYERS_AUTO_THRESHOLD layers and scans beyond.
"""

import jax

SCAN_LAYERS_AUTO_THRESHOLD = 24


def resolve_use_scan(scan_layers, num_layers: int) -> bool:
    """Shared auto policy for the model configs' `scan_layers=None`."""
    if scan_layers is not None:
        return scan_layers
    return num_layers > SCAN_LAYERS_AUTO_THRESHOLD


def run_layer_stack(body, carry, xs, use_scan: bool):
    """Run `body(carry, xs_i) -> (carry, _)` over the leading axis of xs."""
    if use_scan:
        carry, _ = jax.lax.scan(body, carry, xs)
        return carry
    n = jax.tree.leaves(xs)[0].shape[0]
    for i in range(n):
        carry, _ = body(carry, jax.tree.map(lambda a: a[i], xs))
    return carry
