"""GPT-2 as a PipelineModule — the 3D-parallel flagship assembly.

Reference: the Megatron-GPT2 + PipelineModule composition the reference's
model-level tests exercise (tests/model/run_func_test.py:606 mp×zero matrix;
pipe/module.py:87).  Body blocks are DeepSpeedTransformerLayers, so the
pipeline engine picks up their Megatron column/row TP specs automatically
(pipe/engine.py _make_partition_specs) and 3D = pipe × data/ZeRO × model
falls out of the mesh.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from ..ops.activations import dropout
from ..ops.normalize import fused_layer_norm
from ..parallel.mesh import MODEL_AXIS
from ..runtime.pipe.module import (LayerSpec, PipeLayer, PipelineModule,
                                   TiedLayerSpec)
from .gpt2 import GPT2Config


class GPT2EmbedPipe(PipeLayer):
    """wte + wpe lookup (reference: the embedding stage of a Megatron
    pipeline)."""

    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg

    def init_params(self, rng, x):
        cfg = self.cfg
        k1, k2 = jax.random.split(rng)
        init = jax.nn.initializers.normal(cfg.initializer_range)
        return {"wte": init(k1, (cfg.vocab_size, cfg.hidden_size),
                            jnp.float32),
                "wpe": init(k2, (cfg.n_positions, cfg.hidden_size),
                            jnp.float32)}

    def apply(self, params, input_ids, rng=None):
        cfg = self.cfg
        wte = params["wte"].astype(cfg.dtype)
        wpe = params["wpe"].astype(cfg.dtype)
        h = wte[input_ids] + wpe[jnp.arange(input_ids.shape[1])]
        return dropout(h, cfg.embd_dropout, rng, deterministic=rng is None)


class GPT2BlockPipe(PipeLayer):
    """One transformer layer; carries the Megatron TP specs so the
    pipeline engine shards qkv/mlp over the "model" axis."""

    def __init__(self, cfg: GPT2Config):
        from ..ops.transformer import DeepSpeedTransformerLayer
        self.cfg = cfg
        self.layer = DeepSpeedTransformerLayer(cfg.layer_config())

    def init_params(self, rng, x):
        return self.layer.init_params(rng)

    def apply(self, params, x, rng=None):
        return self.layer(params, x, rng=rng, deterministic=rng is None)

    def param_partition_specs(self):
        return type(self.layer).param_partition_specs(self.layer.config.ffn)

    # -- explicit-collective TP (the gated 1F1B executor's manual mode;
    #    ops/transformer.py tp_axis= / tp_manual_* docstrings) ---------- #
    def supports_manual_tp(self, tp_size: int) -> bool:
        """Config-level gate for the manual mode: sparse attention builds
        its layouts for the GLOBAL head count (SparseSelfAttention rejects
        a local head shard), and shard_map needs the heads dim to divide
        evenly over the model axis (GSPMD's column split tolerated uneven
        shards via padding; the manual split does not)."""
        return (self.layer.config.sparsity_config is None
                and self.cfg.num_heads % tp_size == 0)

    def apply_manual_tp(self, params, x, rng=None, tp_axis=None):
        return self.layer(params, x, rng=rng, deterministic=rng is None,
                          tp_axis=tp_axis or MODEL_AXIS)

    # -- combined manual modes (gated executor: TP and/or SP axes) ------ #
    def supports_manual_sp(self, sp_size: int) -> bool:
        """Sequence-parallel manual mode: dense attention only (sparse
        layouts are built for the full sequence)."""
        return self.layer.config.sparsity_config is None

    def apply_manual(self, params, x, rng=None, tp_axis=None, seq_axis=None,
                     sp_mode="auto"):
        """General manual-mode apply: params are local TP shards when
        tp_axis is set (tp_manual_views layout); x is the local sequence
        chunk when seq_axis is set (ring/Ulysses attention inside)."""
        return self.layer(params, x, rng=rng, deterministic=rng is None,
                          tp_axis=tp_axis, seq_axis=seq_axis,
                          sp_mode=sp_mode)

    def tp_manual_views(self, params):
        return type(self.layer).tp_manual_views(params, self.cfg.num_heads)

    def tp_manual_unview(self, params):
        return type(self.layer).tp_manual_unview(params)

    def tp_manual_view_specs(self):
        # ffn derived from the layer's own config (ADVICE r4: a future
        # non-dense body reusing this path must not get a dense spec tree)
        return type(self.layer).tp_manual_view_specs(self.layer.config.ffn)


class GPT2HeadPipe(PipeLayer):
    """Final LN + (untied) LM head producing fp32 logits."""

    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg

    def init_params(self, rng, x):
        cfg = self.cfg
        init = jax.nn.initializers.normal(cfg.initializer_range)
        return {"ln_f": {"w": jnp.ones((cfg.hidden_size,), jnp.float32),
                         "b": jnp.zeros((cfg.hidden_size,), jnp.float32)},
                "lm_head": init(rng, (cfg.hidden_size, cfg.vocab_size),
                                jnp.float32)}

    def apply(self, params, h, rng=None):
        cfg = self.cfg
        h = fused_layer_norm(h, params["ln_f"]["w"], params["ln_f"]["b"],
                             cfg.layer_norm_eps)
        head = params["lm_head"].astype(h.dtype)
        return (h @ head).astype(jnp.float32)


class GPT2FinalLNPipe(PipeLayer):
    """Final LayerNorm alone (tied-head pipelines: the projection lives in
    the tied embed spec)."""

    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg

    def init_params(self, rng, x):
        cfg = self.cfg
        return {"w": jnp.ones((cfg.hidden_size,), jnp.float32),
                "b": jnp.zeros((cfg.hidden_size,), jnp.float32)}

    def apply(self, params, h, rng=None):
        return fused_layer_norm(h, params["w"], params["b"],
                                self.cfg.layer_norm_eps)


def gpt2_next_token_loss(logits, input_ids):
    """Shift-by-one LM loss over the microbatch's own ids as labels."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], input_ids[:, 1:].astype(jnp.int32)).mean()


def gpt2_pipeline_module(cfg: GPT2Config,
                         num_stages: Optional[int] = None,
                         activation_checkpoint_interval: int = 0
                         ) -> PipelineModule:
    """GPT-2 as [embed] + num_layers × [block] + [ln_f, head] pipeline
    stages.  cfg.tie_word_embeddings routes the LM projection through a
    TiedLayerSpec sharing the embed stage's wte (reference:
    pipe/module.py:73 tied input/output embeddings); untied uses an
    independent lm_head.

    The loss consumes (logits, labels) where the dataloader feeds
    (input_ids, input_ids) — next-token shift happens in the loss.
    """
    blocks = [LayerSpec(GPT2BlockPipe, cfg) for _ in range(cfg.num_layers)]
    if cfg.tie_word_embeddings:
        def tied_head(params, h):
            head = params["wte"].astype(h.dtype).T
            return (h @ head).astype(jnp.float32)

        layers = ([TiedLayerSpec("embed", GPT2EmbedPipe, cfg)] + blocks +
                  [LayerSpec(GPT2FinalLNPipe, cfg),
                   TiedLayerSpec("embed", GPT2EmbedPipe, cfg,
                                 forward_fn=tied_head)])
    else:
        layers = ([LayerSpec(GPT2EmbedPipe, cfg)] + blocks +
                  [LayerSpec(GPT2HeadPipe, cfg)])
    module = PipelineModule(
        layers, num_stages=num_stages, loss_fn=gpt2_next_token_loss,
        activation_checkpoint_interval=activation_checkpoint_interval)
    _attach_vocab_parallel_aux(module, cfg)
    _attach_seq_parallel_aux(module, cfg)
    return module


def _attach_vocab_parallel_aux(module, cfg: GPT2Config):
    """Manual-TP pre/post chains for the gated 1F1B executor (pipe×model
    meshes): vocab-parallel embedding lookup and fused vocab-parallel
    linear+CE — the Megatron VocabParallelEmbedding/parallel-CE role,
    which the replicated aux chains otherwise duplicate on every model
    peer (the head matmul is ~2 layers' worth of FLOPs at GPT-2 scale).
    Consumed by PipelineEngine when the executor gates with a model
    axis; the GSPMD (non-gated) engines shard the embedding
    declaratively instead (models/gpt2.py param_partition_specs).

    Numerics note: the vocab-parallel CE accumulates logits in fp32
    (preferred_element_type) where the replicated head rounds them
    through bf16 first — equal under fp32 configs (the trajectory
    tests), one rounding better under bf16."""
    from jax.sharding import PartitionSpec as P

    from ..ops.vocab_parallel import (vocab_parallel_embedding,
                                      vocab_parallel_linear_cross_entropy)

    tied_case = cfg.tie_word_embeddings

    def supports(tp_size: int) -> bool:
        return cfg.vocab_size % tp_size == 0

    def pre_apply(pre, tied, ids, rng, tp_axis):
        p = tied["embed"] if tied_case else pre[0]
        h = vocab_parallel_embedding(p["wte"].astype(cfg.dtype), ids,
                                     tp_axis)
        h = h + p["wpe"].astype(cfg.dtype)[jnp.arange(ids.shape[1])]
        return dropout(h, cfg.embd_dropout, rng, deterministic=rng is None)

    def post_loss(post, tied, h, y_mb, rng, tp_axis):
        lnp = post[0]
        if tied_case:
            w, b = lnp["w"], lnp["b"]
            head_local = tied["embed"]["wte"].T      # [H, V_local]
        else:
            w, b = lnp["ln_f"]["w"], lnp["ln_f"]["b"]
            head_local = lnp["lm_head"]
        h = fused_layer_norm(h, w, b, cfg.layer_norm_eps)
        hid = h.shape[-1]
        h2 = h[:, :-1].reshape(-1, hid)
        labels = y_mb[:, 1:].astype(jnp.int32).reshape(-1)
        return vocab_parallel_linear_cross_entropy(
            h2, head_local.astype(h.dtype), labels, tp_axis)

    def aux_specs(pre, post, tied):
        rep = lambda t: jax.tree.map(lambda _: P(), t)  # noqa: E731
        pre_s, post_s, tied_s = rep(pre), rep(post), rep(tied)
        if tied_case:
            tied_s["embed"]["wte"] = P(MODEL_AXIS, None)
        else:
            pre_s[0]["wte"] = P(MODEL_AXIS, None)
            post_s[0]["lm_head"] = P(None, MODEL_AXIS)
        return pre_s, post_s, tied_s

    module.tp_manual_aux_supports = supports
    module.tp_manual_pre_apply = pre_apply
    module.tp_manual_post_loss = post_loss
    module.tp_manual_aux_specs = aux_specs


def _attach_seq_parallel_aux(module, cfg: GPT2Config):
    """Sequence-DISTRIBUTED pre/post chains for the gated 1F1B executor on
    pipe×seq meshes (round 5).  Unlike the replicated aux chains, every
    seq peer embeds ONLY its sequence chunk (global positions from its
    axis index) and computes the loss over ONLY its chunk's positions —
    so every parameter gradient is a per-peer partial sum and the
    executor finalizes ALL grads (and the loss) with one psum over the
    seq axis (one_f_one_b.py seq_axis=).  The next-token label shift
    crosses chunk boundaries, so the post chain receives the FULL label
    ids (token ids are tiny next to activations) and slices the
    shifted window itself; the final global position carries zero loss
    weight, matching gpt2_next_token_loss's logits[:, :-1] vs
    labels[:, 1:] on one device exactly."""
    from jax import lax

    tied_case = cfg.tie_word_embeddings

    def supports(sp_size: int) -> bool:
        return cfg.n_positions % sp_size == 0

    def pre_apply(pre, tied, ids_full, rng, seq_axis):
        p = tied["embed"] if tied_case else pre[0]
        sp = lax.psum(1, seq_axis)  # static under shard_map
        idx = lax.axis_index(seq_axis)
        s = ids_full.shape[1]
        s_local = s // sp
        ids_loc = lax.dynamic_slice_in_dim(ids_full, idx * s_local,
                                           s_local, 1)
        pos = idx * s_local + jnp.arange(s_local)
        h = (p["wte"].astype(cfg.dtype)[ids_loc] +
             p["wpe"].astype(cfg.dtype)[pos])
        r = None if rng is None else jax.random.fold_in(rng, idx)
        return dropout(h, cfg.embd_dropout, r, deterministic=rng is None)

    def post_loss(post, tied, h, y_full, rng, seq_axis):
        import optax

        lnp = post[0]
        if tied_case:
            w, b = lnp["w"], lnp["b"]
            head = tied["embed"]["wte"].T           # [H, V]
        else:
            w, b = lnp["ln_f"]["w"], lnp["ln_f"]["b"]
            head = lnp["lm_head"]
        h = fused_layer_norm(h, w, b, cfg.layer_norm_eps)
        logits = (h @ head.astype(h.dtype)).astype(jnp.float32)
        idx = lax.axis_index(seq_axis)
        bsz, s_local = h.shape[0], h.shape[1]
        s = y_full.shape[1]
        # global pre-shift then local slice: shifted[t] = y[t+1]; the
        # garbage at the last global position gets zero weight below
        shifted = jnp.concatenate(
            [y_full[:, 1:], jnp.zeros_like(y_full[:, :1])], axis=1)
        labels = lax.dynamic_slice_in_dim(shifted, idx * s_local,
                                          s_local, 1).astype(jnp.int32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        pos = idx * s_local + jnp.arange(s_local)
        weight = (pos < s - 1).astype(jnp.float32)
        # per-peer PARTIAL of the global mean over [B, S-1]; the executor
        # psums partials over the seq axis
        return (ce * weight[None, :]).sum() / (bsz * (s - 1))

    module.sp_manual_supports = supports
    module.sp_manual_pre_apply = pre_apply
    module.sp_manual_post_loss = post_loss
