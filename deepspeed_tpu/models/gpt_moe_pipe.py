"""GPT-MoE as a PipelineModule — the PP × EP composition.

Reference: the reference runs MoE models under any of its engines — expert
grads are reduced per expert-data group uniformly
(deepspeed/runtime/engine.py:1714-1727) and nothing in its PipelineEngine
forbids an MoE layer inside a stage.  Here the SPMD pipeline body must be
HOMOGENEOUS (stacked params, runtime/pipe/module.py), so the dense/MoE
interleave (gpt_moe.py is_moe_layer: layer i is MoE when
i % moe_every == moe_every - 1) is expressed as a stackable "MoE group"
unit: (moe_every - 1) dense transformer layers followed by one
attention-only layer + gated expert FFN.  Every group has an identical
param signature, so `num_layers // moe_every` groups stack into the
pipeline body and partition over stages.

The GShard load-balance loss rides the executors' aux-loss channel
(PipeLayer.apply_with_aux -> one_f_one_b.py): each group's l_aux is
pre-scaled by moe_aux_loss_coef here, summed into the training loss for
active (stage, microbatch) forwards, and its gradient is injected with a
loss_scale vjp seed — exact under fp16 dynamic scaling because the aux
term is additive in the scaled total loss.

Expert parallelism: the MOELayer's [E, C, d] dispatch buffers carry
expert-axis sharding constraints (moe/sharded_moe.py _constrain_expert);
under the masked 1F1B executor GSPMD lowers the token->slot resharding to
all-to-alls WITHIN each pipe row (the batch is sharded over (data,
expert); the blocks' expert dim over the expert axis) — the composition
the reference gets from its expert process groups (moe/sharded_moe.py
_AllToAll over the expert group).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from ..moe import MoE
from ..ops.normalize import fused_layer_norm
from ..ops.activations import dropout
from ..ops.transformer import DeepSpeedTransformerLayer
from ..runtime.pipe.module import (LayerSpec, PipeLayer, PipelineModule,
                                   TiedLayerSpec)
from .gpt_moe import GPTMoEConfig
from .gpt2_pipe import (GPT2EmbedPipe, GPT2FinalLNPipe, GPT2HeadPipe,
                        gpt2_next_token_loss)


class GPTMoEGroupPipe(PipeLayer):
    """One stackable MoE group: (moe_every - 1) dense transformer layers,
    then [attention-only layer -> pre-LN -> top-k gated experts ->
    dropout -> residual] (the GShard interleave as a homogeneous unit)."""

    def __init__(self, cfg: GPTMoEConfig):
        self.cfg = cfg
        self.dense_layer = DeepSpeedTransformerLayer(
            cfg.layer_config("dense"))
        self.attn_layer = DeepSpeedTransformerLayer(cfg.layer_config("none"))
        self.moe = MoE(hidden_size=cfg.hidden_size,
                       num_experts=cfg.num_experts, k=cfg.top_k,
                       capacity_factor=cfg.capacity_factor,
                       min_capacity=cfg.min_capacity)

    def init_params(self, rng, x):
        cfg = self.cfg
        keys = jax.random.split(rng, cfg.moe_every + 1)
        probe = jnp.zeros((1, cfg.hidden_size), jnp.float32)
        return {
            "dense": tuple(self.dense_layer.init_params(keys[j])
                           for j in range(cfg.moe_every - 1)),
            "attn": self.attn_layer.init_params(keys[-2]),
            "moe_nw": jnp.ones((cfg.hidden_size,), jnp.float32),
            "moe_nb": jnp.zeros((cfg.hidden_size,), jnp.float32),
            "moe": self.moe.init_params(keys[-1], probe),
        }

    def param_partition_specs(self):
        from jax.sharding import PartitionSpec as P
        cfg = self.cfg
        return {
            "dense": tuple(
                DeepSpeedTransformerLayer.param_partition_specs("dense")
                for _ in range(cfg.moe_every - 1)),
            "attn": DeepSpeedTransformerLayer.param_partition_specs("none"),
            "moe_nw": P(), "moe_nb": P(),
            "moe": self.moe.param_partition_specs(),
        }

    def apply_with_aux(self, params, x, rng=None):
        """x: [B, S, H] -> (y, aux) with aux = moe_aux_loss_coef * l_aux
        (pre-scaled: the executors sum aux terms directly into the loss).
        One body shared with the manual modes (apply_manual with no axes
        is the replicated computation)."""
        return self.apply_manual(params, x, rng=rng)

    def apply(self, params, x, rng=None):
        y, _ = self.apply_with_aux(params, x, rng=rng)
        return y

    # -- manual tensor parallelism (gated 1F1B executor, round 5) ------- #
    # The expert FFNs Megatron-split over the model axis with explicit
    # psums (ExpertMLP.apply_tp); the gate stays replicated so every
    # model peer routes identically; dense/attention layers run the
    # layer's tp_axis mode.  Reference slot: the expert FFN position of
    # moe/sharded_moe.py:312 under Megatron mp.
    def supports_manual_tp(self, tp_size: int) -> bool:
        cfg = self.cfg
        d_ff = self.moe.deepspeed_moe.expert.d_ff
        return (self.dense_layer.config.sparsity_config is None
                and cfg.num_heads % tp_size == 0
                and cfg.intermediate_size % tp_size == 0
                and d_ff % tp_size == 0)

    def apply_manual(self, params, x, rng=None, tp_axis=None, seq_axis=None,
                     sp_mode="auto"):
        """Manual-mode apply; returns (y, aux) — the executors detect the
        aux channel via apply_with_aux and unpack accordingly."""
        if seq_axis is not None:
            raise NotImplementedError(
                "MoE pipeline body does not compose with manual sequence "
                "parallelism yet (token routing would need chunk-global "
                "capacity)")
        cfg = self.cfg
        deterministic = rng is None
        b, s, hid = x.shape
        for j, dp in enumerate(params["dense"]):
            r = None if deterministic else jax.random.fold_in(rng, j)
            x = self.dense_layer(dp, x, rng=r, deterministic=deterministic,
                                 tp_axis=tp_axis)
        r_attn = (None if deterministic
                  else jax.random.fold_in(rng, cfg.moe_every + 1))
        x = self.attn_layer(params["attn"], x, rng=r_attn,
                            deterministic=deterministic, tp_axis=tp_axis)
        moe_in = fused_layer_norm(x, params["moe_nw"], params["moe_nb"],
                                  cfg.layer_norm_eps)
        # NOTE: the "f" operator (identity fwd / psum bwd) sits INSIDE the
        # MoE layer on the expert-dispatch input only — placing it here
        # would also route the gate's REPLICATED cotangent through the
        # psum and overcount it by tp (measured: LN/upstream grads off by
        # the gate path's weight).  See MOELayer._apply_scatter tp_axis.
        # gate noise / dropout keys SHARED across model peers: routing and
        # the post-psum values are replicated over the model axis
        r_moe = (None if deterministic
                 else jax.random.fold_in(rng, cfg.moe_every + 2))
        out, l_aux, _ = self.moe.apply(params["moe"],
                                       moe_in.reshape(b * s, hid),
                                       rng=r_moe, train=not deterministic,
                                       tp_axis=tp_axis)
        out = out.reshape(b, s, hid).astype(x.dtype)
        r_drop = (jax.random.fold_in(rng, cfg.moe_every + 3)
                  if not deterministic else None)
        out = dropout(out, cfg.hidden_dropout, r_drop,
                      deterministic=deterministic)
        aux = cfg.moe_aux_loss_coef * l_aux.astype(jnp.float32)
        return x + out, aux

    def apply_manual_tp(self, params, x, rng=None, tp_axis=None):
        from ..parallel.mesh import MODEL_AXIS
        return self.apply_manual(params, x, rng=rng,
                                 tp_axis=tp_axis or MODEL_AXIS)

    def tp_manual_views(self, params):
        heads = self.cfg.num_heads
        p = dict(params)
        p["dense"] = tuple(
            DeepSpeedTransformerLayer.tp_manual_views(dp, heads)
            for dp in params["dense"])
        p["attn"] = DeepSpeedTransformerLayer.tp_manual_views(
            params["attn"], heads)
        return p

    def tp_manual_unview(self, params):
        p = dict(params)
        p["dense"] = tuple(DeepSpeedTransformerLayer.tp_manual_unview(dp)
                           for dp in params["dense"])
        p["attn"] = DeepSpeedTransformerLayer.tp_manual_unview(
            params["attn"])
        return p

    def tp_manual_view_specs(self):
        from jax.sharding import PartitionSpec as P

        from ..moe.experts import ExpertMLP
        from ..parallel.mesh import MODEL_AXIS
        cfg = self.cfg
        expert_specs = jax.tree.map(
            lambda sp: P(None, *sp),  # leading expert-stack dim
            ExpertMLP.tp_partition_specs(MODEL_AXIS),
            is_leaf=lambda v: isinstance(v, P))
        return {
            "dense": tuple(
                DeepSpeedTransformerLayer.tp_manual_view_specs("dense")
                for _ in range(cfg.moe_every - 1)),
            "attn": DeepSpeedTransformerLayer.tp_manual_view_specs("none"),
            "moe_nw": P(), "moe_nb": P(),
            "moe": {"gate": {"wg": P()}, "experts": expert_specs},
        }


def gpt_moe_pipeline_module(cfg: GPTMoEConfig,
                            num_stages: Optional[int] = None,
                            activation_checkpoint_interval: int = 0
                            ) -> PipelineModule:
    """GPT-MoE as [embed] + (num_layers / moe_every) x [MoE group] +
    [ln_f, head] pipeline stages.  The embed/head stages are GPT-2's
    (gpt2_pipe.py); tied embeddings route through a TiedLayerSpec."""
    if cfg.moe_every < 1 or cfg.num_layers % cfg.moe_every != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} must be a positive multiple of "
            f"moe_every={cfg.moe_every}: the pipeline body stacks "
            "homogeneous [dense^(moe_every-1), moe] groups")
    n_groups = cfg.num_layers // cfg.moe_every
    blocks = [LayerSpec(GPTMoEGroupPipe, cfg) for _ in range(n_groups)]
    if cfg.tie_word_embeddings:
        def tied_head(params, h):
            head = params["wte"].astype(h.dtype).T
            return (h @ head).astype(jnp.float32)

        layers = ([TiedLayerSpec("embed", GPT2EmbedPipe, cfg)] + blocks +
                  [LayerSpec(GPT2FinalLNPipe, cfg),
                   TiedLayerSpec("embed", GPT2EmbedPipe, cfg,
                                 forward_fn=tied_head)])
    else:
        layers = ([LayerSpec(GPT2EmbedPipe, cfg)] + blocks +
                  [LayerSpec(GPT2HeadPipe, cfg)])
    return PipelineModule(
        layers, num_stages=num_stages, loss_fn=gpt2_next_token_loss,
        activation_checkpoint_interval=activation_checkpoint_interval)
